"""Networked queue broker: a real cross-process message-ingestion path.

Reference: the C++ stack consumes real Kafka via librdkafka
(common/kafka/kafka_consumer.h:27-118 — Seek by timestamp/offset,
Consume, Commit) from brokers in other processes. This module is the
TPU-framework equivalent: a standalone ``BrokerServer`` process hosting
durable topic/partition logs behind the framework's own RPC plane, plus
``NetworkConsumer`` / ``NetworkProducer`` clients. The embedded
``MockKafkaCluster`` stays the in-process test backend behind the same
``Consumer`` interface.

Durability: each (topic, partition) appends to
``<data_dir>/<topic>.<partition>.log`` (u32 len-prefixed records:
u64 timestamp_ms, u32 klen, key, value) reloaded on start, so ingestion
resume-from-timestamp works across broker restarts (the reference's
brokers are durable too; admin resume relies on it). Committed group
offsets persist to ``offsets.json``.

Run a broker:  python -m rocksplicator_tpu.kafka.network \
                   --port 9092 --data_dir /var/broker
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import struct
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..rpc import IoLoop, RpcClientPool, RpcServer
from ..rpc.errors import RpcApplicationError
from .broker import Consumer, Message, MockKafkaCluster

_REC = struct.Struct("<QI")  # timestamp_ms, key_len (value = rest)


class _DurableLog:
    """Append-only record log for one (topic, partition)."""

    def __init__(self, path: str):
        self._path = path
        self._f = None

    def load(self, sink) -> None:
        if not os.path.isfile(self._path):
            return
        with open(self._path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + 4 <= len(data):
            (rec_len,) = struct.unpack_from("<I", data, pos)
            if pos + 4 + rec_len > len(data):
                break  # torn tail from a crash mid-append — drop it
            rec = data[pos + 4: pos + 4 + rec_len]
            ts, klen = _REC.unpack_from(rec, 0)
            key = rec[_REC.size: _REC.size + klen]
            value = rec[_REC.size + klen:]
            sink(ts, key, value)
            pos += 4 + rec_len
        if pos < len(data):  # truncate the torn tail
            with open(self._path, "r+b") as f:
                f.truncate(pos)

    def append(self, ts_ms: int, key: bytes, value: bytes) -> None:
        if self._f is None:
            self._f = open(self._path, "ab")
        rec = _REC.pack(ts_ms, len(key)) + key + value
        self._f.write(struct.pack("<I", len(rec)) + rec)
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class BrokerHandler:
    """RPC handler hosting the broker state (methods are ``broker_*`` so
    it can stack with other handlers on one RpcServer)."""

    def __init__(self, data_dir: Optional[str] = None,
                 fetch_threads: int = 64):
        self._cluster = MockKafkaCluster()
        self._data_dir = data_dir
        self._logs: Dict[Tuple[str, int], _DurableLog] = {}
        self._log_lock = threading.Lock()
        # group -> topic -> {partition: offset}
        self._offsets: Dict[str, Dict[str, Dict[str, int]]] = {}
        # long-poll fetches park a thread each; a dedicated executor keeps
        # them from starving the process-wide asyncio default executor
        from concurrent.futures import ThreadPoolExecutor

        self._fetch_executor = ThreadPoolExecutor(
            max_workers=fetch_threads, thread_name_prefix="broker-fetch")
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()

    # -- persistence -------------------------------------------------------

    def _log_for(self, topic: str, partition: int) -> Optional[_DurableLog]:
        if not self._data_dir:
            return None
        with self._log_lock:
            log = self._logs.get((topic, partition))
            if log is None:
                log = self._logs[(topic, partition)] = _DurableLog(
                    os.path.join(self._data_dir,
                                 f"{topic}.{partition}.log"))
            return log

    def _load(self) -> None:
        assert self._data_dir is not None
        # topics meta
        meta_path = os.path.join(self._data_dir, "topics.json")
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                topics = json.load(f)
            for topic, n in topics.items():
                self._cluster.create_topic(topic, n)
                for p in range(n):
                    log = self._log_for(topic, p)
                    if log:
                        log.load(
                            lambda ts, k, v, t=topic, pp=p:
                            self._cluster.produce(t, pp, k, v, ts)
                        )
        off_path = os.path.join(self._data_dir, "offsets.json")
        if os.path.isfile(off_path):
            with open(off_path) as f:
                self._offsets = json.load(f)

    def _persist_topics(self) -> None:
        if not self._data_dir:
            return
        topics = {
            t: self._cluster.num_partitions(t)
            for t in self._cluster.topics()
        }
        tmp = os.path.join(self._data_dir, "topics.json.tmp")
        with open(tmp, "w") as f:
            json.dump(topics, f)
        os.replace(tmp, os.path.join(self._data_dir, "topics.json"))

    def _persist_offsets(self) -> None:
        if not self._data_dir:
            return
        tmp = os.path.join(self._data_dir, "offsets.json.tmp")
        with open(tmp, "w") as f:
            json.dump(self._offsets, f)
        os.replace(tmp, os.path.join(self._data_dir, "offsets.json"))

    # -- RPC methods -------------------------------------------------------

    async def handle_broker_create_topic(
        self, topic: str = "", num_partitions: int = 1
    ) -> dict:
        self._cluster.create_topic(topic, num_partitions)
        self._persist_topics()
        return {"ok": True}

    async def handle_broker_num_partitions(self, topic: str = "") -> dict:
        return {"num_partitions": self._cluster.num_partitions(topic)}

    async def handle_broker_produce(
        self, topic: str = "", partition: int = 0, key: bytes = b"",
        value: bytes = b"", timestamp_ms: Optional[int] = None,
    ) -> dict:
        key, value = bytes(key), bytes(value)
        ts = (int(timestamp_ms) if timestamp_ms is not None
              else int(time.time() * 1000))
        try:
            offset = self._cluster.produce(topic, partition, key, value, ts)
        except (KeyError, IndexError) as e:
            raise RpcApplicationError("NO_SUCH_TOPIC", str(e))
        log = self._log_for(topic, partition)
        if log:
            with self._log_lock:
                log.append(ts, key, value)
        return {"offset": offset}

    async def handle_broker_fetch(
        self, topic: str = "", partition: int = 0, offset: int = 0,
        max_wait_ms: int = 1000, max_messages: int = 50,
    ) -> dict:
        """Batched long-poll fetch (the replicate-RPC pattern applied to
        the queue: park until data or timeout, then return ≤N messages)."""
        loop = asyncio.get_running_loop()
        first = await loop.run_in_executor(
            self._fetch_executor, self._cluster.fetch, topic, partition,
            offset, max_wait_ms / 1000.0,
        )
        msgs: List[dict] = []
        if first is not None:
            msgs.append(self._msg_dict(first))
            next_off = first.offset + 1
            while len(msgs) < max_messages:
                m = self._cluster.fetch(topic, partition, next_off, 0.0)
                if m is None:
                    break
                msgs.append(self._msg_dict(m))
                next_off = m.offset + 1
        return {"messages": msgs}

    @staticmethod
    def _msg_dict(m: Message) -> dict:
        return {
            "partition": m.partition, "offset": m.offset,
            "timestamp_ms": m.timestamp_ms, "key": m.key, "value": m.value,
        }

    async def handle_broker_high_watermark(
        self, topic: str = "", partition: int = 0
    ) -> dict:
        return {"offset": self._cluster.high_watermark(topic, partition)}

    async def handle_broker_offset_for_timestamp(
        self, topic: str = "", partition: int = 0, timestamp_ms: int = 0
    ) -> dict:
        return {
            "offset": self._cluster.offset_for_timestamp(
                topic, partition, timestamp_ms)
        }

    async def handle_broker_commit(
        self, group: str = "", topic: str = "",
        offsets: Optional[Dict[str, int]] = None,
    ) -> dict:
        # merge per partition: different consumers in one group may each
        # commit only the partitions they own
        self._offsets.setdefault(group, {}).setdefault(topic, {}).update(
            offsets or {})
        self._persist_offsets()
        return {"ok": True}

    async def handle_broker_committed(
        self, group: str = "", topic: str = ""
    ) -> dict:
        return {"offsets": self._offsets.get(group, {}).get(topic, {})}

    def close(self) -> None:
        self._fetch_executor.shutdown(wait=False)
        with self._log_lock:
            for log in self._logs.values():
                log.close()


class BrokerServer:
    """Standalone broker: RpcServer + BrokerHandler."""

    def __init__(self, port: int = 0, data_dir: Optional[str] = None,
                 ioloop: Optional[IoLoop] = None):
        self.handler = BrokerHandler(data_dir)
        self._server = RpcServer(port=port, ioloop=ioloop)
        self._server.add_handler(self.handler)

    def start(self) -> "BrokerServer":
        self._server.start()
        return self

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self) -> None:
        self._server.stop()
        self.handler.close()


class _BrokerRpc:
    """Shared sync RPC plumbing for the network client classes."""

    def __init__(self, host: str, port: int,
                 pool: Optional[RpcClientPool] = None,
                 ioloop: Optional[IoLoop] = None):
        self._host = host
        self._port = port
        self._ioloop = ioloop or IoLoop.default()
        self._own_pool = pool is None
        self._pool = pool or RpcClientPool()

    def call(self, method: str, timeout: float = 10.0, **args):
        async def go():
            return await self._pool.call(
                self._host, self._port, method, args, timeout=timeout)

        return self._ioloop.run_sync(go(), timeout=timeout + 5)

    def close(self) -> None:
        """Closes the pool (and its sockets) if this client owns it."""
        if self._own_pool:
            try:
                self._ioloop.run_sync(self._pool.close(), timeout=5)
            except Exception:
                pass


class NetworkProducer(_BrokerRpc):
    def create_topic(self, topic: str, num_partitions: int = 1) -> None:
        self.call("broker_create_topic", topic=topic,
                  num_partitions=num_partitions)

    def produce(self, topic: str, partition: int, key: bytes, value: bytes,
                timestamp_ms: Optional[int] = None) -> int:
        return self.call(
            "broker_produce", topic=topic, partition=partition, key=key,
            value=value, timestamp_ms=timestamp_ms,
        )["offset"]


class NetworkConsumer(Consumer, _BrokerRpc):
    """Consumer over a remote BrokerServer (librdkafka-equivalent role).

    Batched long-poll fetches fill a local buffer; ``consume`` drains it
    message by message, preserving the reference Consumer semantics."""

    def __init__(self, host: str, port: int, group_id: str = "",
                 pool: Optional[RpcClientPool] = None,
                 ioloop: Optional[IoLoop] = None,
                 fetch_batch: int = 50):
        _BrokerRpc.__init__(self, host, port, pool=pool, ioloop=ioloop)
        self.group_id = group_id
        self._topic: Optional[str] = None
        self._positions: Dict[int, int] = {}
        self._buffer: List[Message] = []
        self._rr: List[int] = []
        self._fetch_batch = fetch_batch

    def assign(self, topic: str, partitions: Sequence[int]) -> None:
        self._topic = topic
        self._positions = {p: 0 for p in partitions}
        self._rr = list(partitions)
        self._buffer.clear()

    def seek(self, partition: int, offset: int) -> None:
        self._positions[partition] = offset
        self._buffer = [m for m in self._buffer
                        if m.partition != partition]

    def seek_to_timestamp(self, ts_ms: int) -> None:
        assert self._topic is not None
        for p in list(self._positions):
            self._positions[p] = self.call(
                "broker_offset_for_timestamp", topic=self._topic,
                partition=p, timestamp_ms=ts_ms,
            )["offset"]
        self._buffer.clear()

    def _fetch_into_buffer(self, partition: int, wait_ms: int) -> bool:
        assert self._topic is not None
        out = self.call(
            "broker_fetch", timeout=wait_ms / 1000.0 + 10.0,
            topic=self._topic, partition=partition,
            offset=self._positions[partition],
            max_wait_ms=wait_ms, max_messages=self._fetch_batch,
        )
        got = False
        for m in out["messages"]:
            self._buffer.append(Message(
                topic=self._topic, partition=int(m["partition"]),
                offset=int(m["offset"]),
                timestamp_ms=int(m["timestamp_ms"]),
                key=bytes(m["key"]), value=bytes(m["value"]),
            ))
            got = True
        return got

    def consume(self, timeout_sec: float) -> Optional[Message]:
        assert self._topic is not None
        if self._buffer:
            msg = self._buffer.pop(0)
            self._positions[msg.partition] = msg.offset + 1
            return msg
        deadline = time.monotonic() + timeout_sec
        while True:
            # non-blocking round-robin sweep first
            for _ in range(len(self._rr)):
                p = self._rr.pop(0)
                self._rr.append(p)
                if self._fetch_into_buffer(p, 0):
                    msg = self._buffer.pop(0)
                    self._positions[msg.partition] = msg.offset + 1
                    return msg
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            p = self._rr[0]
            if self._fetch_into_buffer(
                    p, int(min(remaining, 0.5) * 1000)):
                msg = self._buffer.pop(0)
                self._positions[msg.partition] = msg.offset + 1
                return msg

    def commit(self) -> None:
        assert self._topic is not None
        self.call(
            "broker_commit", group=self.group_id, topic=self._topic,
            offsets={str(p): o for p, o in self._positions.items()},
        )

    @property
    def committed(self) -> Dict[int, int]:
        assert self._topic is not None
        out = self.call(
            "broker_committed", group=self.group_id, topic=self._topic)
        return {int(p): int(o) for p, o in out["offsets"].items()}

    def position(self, partition: int) -> int:
        return self._positions[partition]

    def high_watermark(self, partition: int) -> int:
        assert self._topic is not None
        return self.call(
            "broker_high_watermark", topic=self._topic,
            partition=partition,
        )["offset"]

    def close(self) -> None:
        # MRO would resolve to the no-op Consumer.close(); the TCP pool
        # must actually be released on watcher teardown
        _BrokerRpc.close(self)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="standalone queue broker")
    p.add_argument("--port", type=int, default=9092)
    p.add_argument("--data_dir", default=None,
                   help="durable log directory (omit for in-memory)")
    args = p.parse_args(argv)
    srv = BrokerServer(port=args.port, data_dir=args.data_dir).start()
    print(f"broker up: port={srv.port} data_dir={args.data_dir}",
          flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
