"""Message-queue ingestion stack (reference: common/kafka/ — SURVEY §2.3).

Implemented by the queue stack stage; ``ingestion.start_ingestion`` is the
seam the admin plane's start/stopMessageIngestion RPCs call.
"""
