"""Kafka binary wire protocol — real-broker interop for the queue stack.

Reference: common/kafka/kafka_consumer.h:27-118 wraps librdkafka speaking
the Apache Kafka protocol to actual clusters. This module implements that
protocol natively (no librdkafka in the image):

- :class:`KafkaWireConsumer` — a :class:`~.broker.Consumer` backend that
  bootstraps, fetches and commits against ANY Kafka-protocol broker.
- :class:`KafkaWireBroker` — serves the same protocol from the embedded
  :class:`~.broker.MockKafkaCluster`, so the consumer is exercised over
  real TCP frames in CI (and standard Kafka clients can read from the
  embedded queue).

Implemented APIs (fixed, non-flexible versions — pre-KIP-482 encodings):

  ========== ===== =============================================
  ApiVersions  v0  handshake / capability discovery
  Metadata     v1  topic -> partitions + leaders
  ListOffsets  v1  timestamp seek (-1 latest, -2 earliest)
  Fetch        v4  record batches v2 (magic=2, CRC-32C)
  OffsetCommit v2  consumer-group offset store
  OffsetFetch  v1  committed-offset recovery
  ========== ===== =============================================

Record batches are the v2 format: zigzag-varint records inside a
CRC-32C-protected batch frame. Compression: incoming gzip batches
(attributes codec 1 — what a default Java/librdkafka producer with
``compression.type=gzip`` ships) are decoded via stdlib zlib with bounded
decompression; snappy batches (codec 2) decode through a pure-python
block-format decoder that also understands snappy-java's xerial stream
framing; lz4/zstd are still rejected loudly (codec bytes must never be
handed up as record bytes). Produced batches are uncompressed by default
(``codec="gzip"``/``codec="snappy"`` opt-in; the snappy encoder emits
literal-only blocks — valid snappy, no match search).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from .broker import Consumer, Message, MockKafkaCluster

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_API_VERSIONS = 18

ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3


class KafkaWireError(Exception):
    """Broker-reported error the consumer cannot make progress past.
    ``error_code`` is the Kafka protocol code; for OFFSET_OUT_OF_RANGE,
    ``log_start``/``high_watermark`` (when known) let callers reseek."""

    def __init__(self, msg: str, error_code: int,
                 partition: int = -1, high_watermark: int = -1):
        super().__init__(msg)
        self.error_code = error_code
        self.partition = partition
        self.high_watermark = high_watermark

_SUPPORTED = {
    API_PRODUCE: (3, 3),
    API_FETCH: (4, 4),
    API_LIST_OFFSETS: (1, 1),
    API_METADATA: (1, 1),
    API_OFFSET_COMMIT: (2, 2),
    API_OFFSET_FETCH: (1, 1),
    API_API_VERSIONS: (0, 0),
}


# ---------------------------------------------------------------------------
# CRC-32C (Castagnoli) — record batch v2 checksum. Software table; batches
# are small and this path is interop, not the hot loop.
# ---------------------------------------------------------------------------

def _make_crc32c_table() -> List[int]:
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def crc32c(data: bytes) -> int:
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC32C_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# primitive encoding
# ---------------------------------------------------------------------------

def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def encode_varint(n: int) -> bytes:
    """Unsigned LEB128 of the zigzag encoding (Kafka varint)."""
    u = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = u = 0
    while True:
        if pos >= len(buf):
            raise ValueError("kafka varint: truncated")
        b = buf[pos]
        pos += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(u), pos
        shift += 7
        if shift > 70:
            raise ValueError("kafka varint: too long")


class _W:
    """Request/response body writer (big-endian, Kafka conventions)."""

    def __init__(self) -> None:
        self.b = bytearray()

    def i8(self, v):
        self.b += struct.pack(">b", v)
        return self

    def i16(self, v):
        self.b += struct.pack(">h", v)
        return self

    def i32(self, v):
        self.b += struct.pack(">i", v)
        return self

    def i64(self, v):
        self.b += struct.pack(">q", v)
        return self

    def string(self, s: Optional[str]):
        if s is None:
            return self.i16(-1)
        raw = s.encode("utf-8")
        self.i16(len(raw))
        self.b += raw
        return self

    def bytes_(self, v: Optional[bytes]):
        if v is None:
            return self.i32(-1)
        self.i32(len(v))
        self.b += v
        return self

    def raw(self, v: bytes):
        self.b += v
        return self


class _R:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("kafka frame: truncated")
        v = self.buf[self.pos:self.pos + n]
        self.pos += n
        return v

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        return bytes(self._take(n))


# ---------------------------------------------------------------------------
# record batch v2
# ---------------------------------------------------------------------------

_BATCH_HEAD = struct.Struct(">qiib")  # base_offset, batch_len, leader_epoch, magic

# decompressed-records cap, matching the fetch frame cap (_read_frame):
# a gzip bomb must not balloon past what an uncompressed record set
# could legally carry. The cap is CUMULATIVE across one record set's
# batches — per-batch bounding alone would let a 64MB frame packed with
# many maximally-compressed batches decode to frame_cap × batch_count
_MAX_DECOMPRESSED = 64 << 20


def _gunzip_bounded(data: bytes, cap: int) -> bytes:
    """gzip/zlib decode with an output bound (wbits=47 auto-detects both
    wrappers — Java producers write gzip format; tolerate zlib too)."""
    d = zlib.decompressobj(wbits=47)
    try:
        raw = d.decompress(data, cap + 1)
    except zlib.error as e:
        raise ValueError(f"kafka batch: bad gzip records: {e}") from None
    if len(raw) > cap or d.unconsumed_tail:
        raise ValueError("kafka batch: gzip records exceed size cap")
    return raw


# snappy-java's stream framing (what a Java producer's snappy codec
# actually ships): 8-byte magic, version, compat, then [len_be4, raw
# snappy block]*. librdkafka ships the raw block alone.
_XERIAL_MAGIC = b"\x82SNAPPY\x00"


def _snappy_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(data) or shift > 31:
            raise ValueError("kafka batch: bad snappy preamble")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7


def _snappy_block(data: bytes, cap: int) -> bytes:
    """Pure-python snappy *block format* decode (format_description.txt):
    little-endian-varint uncompressed length, then tagged elements —
    literals (tag 00, lengths 1-60 inline, 61-64 → 1-4 trailing length
    bytes) and back-references (tag 01: 4-11 bytes at an 11-bit offset;
    tag 10/11: 1-64 bytes at a 16/32-bit offset), overlap-legal (an
    offset shorter than the copy length repeats the tail, the RLE
    idiom). Bounded: the declared length must fit ``cap`` and every
    element is range-checked, so hostile bytes fail loudly instead of
    ballooning memory or reading out of bounds."""
    n, pos = _snappy_uvarint(data, 0)
    if n > cap:
        raise ValueError("kafka batch: snappy records exceed size cap")
    out = bytearray()
    ln = len(data)
    while pos < ln:
        tag = data[pos]
        pos += 1
        typ = tag & 3
        if typ == 0:  # literal
            length = tag >> 2
            if length >= 60:
                nb = length - 59
                if pos + nb > ln:
                    raise ValueError("kafka batch: bad snappy literal")
                length = int.from_bytes(data[pos:pos + nb], "little")
                pos += nb
            length += 1
            if pos + length > ln or len(out) + length > n:
                raise ValueError("kafka batch: bad snappy literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if typ == 1:  # copy, 1-byte offset
            if pos >= ln:
                raise ValueError("kafka batch: bad snappy copy")
            length = ((tag >> 2) & 0x07) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif typ == 2:  # copy, 2-byte offset
            if pos + 2 > ln:
                raise ValueError("kafka batch: bad snappy copy")
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            if pos + 4 > ln:
                raise ValueError("kafka batch: bad snappy copy")
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out) or len(out) + length > n:
            raise ValueError("kafka batch: bad snappy copy")
        if offset >= length:
            start = len(out) - offset
            out += out[start:start + length]
        else:  # overlapping copy: byte-at-a-time semantics
            for _ in range(length):
                out.append(out[-offset])
    if len(out) != n:
        raise ValueError("kafka batch: snappy length mismatch")
    return bytes(out)


def _snappy_bounded(data: bytes, cap: int) -> bytes:
    """Codec-2 records decode: raw snappy block (librdkafka) or the
    xerial stream framing (snappy-java), auto-detected by magic."""
    if data[:8] == _XERIAL_MAGIC:
        if len(data) < 16:
            raise ValueError("kafka batch: truncated snappy stream")
        out = bytearray()
        pos = 16  # magic + version + compat
        while pos < len(data):
            if pos + 4 > len(data):
                raise ValueError("kafka batch: truncated snappy stream")
            blen = int.from_bytes(data[pos:pos + 4], "big")
            pos += 4
            if pos + blen > len(data):
                raise ValueError("kafka batch: truncated snappy stream")
            out += _snappy_block(data[pos:pos + blen], cap - len(out))
            pos += blen
        return bytes(out)
    return _snappy_block(data, cap)


def _snappy_compress(data: bytes) -> bytes:
    """Literal-only snappy block encoding (valid per the format spec;
    no back-reference search — the encoder exists for round-trips and a
    second produce codec, the pure-python *decoder* is the parity
    item)."""
    out = bytearray()
    n = len(data)
    # preamble: uncompressed length, little-endian varint
    v = n
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    pos = 0
    while pos < n:
        length = min(n - pos, 1 << 16)
        if length <= 60:
            out.append((length - 1) << 2)
        else:
            nb = ((length - 1).bit_length() + 7) // 8
            out.append((59 + nb) << 2)
            out += (length - 1).to_bytes(nb, "little")
        out += data[pos:pos + length]
        pos += length
    return bytes(out)


def encode_record_batch(base_offset: int,
                        records: Sequence[Tuple[int, bytes, bytes]],
                        codec: Optional[str] = None) -> bytes:
    """records: [(timestamp_ms, key, value)] -> one v2 batch.
    ``codec="gzip"`` compresses the records section (attributes codec 1,
    the v2 layout: batch header through recordCount stays uncompressed,
    only the records array is wrapped); default is uncompressed."""
    if not records:
        return b""
    if codec not in (None, "gzip", "snappy"):
        raise ValueError(f"unsupported kafka codec: {codec}")
    first_ts = records[0][0]
    max_ts = max(r[0] for r in records)
    body = _W()
    body.i16({"gzip": 1, "snappy": 2}.get(codec, 0))  # attributes codec
    body.i32(len(records) - 1)       # lastOffsetDelta
    body.i64(first_ts)
    body.i64(max_ts)
    body.i64(-1).i16(-1).i32(-1)     # producerId/Epoch, baseSequence
    body.i32(len(records))
    # uncompressed (the hot default): records append straight into body;
    # gzip diverts them through an intermediate buffer for the wrapper
    recs = _W() if codec in ("gzip", "snappy") else body
    for delta, (ts, key, value) in enumerate(records):
        rec = _W()
        rec.i8(0)                    # record attributes
        rec.raw(encode_varint(ts - first_ts))
        rec.raw(encode_varint(delta))
        if key is None:
            rec.raw(encode_varint(-1))
        else:
            rec.raw(encode_varint(len(key)))
            rec.raw(bytes(key))
        rec.raw(encode_varint(len(value)))
        rec.raw(bytes(value))
        rec.raw(encode_varint(0))    # headers
        recs.raw(encode_varint(len(rec.b)))
        recs.raw(bytes(rec.b))
    if codec == "gzip":
        # wbits=31 → gzip wrapper (what Kafka's gzip codec is); mtime
        # defaults to 0 in zlib's stream header, keeping output stable
        c = zlib.compressobj(wbits=31)
        body.raw(c.compress(bytes(recs.b)) + c.flush())
    elif codec == "snappy":
        body.raw(_snappy_compress(bytes(recs.b)))
    crc = crc32c(bytes(body.b))
    # batch_length counts everything after the length field itself
    batch_len = 4 + 1 + 4 + len(body.b)  # leader_epoch + magic + crc + body
    out = _W()
    out.raw(_BATCH_HEAD.pack(base_offset, batch_len, 0, 2))
    out.b += struct.pack(">I", crc)
    out.raw(bytes(body.b))
    return bytes(out.b)


def decode_record_batches(buf: bytes) -> List[Tuple[int, int, Optional[bytes], bytes]]:
    """record_set bytes -> [(offset, timestamp_ms, key, value)]. Verifies
    magic and CRC-32C per batch; rejects compressed batches; control
    batches (transaction markers) are skipped."""
    return decode_record_set(buf)[0]


def decode_record_set(buf: bytes) -> Tuple[
        List[Tuple[int, int, Optional[bytes], bytes]], Optional[int]]:
    """Like :func:`decode_record_batches` but also returns the offset
    AFTER the last complete batch (base_offset + last_offset_delta + 1),
    or None when no complete batch was present. Consumers need it to
    advance past control-only batches — a position parked on a
    transaction marker would otherwise refetch it forever."""
    out: List[Tuple[int, int, Optional[bytes], bytes]] = []
    gunzip_budget = _MAX_DECOMPRESSED  # shared across the set's batches
    next_offset: Optional[int] = None
    pos = 0
    while pos + _BATCH_HEAD.size + 4 <= len(buf):
        base_offset, batch_len, _epoch, magic = _BATCH_HEAD.unpack_from(buf, pos)
        end = pos + 8 + 4 + batch_len
        if end > len(buf):
            break  # partial trailing batch (legal in fetch responses)
        if magic != 2:
            raise ValueError(f"kafka batch: unsupported magic {magic}")
        crc = struct.unpack_from(">I", buf, pos + _BATCH_HEAD.size)[0]
        body_start = pos + _BATCH_HEAD.size + 4
        body = buf[body_start:end]
        if crc32c(body) != crc:
            raise ValueError("kafka batch: CRC-32C mismatch")
        r = _R(body)
        attributes = r.i16()
        codec = attributes & 0x07
        if codec not in (0, 1, 2):
            # lz4(3)/zstd(4): no in-image codec — reject loudly rather
            # than hand codec bytes up as record bytes (gzip rides
            # stdlib zlib, snappy has the pure-python block decoder)
            raise ValueError(
                f"kafka batch: compression codec {codec} "
                f"not supported")
        if attributes & 0x20:
            # control batch (transaction COMMIT/ABORT markers): its
            # records are protocol metadata, never application data —
            # but its offset range still advances next_offset
            next_offset = base_offset + r.i32() + 1
            pos = end
            continue
        # lastOffsetDelta advances next_offset even when the batch's
        # records were all compacted away (count may be 0)
        next_offset = base_offset + r.i32() + 1
        first_ts = r.i64()
        r.i64()                      # maxTimestamp
        r.i64(); r.i16(); r.i32()    # producer id/epoch, base seq
        count = r.i32()
        # v2 layout: only the records array (after recordCount) is
        # compressed; the CRC above covered the on-wire (compressed)
        # bytes. Codec 1 = gzip — stdlib zlib, bounded so a hostile
        # batch cannot balloon memory past the frame cap.
        rbuf, rpos = body, r.pos
        if codec == 1:
            rbuf = _gunzip_bounded(body[r.pos:], gunzip_budget)
            gunzip_budget -= len(rbuf)
            rpos = 0
        elif codec == 2:
            rbuf = _snappy_bounded(body[r.pos:], gunzip_budget)
            gunzip_budget -= len(rbuf)
            rpos = 0
        for _ in range(count):
            rec_len, p = decode_varint(rbuf, rpos)
            rec_end = p + rec_len
            rr = _R(rbuf[:rec_end], p)
            rr.i8()                  # record attributes
            ts_delta, rr.pos = decode_varint(rbuf, rr.pos)
            off_delta, rr.pos = decode_varint(rbuf, rr.pos)
            klen, rr.pos = decode_varint(rbuf, rr.pos)
            key = bytes(rr._take(klen)) if klen >= 0 else None
            vlen, rr.pos = decode_varint(rbuf, rr.pos)
            value = bytes(rr._take(vlen)) if vlen >= 0 else b""
            out.append((base_offset + off_delta, first_ts + ts_delta,
                        key, value))
            rpos = rec_end
        pos = end
    return out, next_offset


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(n)
        if not c:
            raise ConnectionError("kafka peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _read_frame(sock: socket.socket) -> bytes:
    size = struct.unpack(">i", _read_exact(sock, 4))[0]
    if size < 0 or size > 64 << 20:
        raise ValueError(f"kafka frame size {size}")
    return _read_exact(sock, size)


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">i", len(payload)) + payload)


# ---------------------------------------------------------------------------
# broker (serves MockKafkaCluster over the wire)
# ---------------------------------------------------------------------------

class KafkaWireBroker:
    """Kafka-protocol front end for the embedded cluster."""

    def __init__(self, cluster: MockKafkaCluster, port: int = 0,
                 node_id: int = 0, host: str = "127.0.0.1",
                 auto_create_partitions: int = 16):
        self._cluster = cluster
        self.auto_create_partitions = auto_create_partitions
        self.node_id = node_id
        self.host = host
        self._committed: Dict[Tuple[str, str, int], int] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="kafka-wire-broker", daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass

    # -- server loop -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop:
                req = _read_frame(conn)
                r = _R(req)
                api_key = r.i16()
                api_version = r.i16()
                correlation_id = r.i32()
                r.string()  # client_id
                body = self._dispatch(api_key, api_version, r)
                resp = _W().i32(correlation_id).raw(bytes(body.b))
                _send_frame(conn, bytes(resp.b))
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, api_key: int, version: int, r: _R) -> _W:
        lo_hi = _SUPPORTED.get(api_key)
        if lo_hi is None or not lo_hi[0] <= version <= lo_hi[1]:
            if api_key == API_API_VERSIONS:
                # protocol convention: an unsupported ApiVersions version
                # still gets error 35 PLUS the supported-versions array
                # (in the v0 shape) so the client can fall back to v0 —
                # modern clients open with v3+ and need this to connect
                return self._api_versions(error=35)
            # UNSUPPORTED_VERSION (35) in the shape of the closest body
            return _W().i16(35)
        if api_key == API_API_VERSIONS:
            return self._api_versions()
        if api_key == API_PRODUCE:
            return self._produce(r)
        if api_key == API_METADATA:
            return self._metadata(r)
        if api_key == API_LIST_OFFSETS:
            return self._list_offsets(r)
        if api_key == API_FETCH:
            return self._fetch(r)
        if api_key == API_OFFSET_COMMIT:
            return self._offset_commit(r)
        return self._offset_fetch(r)

    def _api_versions(self, error: int = ERR_NONE) -> _W:
        w = _W().i16(error).i32(len(_SUPPORTED))
        for key, (lo, hi) in sorted(_SUPPORTED.items()):
            w.i16(key).i16(lo).i16(hi)
        return w

    def _produce(self, r: _R) -> _W:
        """Produce v3: record batches decoded and appended to the
        embedded cluster — any Kafka-protocol producer can publish into
        the embedded queue. Unknown topics auto-create
        (``auto_create_partitions``), mirroring auto.create.topics."""
        r.string()                    # transactional_id
        r.i16()                       # acks (the append is synchronous)
        r.i32()                       # timeout_ms
        n_topics = r.i32()
        w = _W().i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            w.string(topic).i32(n_parts)
            for _ in range(n_parts):
                p = r.i32()
                record_set = r.bytes_() or b""
                if (self._cluster.num_partitions(topic) == 0
                        and self.auto_create_partitions > 0):
                    self._cluster.create_topic(
                        topic, max(self.auto_create_partitions, p + 1))
                if not 0 <= p < self._cluster.num_partitions(topic):
                    w.i32(p).i16(ERR_UNKNOWN_TOPIC_OR_PARTITION)
                    w.i64(-1).i64(-1)
                    continue
                try:
                    records = decode_record_batches(record_set)
                except ValueError:
                    w.i32(p).i16(87)  # INVALID_RECORD
                    w.i64(-1).i64(-1)
                    continue
                base_offset = -1
                for off, ts, key, value in records:
                    got = self._cluster.produce(
                        topic, p, key or b"", value, timestamp_ms=ts)
                    if base_offset < 0:
                        base_offset = got
                w.i32(p).i16(ERR_NONE).i64(base_offset).i64(-1)
        w.i32(0)                      # throttle_time_ms (trails in v1+)
        return w

    def _metadata(self, r: _R) -> _W:
        n = r.i32()
        names = (None if n < 0
                 else [r.string() for _ in range(n)])
        if names is None:
            names = self._cluster.topics()
        w = _W()
        w.i32(1)                                 # brokers
        w.i32(self.node_id).string(self.host).i32(self.port).string(None)
        w.i32(self.node_id)                      # controller_id
        w.i32(len(names))
        for t in names:
            parts = self._cluster.num_partitions(t)
            w.i16(ERR_NONE if parts else ERR_UNKNOWN_TOPIC_OR_PARTITION)
            w.string(t)
            w.i8(0)                              # is_internal
            w.i32(parts)
            for p in range(parts):
                w.i16(ERR_NONE).i32(p).i32(self.node_id)
                w.i32(1).i32(self.node_id)       # replicas
                w.i32(1).i32(self.node_id)       # isr
        return w

    def _list_offsets(self, r: _R) -> _W:
        r.i32()  # replica_id
        n_topics = r.i32()
        w = _W().i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            w.string(topic).i32(n_parts)
            for _ in range(n_parts):
                p = r.i32()
                ts = r.i64()
                if not 0 <= p < self._cluster.num_partitions(topic):
                    w.i32(p).i16(ERR_UNKNOWN_TOPIC_OR_PARTITION)
                    w.i64(-1).i64(-1)
                    continue
                if ts == -1:
                    off = self._cluster.high_watermark(topic, p)
                elif ts == -2:
                    off = 0
                else:
                    off = self._cluster.offset_for_timestamp(topic, p, ts)
                w.i32(p).i16(ERR_NONE).i64(-1).i64(off)
        return w

    def _fetch(self, r: _R) -> _W:
        r.i32()                       # replica_id
        max_wait_ms = r.i32()
        r.i32()                       # min_bytes
        max_bytes = r.i32()
        r.i8()                        # isolation_level
        n_topics = r.i32()
        requests = []
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            for _ in range(n_parts):
                p = r.i32()
                fetch_offset = r.i64()
                part_max = r.i32()
                requests.append((topic, p, fetch_offset, part_max))
        # long-poll: wait for data on ANY VALID requested partition (an
        # unknown topic/partition must produce an error entry below, not
        # an IndexError that kills the connection thread)
        waitable = [
            (t, p, off) for t, p, off, _m in requests
            if 0 <= p < self._cluster.num_partitions(t)
        ]
        deadline = time.monotonic() + max_wait_ms / 1000.0
        while waitable and time.monotonic() < deadline:
            if any(self._cluster.high_watermark(t, p) > off
                   for t, p, off in waitable):
                break
            remaining = deadline - time.monotonic()
            self._cluster.fetch(waitable[0][0], waitable[0][1],
                                waitable[0][2],
                                max(0.0, min(remaining, 0.05)))
        w = _W().i32(0)               # throttle_time_ms
        by_topic: Dict[str, List] = {}
        for t, p, off, m in requests:
            by_topic.setdefault(t, []).append((p, off, m))
        w.i32(len(by_topic))
        budget = max_bytes
        for topic, parts in by_topic.items():
            w.string(topic).i32(len(parts))
            for p, off, part_max in parts:
                if not 0 <= p < self._cluster.num_partitions(topic):
                    w.i32(p).i16(ERR_UNKNOWN_TOPIC_OR_PARTITION)
                    w.i64(-1).i64(-1).i32(0).bytes_(b"")
                    continue
                hwm = self._cluster.high_watermark(topic, p)
                if off > hwm or off < 0:
                    w.i32(p).i16(ERR_OFFSET_OUT_OF_RANGE)
                    w.i64(hwm).i64(hwm).i32(0).bytes_(b"")
                    continue
                records: List[Tuple[int, bytes, bytes]] = []
                size = 0
                o = off
                while o < hwm and size < min(part_max, budget):
                    m = self._cluster.fetch(topic, p, o, 0.0)
                    if m is None:
                        break
                    records.append((m.timestamp_ms, m.key, m.value))
                    size += len(m.key) + len(m.value) + 32
                    o += 1
                record_set = encode_record_batch(off, records)
                budget -= len(record_set)
                w.i32(p).i16(ERR_NONE).i64(hwm).i64(hwm)
                w.i32(0)              # aborted_transactions
                w.bytes_(record_set)
        return w

    def _offset_commit(self, r: _R) -> _W:
        group = r.string()
        r.i32()                       # generation_id
        r.string()                    # member_id
        r.i64()                       # retention_time
        n_topics = r.i32()
        w = _W().i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            w.string(topic).i32(n_parts)
            for _ in range(n_parts):
                p = r.i32()
                off = r.i64()
                r.string()            # metadata
                with self._lock:
                    self._committed[(group, topic, p)] = off
                w.i32(p).i16(ERR_NONE)
        return w

    def _offset_fetch(self, r: _R) -> _W:
        group = r.string()
        n_topics = r.i32()
        w = _W().i32(n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            w.string(topic).i32(n_parts)
            for _ in range(n_parts):
                p = r.i32()
                with self._lock:
                    off = self._committed.get((group, topic, p), -1)
                w.i32(p).i64(off).string(None).i16(ERR_NONE)
        return w


# ---------------------------------------------------------------------------
# consumer
# ---------------------------------------------------------------------------

class KafkaWireConsumer(Consumer):
    """Consumer over the Kafka binary protocol (any compliant broker).

    Mirrors the reference consumer's librdkafka usage
    (kafka_consumer.h:27-118): assign + seek (no group rebalancing),
    timestamp seek via ListOffsets, offsets committed to the group
    coordinator via OffsetCommit."""

    def __init__(self, host: str, port: int, group_id: str = "",
                 client_id: str = "rstpu-wire", connect_timeout: float = 10.0):
        self.group_id = group_id
        self._client_id = client_id
        self._corr = 0
        self._lock = threading.Lock()  # rstpu-check: io-mutex serializes round-trips on the one blocking kafka socket
        self._sock = socket.create_connection((host, port), connect_timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._topic: Optional[str] = None
        self._positions: Dict[int, int] = {}
        self._buffers: Dict[int, deque] = {}
        self._rr: List[int] = []
        self.api_versions = self._api_versions_handshake()

    # -- request plumbing --------------------------------------------------

    def _request(self, api_key: int, api_version: int, body: bytes) -> _R:
        with self._lock:
            self._corr += 1
            corr = self._corr
            head = _W().i16(api_key).i16(api_version).i32(corr)
            head.string(self._client_id)
            _send_frame(self._sock, bytes(head.b) + body)
            resp = _R(_read_frame(self._sock))
        got = resp.i32()
        if got != corr:
            raise ValueError(f"kafka: correlation mismatch {got} != {corr}")
        return resp

    def _api_versions_handshake(self) -> Dict[int, Tuple[int, int]]:
        r = self._request(API_API_VERSIONS, 0, b"")
        err = r.i16()
        if err:
            raise ValueError(f"kafka ApiVersions error {err}")
        out = {}
        for _ in range(r.i32()):
            key, lo, hi = r.i16(), r.i16(), r.i16()
            out[key] = (lo, hi)
        for key, ver in ((API_FETCH, 4), (API_LIST_OFFSETS, 1),
                         (API_METADATA, 1)):
            lo, hi = out.get(key, (0, -1))
            if not lo <= ver <= hi:
                raise ValueError(
                    f"kafka: broker lacks api {key} v{ver} "
                    f"(supports {lo}..{hi})")
        return out

    # -- metadata ----------------------------------------------------------

    def partitions_for(self, topic: str) -> int:
        body = _W().i32(1).string(topic)
        r = self._request(API_METADATA, 1, bytes(body.b))
        for _ in range(r.i32()):      # brokers
            r.i32(); r.string(); r.i32(); r.string()
        r.i32()                       # controller_id
        n_topics = r.i32()
        for _ in range(n_topics):
            err = r.i16()
            name = r.string()
            r.i8()
            n_parts = r.i32()
            for _ in range(n_parts):
                r.i16(); r.i32(); r.i32()
                for _ in range(r.i32()):
                    r.i32()
                for _ in range(r.i32()):
                    r.i32()
            if name == topic:
                if err:
                    raise KeyError(f"kafka topic {topic}: error {err}")
                return n_parts
        raise KeyError(f"kafka topic {topic}: not in metadata")

    # -- Consumer interface ------------------------------------------------

    def assign(self, topic: str, partitions: Sequence[int]) -> None:
        self._topic = topic
        self._positions = {p: 0 for p in partitions}
        self._buffers = {p: deque() for p in partitions}
        self._rr = list(partitions)

    def seek(self, partition: int, offset: int) -> None:
        self._positions[partition] = offset
        self._buffers[partition].clear()

    def _list_offsets(self, timestamp: int) -> Dict[int, int]:
        assert self._topic is not None
        body = _W().i32(-1).i32(1).string(self._topic)
        body.i32(len(self._positions))
        for p in self._positions:
            body.i32(p).i64(timestamp)
        r = self._request(API_LIST_OFFSETS, 1, bytes(body.b))
        out = {}
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                p = r.i32()
                err = r.i16()
                r.i64()               # timestamp
                off = r.i64()
                if err:
                    raise ValueError(f"kafka ListOffsets p{p}: error {err}")
                out[p] = off
        return out

    def seek_to_timestamp(self, ts_ms: int) -> None:
        for p, off in self._list_offsets(ts_ms).items():
            self.seek(p, off)

    def high_watermark(self, partition: int) -> int:
        return self._list_offsets(-1)[partition]

    def position(self, partition: int) -> int:
        return self._positions[partition]

    def _fetch_into_buffers(self, timeout_sec: float) -> None:
        assert self._topic is not None
        body = _W().i32(-1).i32(max(0, int(timeout_sec * 1000)))
        body.i32(1)                   # min_bytes
        body.i32(8 << 20)             # max_bytes
        body.i8(0)                    # isolation_level: READ_UNCOMMITTED
        body.i32(1).string(self._topic).i32(len(self._positions))
        for p in self._rr:
            body.i32(p).i64(self._positions[p]).i32(1 << 20)
        r = self._request(API_FETCH, 4, bytes(body.b))
        r.i32()                       # throttle_time_ms
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                p = r.i32()
                err = r.i16()
                hwm = r.i64()         # high_watermark
                r.i64()               # last_stable_offset
                for _ in range(r.i32()):
                    r.i64(); r.i64()  # aborted txns
                record_set = r.bytes_() or b""
                if err:
                    # swallowing this would wedge consume() in an
                    # indefinite empty-poll loop (e.g. retention deleted
                    # our position: every fetch repeats the error). Fail
                    # loudly with enough context to reseek.
                    raise KafkaWireError(
                        f"kafka fetch {self._topic}[{p}] "
                        f"@{self._positions.get(p)}: error {err}",
                        error_code=err, partition=p, high_watermark=hwm)
                records, next_off = decode_record_set(record_set)
                delivered = False
                for off, ts, key, value in records:
                    if off < self._positions[p]:
                        continue      # broker returned the whole batch
                    delivered = True
                    self._buffers[p].append(Message(
                        topic=self._topic, partition=p, offset=off,
                        timestamp_ms=ts, key=key or b"", value=value,
                    ))
                if (not delivered and not self._buffers[p]
                        and next_off is not None
                        and next_off > self._positions[p]):
                    # nothing consumable (control markers / compacted
                    # batches): advance past them or the next fetch
                    # refetches the same batch forever
                    self._positions[p] = next_off

    def consume(self, timeout_sec: float) -> Optional[Message]:
        assert self._topic is not None
        deadline = time.monotonic() + timeout_sec
        while True:
            for _ in range(len(self._rr)):
                p = self._rr.pop(0)
                self._rr.append(p)
                if self._buffers[p]:
                    msg = self._buffers[p].popleft()
                    self._positions[p] = msg.offset + 1
                    return msg
            remaining = deadline - time.monotonic()
            if remaining < 0:
                return None
            self._fetch_into_buffers(min(remaining, 0.5))
            if not any(self._buffers.values()) and remaining <= 0.5:
                return None

    def commit(self) -> None:
        assert self._topic is not None
        body = _W().string(self.group_id).i32(-1).string("").i64(-1)
        body.i32(1).string(self._topic).i32(len(self._positions))
        for p, off in self._positions.items():
            body.i32(p).i64(off).string(None)
        r = self._request(API_OFFSET_COMMIT, 2, bytes(body.b))
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                p = r.i32()
                err = r.i16()
                if err:
                    raise ValueError(f"kafka OffsetCommit p{p}: error {err}")

    def committed_offsets(self) -> Dict[int, int]:
        assert self._topic is not None
        body = _W().string(self.group_id)
        body.i32(1).string(self._topic).i32(len(self._positions))
        for p in self._positions:
            body.i32(p)
        r = self._request(API_OFFSET_FETCH, 1, bytes(body.b))
        out = {}
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                p = r.i32()
                off = r.i64()
                r.string()
                err = r.i16()
                if err:
                    raise ValueError(f"kafka OffsetFetch p{p}: error {err}")
                if off >= 0:
                    out[p] = off
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class KafkaWireProducer:
    """Minimal Kafka-protocol producer (Produce v3, acks=1): one record
    batch per request — the CDC publish cadence, not a bulk pipeline.
    Works against any Kafka-protocol broker (the reference publishes CDC
    updates through librdkafka producers)."""

    def __init__(self, host: str, port: int, client_id: str = "rstpu-wire",
                 connect_timeout: float = 10.0):
        self._client_id = client_id
        self._corr = 0
        self._lock = threading.Lock()  # rstpu-check: io-mutex serializes round-trips on the one blocking kafka socket
        self._sock = socket.create_connection((host, port), connect_timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _request(self, api_key: int, api_version: int, body: bytes) -> _R:
        with self._lock:
            self._corr += 1
            corr = self._corr
            head = _W().i16(api_key).i16(api_version).i32(corr)
            head.string(self._client_id)
            _send_frame(self._sock, bytes(head.b) + body)
            resp = _R(_read_frame(self._sock))
        got = resp.i32()
        if got != corr:
            raise ValueError(f"kafka: correlation mismatch {got} != {corr}")
        return resp

    def produce(self, topic: str, partition: int, key: bytes, value: bytes,
                timestamp_ms: int) -> int:
        """Appends one record; returns its offset."""
        record_set = encode_record_batch(
            0, [(timestamp_ms, key, value)])
        body = _W().string(None).i16(1).i32(30_000)
        body.i32(1).string(topic).i32(1)
        body.i32(partition).bytes_(record_set)
        r = self._request(API_PRODUCE, 3, bytes(body.b))
        base_offset = -1
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                p = r.i32()
                err = r.i16()
                off = r.i64()
                r.i64()               # log_append_time
                if err:
                    raise KafkaWireError(
                        f"kafka produce {topic}[{p}]: error {err}",
                        error_code=err, partition=p)
                base_offset = off
        return base_offset

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class KafkaWirePublisher:
    """CDC Publisher callable over the wire protocol — the drop-in
    real-Kafka variant of kafka/publisher.QueuePublisher (same partition
    routing: shard id mod partitions)."""

    def __init__(self, topic: str, host: str, port: int,
                 num_partitions: int = 16):
        from ..utils.segment_utils import extract_shard_id

        self._extract_shard_id = extract_shard_id
        self._topic = topic
        self._num_partitions = num_partitions
        self._producer = KafkaWireProducer(host, port)

    def __call__(self, db_name: str, start_seq: int, raw: bytes,
                 timestamp_ms) -> None:
        shard = self._extract_shard_id(db_name)
        partition = shard % self._num_partitions if shard >= 0 else 0
        self._producer.produce(
            self._topic, partition,
            key=f"{db_name}:{start_seq}".encode(), value=bytes(raw),
            timestamp_ms=int(timestamp_ms) if timestamp_ms else 0,
        )

    def close(self) -> None:
        self._producer.close()
