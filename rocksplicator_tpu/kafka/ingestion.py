"""start/stopMessageIngestion: queue → db wiring, exactly-once.

Reference: admin_handler.cpp message-ingestion paths — a consumer per db
on the topic partition matching the db's shard id; messages apply as
PUTs (empty value ⇒ DELETE); ``last_kafka_msg_timestamp_ms`` persists
into the meta_db every 1000 messages (admin_handler.cpp:2065-2075).

This implementation replaces the reference's at-least-once
timestamp-replay resume with exactly-once WAL-riding checkpoints
(kafka/checkpoint.py): every apply batch carries the partition's
watermark PUT in the same engine WriteBatch as its records, so a
crashed consumer reopens, reads the durable watermark, seeks to it, and
skips re-delivered offsets below it — zero duplicates, zero gaps, by
construction. Batches commit through the round-6 ``write_many``
grouped-commit path (one lock pass + one WAL flush per drained fetch,
not per record). The timestamp-persist path stays as the reference-
compatible fallback for dbs that never checkpointed.

Backpressure: before each fetch round the consumer reads the engine's
round-14 pressure gauges (L0 depth vs the delayed-write controller's
slowdown/stop triggers, memtable fullness, WAL backlog) and sleeps
proportionally — a hot topic slows the fetch loop instead of stacking
unflushed memtables. A typed RETRY_LATER from the write path (admission
shedding) is honored via the round-19 retry-after hint: the SAME group
retries after the hinted delay, so shedding never drops or duplicates
records.

Fault seams (registered): ``kafka.fetch`` (before each fetch round),
``kafka.apply`` (before the grouped commit), ``kafka.checkpoint`` (as
each batch's watermark is folded in). A fault at any seam kills the
consumer thread mid-batch; restart resumes from the durable watermark.

Broker addressing: ``embedded://<cluster>`` selects an in-process
MockKafkaCluster; ``broker://host:port`` the networked broker; a file
path is a broker-serverset file.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..rpc.errors import RpcApplicationError
from ..storage.records import WriteBatch
from ..testing import failpoints as fp
from ..utils.retry_policy import retry_after_hint
from ..utils.segment_utils import extract_shard_id
from ..utils.stats import Stats
from .broker import Message, MockConsumer, get_cluster
from .checkpoint import (applies_key, encode_watermark, read_applies,
                         read_watermark, watermark_key)

log = logging.getLogger(__name__)

META_PERSIST_EVERY = 1000  # messages (admin_handler.cpp:2065-2075)

# grouped-commit shape: one fetch round drains up to MAX_DRAIN messages,
# chunked into WriteBatches of BATCH_RECORDS records (each chunk carries
# its own watermark — write_many groups are not crash-atomic across
# batches, so every batch must be self-describing)
MAX_DRAIN = 512
BATCH_RECORDS = 64
POLL_SEC = 0.2  # blocking fetch when idle
PACE_MAX_SEC = 0.25  # hard cap on one backpressure sleep


def _pacing_delay(snap: Dict, opts) -> float:
    """Fetch-pacing delay derived from the delayed-write controller's
    own inputs (round 14 gauges): scale from 0 at the L0 slowdown
    trigger to PACE_MAX at the stop trigger, and from a full memtable
    pipeline upward. Zero when the engine is keeping up."""
    if not snap:
        return 0.0
    delay = 0.0
    level_files = snap.get("level_files") or [0]
    l0 = level_files[0]
    soft = opts.level0_slowdown_writes_trigger
    hard = opts.level0_stop_writes_trigger
    if hard > soft and l0 > soft:
        delay = PACE_MAX_SEC * min(1.0, (l0 - soft) / (hard - soft))
    # memtable pipeline fullness: active + immutables vs one memtable
    mem_frac = snap.get("memtable_bytes", 0) / max(1.0, opts.memtable_bytes)
    if mem_frac > 1.0:
        delay = max(delay, PACE_MAX_SEC * min(1.0, mem_frac - 1.0))
    # WAL backlog: unflushed bytes several memtables deep means flush is
    # behind — back off proportionally
    wal_frac = snap.get("wal_backlog_bytes", 0) / max(
        1.0, 8.0 * opts.memtable_bytes)
    if wal_frac > 1.0:
        delay = max(delay, PACE_MAX_SEC * min(1.0, wal_frac - 1.0))
    return delay


class IngestionWatcher:
    """The exactly-once batched applier: one consumer thread per db."""

    def __init__(self, handler, db_name: str, app_db, consumer, topic: str,
                 partitions: Sequence[int], start_ts: int):
        self._handler = handler
        self._db_name = db_name
        self._app_db = app_db
        self._consumer = consumer
        self._topic = topic
        self._partitions = list(partitions)
        self._start_ts = start_ts
        self._stats = Stats.get()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # durable positions, mirrored in memory: next offset to apply and
        # records-applied-total per partition
        self._watermarks: Dict[int, int] = {}
        self._applied: Dict[int, int] = {}
        self._since_persist = 0
        self.replay_done = threading.Event()
        self.last_timestamp_ms = 0
        self.error: Optional[BaseException] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"cdc-{self._db_name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        if self.last_timestamp_ms:
            self._persist_timestamp(self.last_timestamp_ms)
        try:
            self._consumer.commit()
        except Exception:
            pass  # broker-side offsets are advisory; the WAL is truth
        try:
            self._consumer.close()
        except Exception:
            pass

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def watermark(self, partition: int) -> int:
        """Next offset the consumer will apply (in-memory mirror)."""
        return self._watermarks.get(partition, 0)

    # -- engine access ----------------------------------------------------

    def _engine_db(self):
        return getattr(self._app_db, "db", self._app_db)

    # -- the consume/apply loop -------------------------------------------

    def _run(self) -> None:
        try:
            self._resume()
            highs = {p: self._consumer.high_watermark(p)
                     for p in self._partitions}
            if all(self._position(p) >= highs[p]
                   for p in self._partitions):
                self.replay_done.set()
            while not self._stop_evt.is_set():
                self._pace()
                if self._stop_evt.is_set():
                    break
                fp.hit("kafka.fetch")
                msgs = self._drain()
                if not self.replay_done.is_set() and all(
                        self._position(p) >= highs[p]
                        for p in self._partitions):
                    self.replay_done.set()
                if not msgs:
                    continue
                groups = self._build_batches(msgs)
                if not groups:
                    continue
                fp.hit("kafka.apply")
                self._apply_group([g[4] for g in groups])
                self._commit_positions(groups)
        except BaseException as e:  # noqa: BLE001 — seam kills land here
            if not self._stop_evt.is_set():
                self.error = e
                self._stats.incr("kafka.cdc.consumer_errors")
                log.exception("%s: CDC consumer died (restart resumes "
                              "from the durable watermark)", self._db_name)

    def _position(self, partition: int) -> int:
        try:
            return self._consumer.position(partition)
        except Exception:
            return 0

    def _resume(self) -> None:
        """Durable watermark wins; timestamp seek is the never-
        checkpointed fallback (reference replay semantics)."""
        self._consumer.assign(self._topic, self._partitions)
        engine = self._engine_db()
        unseen: List[int] = []
        for p in self._partitions:
            wm = read_watermark(engine, self._topic, p)
            if wm is None:
                unseen.append(p)
                self._watermarks[p] = 0
                self._applied[p] = read_applies(engine, self._topic, p)
            else:
                self._watermarks[p] = wm["offset"]
                # the durable counter (riding the records batches) is the
                # authority, NOT the watermark's copy: with a checkpoint
                # decoupled from its batch (the cdc_dedup bug class) the
                # watermark's count is stale-consistent and would let
                # re-applied records self-heal the witness
                self._applied[p] = max(
                    wm["applied"], read_applies(engine, self._topic, p))
                self.last_timestamp_ms = max(
                    self.last_timestamp_ms, wm["ts_ms"])
        if unseen and len(unseen) == len(self._partitions) \
                and self._start_ts:
            self._consumer.seek_to_timestamp(self._start_ts)
        for p in self._partitions:
            if p not in unseen:
                self._consumer.seek(p, self._watermarks[p])
        self._stats.incr("kafka.cdc.resumes")

    def _pace(self) -> None:
        engine = self._engine_db()
        snap_fn = getattr(engine, "metrics_snapshot", None)
        if snap_fn is None:
            return
        try:
            delay = _pacing_delay(snap_fn(max_age=0.1), engine.options)
        except Exception:
            return
        if delay > 0:
            self._stats.incr("kafka.cdc.paced_sleeps")
            self._stats.incr("kafka.cdc.paced_ms", delay * 1000.0)
            self._stop_evt.wait(delay)

    def _drain(self) -> List[Message]:
        msgs: List[Message] = []
        msg = self._consumer.consume(POLL_SEC)
        while msg is not None:
            msgs.append(msg)
            if len(msgs) >= MAX_DRAIN:
                break
            msg = self._consumer.consume(0.0)
        return msgs

    def _build_batches(
        self, msgs: List[Message],
    ) -> List[Tuple[int, int, int, int, WriteBatch, int]]:
        """(partition, next_offset, applied_total, last_ts_ms, batch,
        n_records) per chunk — records + applies counter + watermark,
        one atomic WriteBatch each. Re-delivered offsets below the
        watermark are skipped (the dedup-by-construction window)."""
        per_part: Dict[int, List[Message]] = {}
        for m in msgs:
            if m.offset < self._watermarks.get(m.partition, 0):
                self._stats.incr("kafka.cdc.dup_skipped")
                continue
            per_part.setdefault(m.partition, []).append(m)
        groups: List[Tuple[int, int, int, int, WriteBatch, int]] = []
        for p, ms in per_part.items():
            applied = self._applied.get(p, 0)
            for i in range(0, len(ms), BATCH_RECORDS):
                chunk = ms[i:i + BATCH_RECORDS]
                batch = WriteBatch()
                for m in chunk:
                    if m.value:
                        batch.put(m.key, m.value)
                    else:
                        batch.delete(m.key)
                applied += len(chunk)
                next_off = chunk[-1].offset + 1
                ts = chunk[-1].timestamp_ms
                batch.put(applies_key(self._topic, p),
                          b"%d" % applied)
                self._fold_checkpoint(batch, p, next_off, applied, ts)
                groups.append((p, next_off, applied, ts, batch,
                               len(chunk)))
        return groups

    def _fold_checkpoint(self, batch: WriteBatch, partition: int,
                         next_offset: int, applied: int,
                         ts_ms: int) -> None:
        """THE exactly-once seam: the watermark PUT joins the records'
        own WriteBatch (one WAL record, crash-atomic). The chaos
        harness's ``cdc_dedup`` tooth patches this to a decoupled
        second write — which the applies-counter invariant catches."""
        fp.hit("kafka.checkpoint")
        batch.put(watermark_key(self._topic, partition),
                  encode_watermark(next_offset, applied, ts_ms))

    def _apply_group(self, batches: List[WriteBatch]) -> None:
        """One grouped commit; RETRY_LATER (admission shed) retries the
        SAME group after the server's hinted delay — shedding must
        never drop or duplicate records."""
        while True:
            try:
                self._write_many(batches)
                return
            except RpcApplicationError as e:
                hint = retry_after_hint(e)
                if hint is None:
                    raise
                self._stats.incr("kafka.cdc.retry_later")
                if self._stop_evt.wait(min(hint, 5.0)):
                    raise

    def _write_many(self, batches: List[WriteBatch]) -> None:
        target = self._app_db
        if hasattr(target, "db"):  # ApplicationDB: replication-aware
            target.write_many(batches)
        elif hasattr(target, "write_many"):  # raw engine DB
            target.write_many([(b, None) for b in batches])
        else:
            for b in batches:
                target.write(b)

    def _commit_positions(self, groups) -> None:
        n = 0
        for p, next_off, applied, ts, _batch, nrec in groups:
            n += nrec
            self._watermarks[p] = next_off
            self._applied[p] = applied
            if ts > self.last_timestamp_ms:
                self.last_timestamp_ms = ts
        self._stats.incr("kafka.cdc.batches", len(groups))
        self._stats.incr("kafka.cdc.records_applied", n)
        self._stats.incr("kafka.cdc.bytes_applied",
                         sum(g[4].byte_size() for g in groups))
        self._since_persist += n
        if self._since_persist >= META_PERSIST_EVERY:
            self._since_persist = 0
            self._persist_timestamp(self.last_timestamp_ms)

    def _persist_timestamp(self, ts_ms: int) -> None:
        if self._handler is None:
            return
        try:
            self._handler.write_meta_data(
                self._db_name, last_kafka_msg_timestamp_ms=ts_ms
            )
        except Exception:
            log.exception("%s: persisting kafka timestamp failed",
                          self._db_name)


def _resolve_consumer(broker_path: str, topic_name: str, group_id: str):
    """(consumer, num_partitions) for a broker address.

    ``embedded://<name>`` (or empty) → in-process MockKafkaCluster;
    ``broker://host:port`` / ``host:port`` → networked BrokerServer
    (kafka/network.py, the librdkafka analog); an existing file path →
    broker-serverset file whose first line is ``host:port`` (reference
    KafkaBrokerFileWatcher reads the broker list from such files)."""
    import os

    if broker_path.startswith("embedded://") or not broker_path:
        cluster_name = broker_path[len("embedded://"):] or "default"
        cluster = get_cluster(cluster_name)
        return (
            MockConsumer(cluster, group_id=group_id),
            cluster.num_partitions(topic_name),
        )
    addr = broker_path
    if addr.startswith("broker://"):
        addr = addr[len("broker://"):]
    elif os.path.isfile(addr):
        # serverset format (KafkaBrokerFileWatcher): one host:port per
        # line, comments/blanks skipped; use the first broker listed
        with open(addr) as f:
            lines = [ln.strip() for ln in f
                     if ln.strip() and not ln.lstrip().startswith("#")]
        if not lines:
            raise RpcApplicationError(
                "DB_ADMIN_ERROR", f"empty broker serverset: {broker_path}")
        addr = lines[0]
    host, _, port_s = addr.rpartition(":")
    if not host or not port_s.isdigit():
        raise RpcApplicationError(
            "DB_ADMIN_ERROR", f"bad broker address: {broker_path!r}")
    from .network import NetworkConsumer

    consumer = NetworkConsumer(host, int(port_s), group_id=group_id)
    try:
        n = consumer.call("broker_num_partitions",
                          topic=topic_name)["num_partitions"]
    except BaseException:
        consumer.close()
        raise
    return consumer, n


def start_ingestion(handler, db_name: str, app_db, topic_name: str,
                    broker_path: str, start_ts: int) -> IngestionWatcher:
    """The admin RPC seam (handler.py start/stopMessageIngestion)."""
    if not topic_name:
        raise RpcApplicationError("DB_ADMIN_ERROR", "topic_name required")
    consumer, num_partitions = _resolve_consumer(
        broker_path, topic_name, group_id=f"ingest-{db_name}")
    if num_partitions == 0:
        consumer.close()
        raise RpcApplicationError(
            "DB_ADMIN_ERROR", f"no such topic: {topic_name}"
        )
    # The partition IS the shard id (reference rejects any mismatch rather
    # than silently ingesting another shard's data).
    shard = extract_shard_id(db_name)
    if not (0 <= shard < num_partitions):
        consumer.close()
        raise RpcApplicationError(
            "DB_ADMIN_ERROR",
            f"shard {shard} of {db_name} has no partition in topic "
            f"{topic_name} ({num_partitions} partitions)",
        )
    partition = shard
    watcher = IngestionWatcher(
        handler, db_name, app_db, consumer, topic_name, [partition], start_ts
    )
    watcher.start()
    return watcher
