"""start/stopMessageIngestion: queue → db wiring.

Reference: admin_handler.cpp message-ingestion paths — a KafkaWatcher per
db consuming the topic partition matching the db's shard id; messages
apply as PUTs (empty value ⇒ DELETE); ``last_kafka_msg_timestamp_ms``
persists into the meta_db every 1000 messages (admin_handler.cpp:2065-2075)
so a restart resumes from where ingestion left off (replay via timestamp
seek).

Broker addressing: ``embedded://<cluster>`` selects an in-process
MockKafkaCluster (the only backend in this image); a file path is treated
as a broker-serverset file for a future networked backend.
"""

from __future__ import annotations

import logging

from ..rpc.errors import RpcApplicationError
from ..storage.records import WriteBatch
from ..utils.segment_utils import extract_shard_id
from .broker import Message, MockConsumer, get_cluster
from .watcher import KafkaWatcher

log = logging.getLogger(__name__)

META_PERSIST_EVERY = 1000  # messages (admin_handler.cpp:2065-2075)


class IngestionWatcher(KafkaWatcher):
    def __init__(self, handler, db_name: str, app_db, consumer, topic: str,
                 partitions, start_ts: int):
        super().__init__(
            name=db_name, consumer=consumer, topic=topic,
            partitions=partitions, start_timestamp_ms=start_ts,
        )
        self._handler = handler
        self._db_name = db_name
        self._app_db = app_db
        self._since_persist = 0

    def handle_message(self, msg: Message, is_replay: bool) -> None:
        batch = WriteBatch()
        if msg.value:
            batch.put(msg.key, msg.value)
        else:
            batch.delete(msg.key)
        self._app_db.write(batch)
        self._since_persist += 1
        if self._since_persist >= META_PERSIST_EVERY:
            self._since_persist = 0
            self._persist_timestamp(msg.timestamp_ms)

    def _persist_timestamp(self, ts_ms: int) -> None:
        try:
            self._handler.write_meta_data(
                self._db_name, last_kafka_msg_timestamp_ms=ts_ms
            )
        except Exception:
            log.exception("%s: persisting kafka timestamp failed", self._db_name)

    def stop(self) -> None:
        super().stop()
        if self.last_timestamp_ms:
            self._persist_timestamp(self.last_timestamp_ms)


def _resolve_consumer(broker_path: str, topic_name: str, group_id: str):
    """(consumer, num_partitions) for a broker address.

    ``embedded://<name>`` (or empty) → in-process MockKafkaCluster;
    ``broker://host:port`` / ``host:port`` → networked BrokerServer
    (kafka/network.py, the librdkafka analog); an existing file path →
    broker-serverset file whose first line is ``host:port`` (reference
    KafkaBrokerFileWatcher reads the broker list from such files)."""
    import os

    if broker_path.startswith("embedded://") or not broker_path:
        cluster_name = broker_path[len("embedded://"):] or "default"
        cluster = get_cluster(cluster_name)
        return (
            MockConsumer(cluster, group_id=group_id),
            cluster.num_partitions(topic_name),
        )
    addr = broker_path
    if addr.startswith("broker://"):
        addr = addr[len("broker://"):]
    elif os.path.isfile(addr):
        # serverset format (KafkaBrokerFileWatcher): one host:port per
        # line, comments/blanks skipped; use the first broker listed
        with open(addr) as f:
            lines = [ln.strip() for ln in f
                     if ln.strip() and not ln.lstrip().startswith("#")]
        if not lines:
            raise RpcApplicationError(
                "DB_ADMIN_ERROR", f"empty broker serverset: {broker_path}")
        addr = lines[0]
    host, _, port_s = addr.rpartition(":")
    if not host or not port_s.isdigit():
        raise RpcApplicationError(
            "DB_ADMIN_ERROR", f"bad broker address: {broker_path!r}")
    from .network import NetworkConsumer

    consumer = NetworkConsumer(host, int(port_s), group_id=group_id)
    try:
        n = consumer.call("broker_num_partitions",
                          topic=topic_name)["num_partitions"]
    except BaseException:
        consumer.close()
        raise
    return consumer, n


def start_ingestion(handler, db_name: str, app_db, topic_name: str,
                    broker_path: str, start_ts: int) -> IngestionWatcher:
    """The admin RPC seam (handler.py start/stopMessageIngestion)."""
    if not topic_name:
        raise RpcApplicationError("DB_ADMIN_ERROR", "topic_name required")
    consumer, num_partitions = _resolve_consumer(
        broker_path, topic_name, group_id=f"ingest-{db_name}")
    if num_partitions == 0:
        consumer.close()
        raise RpcApplicationError(
            "DB_ADMIN_ERROR", f"no such topic: {topic_name}"
        )
    # The partition IS the shard id (reference rejects any mismatch rather
    # than silently ingesting another shard's data).
    shard = extract_shard_id(db_name)
    if not (0 <= shard < num_partitions):
        consumer.close()
        raise RpcApplicationError(
            "DB_ADMIN_ERROR",
            f"shard {shard} of {db_name} has no partition in topic "
            f"{topic_name} ({num_partitions} partitions)",
        )
    partition = shard
    watcher = IngestionWatcher(
        handler, db_name, app_db, consumer, topic_name, [partition], start_ts
    )
    watcher.start()
    return watcher
