"""start/stopMessageIngestion seam (filled in by the queue stack)."""

from __future__ import annotations

from ..rpc.errors import RpcApplicationError


def start_ingestion(handler, db_name, app_db, topic_name, broker_path, start_ts):
    raise RpcApplicationError(
        "NOT_IMPLEMENTED", "message ingestion requires the queue stack"
    )
