"""Message broker abstraction + the in-process mock cluster.

Reference: common/kafka/ wraps librdkafka against real brokers and tests
against ``MockKafkaCluster`` (an in-memory topic/partition log with
timestamp seek, common/kafka/tests/mock_kafka_cluster.h) +
``MockKafkaConsumer``. Here the mock IS the first-class embedded backend
(no broker binary in the image); a librdkafka-style networked backend
slots in behind the same ``Consumer`` interface.
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Message:
    topic: str
    partition: int
    offset: int
    timestamp_ms: int
    key: bytes
    value: bytes


class _PartitionLog:
    def __init__(self) -> None:
        self.messages: List[Message] = []
        self.timestamps: List[int] = []  # parallel, for timestamp seek

    def append(self, msg: Message) -> None:
        self.messages.append(msg)
        self.timestamps.append(msg.timestamp_ms)

    def offset_for_timestamp(self, ts_ms: int) -> int:
        """First offset with timestamp >= ts_ms (reference Seek-by-time)."""
        return bisect.bisect_left(self.timestamps, ts_ms)


class MockKafkaCluster:
    """In-memory topic/partition logs with condition-variable tailing."""

    def __init__(self) -> None:
        self._topics: Dict[str, List[_PartitionLog]] = {}
        self._cond = threading.Condition()

    def create_topic(self, topic: str, num_partitions: int = 1) -> None:
        with self._cond:
            if topic not in self._topics:
                self._topics[topic] = [
                    _PartitionLog() for _ in range(num_partitions)
                ]

    def num_partitions(self, topic: str) -> int:
        with self._cond:
            return len(self._topics.get(topic, []))

    def topics(self) -> List[str]:
        with self._cond:
            return sorted(self._topics)

    def produce(self, topic: str, partition: int, key: bytes, value: bytes,
                timestamp_ms: Optional[int] = None) -> int:
        with self._cond:
            if topic not in self._topics:
                raise KeyError(f"no such topic: {topic}")
            log = self._topics[topic][partition]
            msg = Message(
                topic=topic, partition=partition, offset=len(log.messages),
                timestamp_ms=(
                    timestamp_ms if timestamp_ms is not None
                    else int(time.time() * 1000)
                ),
                key=bytes(key), value=bytes(value),
            )
            log.append(msg)
            self._cond.notify_all()
            return msg.offset

    def high_watermark(self, topic: str, partition: int) -> int:
        with self._cond:
            return len(self._topics[topic][partition].messages)

    def fetch(self, topic: str, partition: int, offset: int,
              timeout_sec: float) -> Optional[Message]:
        deadline = time.monotonic() + timeout_sec
        with self._cond:
            while True:
                log = self._topics.get(topic, [None])[partition]
                if log is not None and offset < len(log.messages):
                    return log.messages[offset]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)

    def offset_for_timestamp(self, topic: str, partition: int,
                             ts_ms: int) -> int:
        with self._cond:
            return self._topics[topic][partition].offset_for_timestamp(ts_ms)


class Consumer:
    """The consumer interface (reference kafka_consumer.h:27-118)."""

    def assign(self, topic: str, partitions: Sequence[int]) -> None:
        raise NotImplementedError

    def seek(self, partition: int, offset: int) -> None:
        raise NotImplementedError

    def seek_to_timestamp(self, ts_ms: int) -> None:
        raise NotImplementedError

    def consume(self, timeout_sec: float) -> Optional[Message]:
        raise NotImplementedError

    def commit(self) -> None:
        raise NotImplementedError

    def position(self, partition: int) -> int:
        raise NotImplementedError

    def high_watermark(self, partition: int) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MockConsumer(Consumer):
    """Consumer over MockKafkaCluster (reference MockKafkaConsumer)."""

    def __init__(self, cluster: MockKafkaCluster, group_id: str = ""):
        self._cluster = cluster
        self.group_id = group_id
        self._topic: Optional[str] = None
        self._positions: Dict[int, int] = {}
        self._committed: Dict[int, int] = {}
        self._rr: List[int] = []

    def assign(self, topic: str, partitions: Sequence[int]) -> None:
        self._topic = topic
        self._positions = {p: 0 for p in partitions}
        self._rr = list(partitions)

    def seek(self, partition: int, offset: int) -> None:
        self._positions[partition] = offset

    def seek_to_timestamp(self, ts_ms: int) -> None:
        assert self._topic is not None
        for p in self._positions:
            self._positions[p] = self._cluster.offset_for_timestamp(
                self._topic, p, ts_ms
            )

    def consume(self, timeout_sec: float) -> Optional[Message]:
        assert self._topic is not None
        deadline = time.monotonic() + timeout_sec
        while True:
            # round-robin over assigned partitions, non-blocking first
            for _ in range(len(self._rr)):
                p = self._rr.pop(0)
                self._rr.append(p)
                msg = self._cluster.fetch(self._topic, p,
                                          self._positions[p], 0.0)
                if msg is not None:
                    self._positions[p] = msg.offset + 1
                    return msg
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            # block on the first partition for the remainder
            p = self._rr[0]
            msg = self._cluster.fetch(
                self._topic, p, self._positions[p], min(remaining, 0.1)
            )
            if msg is not None:
                self._positions[p] = msg.offset + 1
                return msg

    def commit(self) -> None:
        self._committed = dict(self._positions)

    @property
    def committed(self) -> Dict[int, int]:
        return dict(self._committed)

    def position(self, partition: int) -> int:
        return self._positions[partition]

    def high_watermark(self, partition: int) -> int:
        assert self._topic is not None
        return self._cluster.high_watermark(self._topic, partition)


# process-wide registry so admin RPC handlers can reach embedded clusters
# by name (stands in for broker addresses in the serverset file)
_clusters: Dict[str, MockKafkaCluster] = {}
_clusters_lock = threading.Lock()


def get_cluster(name: str = "default") -> MockKafkaCluster:
    with _clusters_lock:
        cluster = _clusters.get(name)
        if cluster is None:
            cluster = _clusters[name] = MockKafkaCluster()
        return cluster


def reset_clusters_for_test() -> None:
    with _clusters_lock:
        _clusters.clear()
