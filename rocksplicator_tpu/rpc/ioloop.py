"""IoLoop: one asyncio event loop in a dedicated IO thread.

Reference analog: common/thrift_client_pool.h's N IO threads each driving a
folly EventBase. Here one loop multiplexes all connections (Python sockets
are cheap under asyncio); sync layers submit coroutines and wait on
concurrent futures, matching the reference pattern of CPU worker threads
handing IO to EventBase threads.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Awaitable, Coroutine, Optional, TypeVar

T = TypeVar("T")


class IoLoop:
    _default: Optional["IoLoop"] = None
    _default_lock = threading.Lock()

    def __init__(self, name: str = "rpc-io"):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    @classmethod
    def default(cls) -> "IoLoop":
        with cls._default_lock:
            if cls._default is None or not cls._default._thread.is_alive():
                cls._default = cls()
            return cls._default

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.call_soon(self._started.set)
        self._loop.run_forever()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop

    def run_coro(self, coro: Coroutine[Any, Any, T]) -> "concurrent.futures.Future[T]":
        """Submit a coroutine from any thread; returns a concurrent future."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def run_sync(self, coro: Coroutine[Any, Any, T], timeout: Optional[float] = None) -> T:
        if threading.current_thread() is self._thread:
            raise RuntimeError("run_sync called from the IO thread (would deadlock)")
        return self.run_coro(coro).result(timeout)

    def call_soon(self, fn, *args) -> None:
        self._loop.call_soon_threadsafe(fn, *args)

    def stop(self) -> None:
        def _shutdown():
            for task in asyncio.all_tasks(self._loop):
                task.cancel()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=5.0)
