"""IoLoop: one asyncio event loop in a dedicated IO thread.

Reference analog: common/thrift_client_pool.h's N IO threads each driving a
folly EventBase. Here one loop multiplexes all connections (Python sockets
are cheap under asyncio); sync layers submit coroutines and wait on
concurrent futures, matching the reference pattern of CPU worker threads
handing IO to EventBase threads.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import threading
import time
from typing import Any, Awaitable, Coroutine, Optional, TypeVar

T = TypeVar("T")

# Loop-stall watchdog (the runtime half of rstpu-check pass 2): armed
# with the lockwatch (RSTPU_LOCKWATCH) or on its own (RSTPU_LOOPWATCH=1),
# a monitor task measures dispatch lag every tick and publishes stalls
# longer than RSTPU_LOOPWATCH_MS (default 100) as `ioloop.stalls` +
# `ioloop.stall_ms` on /stats — one blocking call on the loop stalls
# every colocated replica, and this is how a chaos run notices.
_WATCH_TICK_S = 0.25


def _loopwatch_armed() -> bool:
    return bool(os.environ.get("RSTPU_LOCKWATCH")
                or os.environ.get("RSTPU_LOOPWATCH"))


class IoLoop:
    _default: Optional["IoLoop"] = None
    _default_lock = threading.Lock()

    def __init__(self, name: str = "rpc-io"):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()
        if _loopwatch_armed():
            self._stall_threshold_s = float(
                os.environ.get("RSTPU_LOOPWATCH_MS", "100")) / 1000.0
            self._loop.call_soon_threadsafe(
                self._stall_tick, time.monotonic())

    def _stall_tick(self, last: float) -> None:
        # self-rescheduling call_later chain (no long-lived task to
        # destroy at loop stop): dispatch lag beyond the tick interval
        # is time some callback/coroutine spent hogging the loop
        now = time.monotonic()
        lag = now - last - _WATCH_TICK_S
        if lag > self._stall_threshold_s:
            from ..utils.stats import Stats

            stats = Stats.get()
            stats.incr("ioloop.stalls")
            stats.add_metric("ioloop.stall_ms", lag * 1000.0)
        self._loop.call_later(_WATCH_TICK_S, self._stall_tick, now)

    @classmethod
    def default(cls) -> "IoLoop":
        with cls._default_lock:
            if cls._default is None or not cls._default._thread.is_alive():
                cls._default = cls()
            return cls._default

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.call_soon(self._started.set)
        self._loop.run_forever()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop

    def run_coro(self, coro: Coroutine[Any, Any, T]) -> "concurrent.futures.Future[T]":
        """Submit a coroutine from any thread; returns a concurrent future."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def run_sync(self, coro: Coroutine[Any, Any, T], timeout: Optional[float] = None) -> T:
        if threading.current_thread() is self._thread:
            raise RuntimeError("run_sync called from the IO thread (would deadlock)")
        return self.run_coro(coro).result(timeout)

    def call_soon(self, fn, *args) -> None:
        self._loop.call_soon_threadsafe(fn, *args)

    def stop(self) -> None:
        def _shutdown():
            for task in asyncio.all_tasks(self._loop):
                task.cancel()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=5.0)
