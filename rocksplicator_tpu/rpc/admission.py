"""Per-tenant admission control at the server edge (round 19 layer 3).

Reference: the production cluster fronts many product surfaces on the
same shard fleet; one misbehaving caller retrying at 10x its share sets
every caller's p99.9 unless admission is tenant-aware. The reference
delegates this to service-mesh quotas; here it lives at the one place
every request already passes — ``RpcServer._dispatch`` — keyed by the
``tenant`` frame-header tag (rpc/deadline.TENANT_KEY).

Machinery: the round-16 ``IoBudget`` token-bucket shape (refill =
elapsed x rate, clamped to capacity) generalized to two meters per
tenant — ops/s and bytes/s — under a **weighted-fair default tier**:
every tenant gets an EQUAL bucket of the configured per-tenant rate,
so a noisy tenant exhausts only its own bucket and gets a typed
``RETRY_LATER`` (+ jittered retry-after hint) while well-behaved
tenants keep admitting. The server meters only tenant-TAGGED requests
— internal plane traffic (replication pulls, coordinator RPCs) carries
no tag and must never be shed by a product tenant's bucket; direct
``admit(None)`` callers share the ``default`` bucket.

Config (env, read once per singleton — ``reset_for_test`` re-reads):

- ``RSTPU_TENANT_OPS``    per-tenant ops/second (0/unset = unlimited)
- ``RSTPU_TENANT_BYTES``  per-tenant bytes/second (0/unset = unlimited)

Determinism: refill math runs off an injectable ``clock`` (tests drive
a fake clock for exact token accounting) and the retry-after jitter
draws from ``seeded_rng("RSTPU_RETRY_SEED")`` — same seed, same hint
schedule, which is what keeps chaos overload runs reproducible.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..utils.retry_policy import seeded_rng

__all__ = ["TokenBucket", "TenantAdmission", "sanitize_tenant"]

_TENANT_RE = re.compile(r"[^A-Za-z0-9_.-]")


def sanitize_tenant(tenant: Optional[str]) -> str:
    """Clamp an untrusted wire tag into a metrics-safe tag value (the
    tenant name becomes a Prometheus label on /metrics — a hostile tag
    must not be able to break the exposition grammar or explode label
    cardinality via length)."""
    if not tenant:
        return "default"
    return _TENANT_RE.sub("_", str(tenant))[:32] or "default"


class TokenBucket:
    """The IoBudget refill shape with a "when could this admit" answer:
    ``try_take`` returns 0.0 on success, else the seconds until ``n``
    tokens will have refilled — the raw material for the RETRY_LATER
    retry-after hint. ``debit`` charges costs only known after the
    work ran (response bytes), allowing the balance to go negative so
    an oversized response is paid off by future refill before the
    tenant admits again."""

    def __init__(self, rate: float, capacity: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._rate = float(rate)
        # default burst = one second of rate (same choice as IoBudget)
        self._capacity = float(capacity) if capacity is not None \
            else max(self._rate, 1.0)
        self._tokens = self._capacity
        self._clock = clock
        self._refilled = clock()

    def _refill_locked(self) -> None:
        now = self._clock()
        elapsed = now - self._refilled
        if elapsed > 0:
            self._tokens = min(self._capacity,
                               self._tokens + elapsed * self._rate)
        self._refilled = now

    def try_take(self, n: float = 1.0) -> float:
        """0.0 = admitted (tokens taken); >0 = seconds until ``n``
        tokens exist (nothing taken)."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            if self._rate <= 0.0:
                return 1.0
            return (n - self._tokens) / self._rate

    def debit(self, n: float) -> None:
        """Post-hoc charge; may drive the balance negative."""
        with self._lock:
            self._refill_locked()
            self._tokens -= n

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens


class TenantAdmission:
    """Per-tenant (ops, bytes) buckets behind the server admission
    edge. Unconfigured (both rates 0) it admits everything at zero
    cost — the killswitch-off and default-deployment path."""

    _instance: Optional["TenantAdmission"] = None
    _instance_lock = threading.Lock()

    def __init__(self, ops_per_sec: float = 0.0,
                 bytes_per_sec: float = 0.0,
                 clock: Callable[[], float] = time.monotonic,
                 rng=None):
        self._ops_rate = max(0.0, float(ops_per_sec))
        self._bytes_rate = max(0.0, float(bytes_per_sec))
        self._clock = clock
        self._rng = rng if rng is not None else seeded_rng()
        self._lock = threading.Lock()
        self._buckets: Dict[str, Tuple[Optional[TokenBucket],
                                       Optional[TokenBucket]]] = {}
        # tenant -> (ops_rate, bytes_rate) runtime overrides (the
        # set_tenant_quota admin RPC): take effect on the NEXT admit —
        # no restart, no env round trip
        self._overrides: Dict[str, Tuple[float, float]] = {}

    # -- singleton wiring --------------------------------------------------

    @classmethod
    def get(cls) -> "TenantAdmission":
        inst = cls._instance
        if inst is None:
            with cls._instance_lock:
                inst = cls._instance
                if inst is None:
                    inst = cls.from_env()
                    cls._instance = inst
        return inst

    @classmethod
    def from_env(cls) -> "TenantAdmission":
        import os

        def _rate(name: str) -> float:
            try:
                return float(os.environ.get(name, "") or 0.0)
            except ValueError:
                return 0.0

        return cls(ops_per_sec=_rate("RSTPU_TENANT_OPS"),
                   bytes_per_sec=_rate("RSTPU_TENANT_BYTES"))

    @classmethod
    def reset_for_test(cls) -> None:
        """Drop the singleton so the next get() re-reads the env (tests
        and per-arm bench children flip quotas via env)."""
        with cls._instance_lock:
            cls._instance = None

    # -- admission ---------------------------------------------------------

    @property
    def configured(self) -> bool:
        if self._ops_rate > 0.0 or self._bytes_rate > 0.0:
            return True
        with self._lock:
            return any(o > 0.0 or b > 0.0
                       for o, b in self._overrides.values())

    def set_quota(self, tenant: Optional[str], ops_per_sec: float,
                  bytes_per_sec: float) -> None:
        """Runtime quota override for one tenant (the set_tenant_quota
        admin RPC). The tenant's buckets are rebuilt at the new rates on
        its next admission — a RAISE takes effect without restart and
        without waiting out a starved bucket's refill horizon. Zero/zero
        clears the override back to the env-configured default tier."""
        name = sanitize_tenant(tenant)
        ops = max(0.0, float(ops_per_sec))
        byt = max(0.0, float(bytes_per_sec))
        with self._lock:
            if ops <= 0.0 and byt <= 0.0:
                self._overrides.pop(name, None)
            else:
                self._overrides[name] = (ops, byt)
            # drop the live buckets so _buckets_for rebuilds at the new
            # rates (keeping them would pin the old refill rate — and a
            # raised tenant would stay starved behind its old horizon)
            self._buckets.pop(name, None)

    def quota_for(self, tenant: Optional[str]) -> Tuple[float, float]:
        """(ops_rate, bytes_rate) currently in force for a tenant."""
        name = sanitize_tenant(tenant)
        with self._lock:
            return self._overrides.get(
                name, (self._ops_rate, self._bytes_rate))

    def _buckets_for(self, tenant: str) -> Tuple[Optional[TokenBucket],
                                                 Optional[TokenBucket]]:
        with self._lock:
            pair = self._buckets.get(tenant)
            if pair is None:
                # equal per-tenant buckets = the weighted-fair default
                # tier (every tenant weight 1); created lazily on first
                # sight so the tenant universe never needs declaring.
                # A runtime override (set_quota) replaces this tenant's
                # default rates.
                ops_rate, bytes_rate = self._overrides.get(
                    tenant, (self._ops_rate, self._bytes_rate))
                ops = TokenBucket(ops_rate, clock=self._clock) \
                    if ops_rate > 0 else None
                byt = TokenBucket(bytes_rate, clock=self._clock) \
                    if bytes_rate > 0 else None
                pair = (ops, byt)
                self._buckets[tenant] = pair
            return pair

    def admit(self, tenant: Optional[str],
              cost_bytes: int = 0) -> Tuple[bool, float]:
        """(admitted, retry_after_ms). Charges one op + the REQUEST
        bytes up front; response bytes are debited post-hoc via
        :meth:`debit_bytes`. The hint is the bucket's exact refill
        horizon plus up to +25% jitter so a shed cohort doesn't
        re-arrive in lockstep."""
        if not self.configured:
            return True, 0.0
        name = sanitize_tenant(tenant)
        ops, byt = self._buckets_for(name)
        wait_s = 0.0
        if ops is not None:
            wait_s = max(wait_s, ops.try_take(1.0))
        if wait_s == 0.0 and byt is not None and cost_bytes > 0:
            w = byt.try_take(float(cost_bytes))
            if w > 0.0 and ops is not None:
                # bytes bucket refused after the op token was taken:
                # refund the op so a shed costs the tenant nothing
                ops.debit(-1.0)
            wait_s = max(wait_s, w)
        if wait_s == 0.0:
            return True, 0.0
        jitter = 1.0 + 0.25 * self._rng.random()
        return False, wait_s * 1e3 * jitter

    def debit_bytes(self, tenant: Optional[str], nbytes: int) -> None:
        """Post-hoc response-bytes charge (size unknown at admission)."""
        if not self.configured or nbytes <= 0:
            return
        _ops, byt = self._buckets_for(sanitize_tenant(tenant))
        if byt is not None:
            byt.debit(float(nbytes))
