"""RpcServer: asyncio server dispatching typed method calls.

Reference: the fbthrift ThriftServer hosting e.g. the ``Replicator`` service
(rocksdb_replicator/rocksdb_replicator.cpp:46-87) and ``Admin`` service.
Handlers are objects exposing ``async def handle_<method>(self, **args)``;
raising RpcApplicationError maps to a typed error frame (thrift exception
equivalent). CPU-bound work should be pushed to an executor by the handler.

The byte layer is pluggable (transport.py): the server always binds its
TCP port (the port is the cluster-wide identity — shard maps and
upstream addresses carry it), and under the ``RSTPU_TRANSPORT`` policy
ALSO serves the derived fast-path endpoints for that port — the
per-port unix socket (``uds``) and/or the in-process loopback key
(``loopback``) — so clients resolving the same (host, port) address
under the same policy land on the fast path while stray tcp clients
still work. Explicit extra endpoints may be passed as URL strings.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from .admission import TenantAdmission, sanitize_tenant
from .deadline import (DEADLINE_EXCEEDED, DEADLINE_KEY, RETRY_LATER,
                       TENANT_KEY, Deadline, armor_enabled, request_scope)
from .errors import RpcApplicationError, RpcTransportConfigError
from .ioloop import IoLoop
from .serde import decode_message, encode_message
from .transport import (
    Connection,
    Endpoint,
    TcpConnection,
    get_transport,
    parse_endpoint,
    transport_policy,
    uds_path_for_port,
)
from ..observability.context import TRACE_KEY
from ..observability.span import start_span
from ..testing import failpoints as fp
from ..utils.stats import Stats, tagged

log = logging.getLogger(__name__)


def _request_cost_bytes(args: Dict[str, Any]) -> int:
    """Admission byte-cost of a request: the payload-bearing argument
    sizes (a write's raw_batch, a multi_get's key list). One shallow
    pass — this runs on every metered dispatch."""
    cost = 0
    for v in args.values():
        if isinstance(v, (bytes, bytearray, memoryview)):
            cost += len(v)
        elif isinstance(v, str):
            cost += len(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, (bytes, bytearray, memoryview, str)):
                    cost += len(item)
    return cost


class RpcServer:
    """Serves one or more handler objects on a TCP port (plus any
    policy-derived or explicit fast-path endpoints).

    Multiple handlers may be stacked (e.g. an application handler extending
    the Admin service — counter.thrift's ``service Counter extends Admin``);
    method lookup walks them in registration order.
    """

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 ioloop: Optional[IoLoop] = None, ssl_manager=None,
                 endpoints: Optional[List[str]] = None):
        self._host = host
        self._port = port
        self._ioloop = ioloop or IoLoop.default()
        self._handlers: list = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._extra_endpoints = list(endpoints or [])
        self._extra_listeners: list = []
        self._ready = threading.Event()
        # connection task -> its in-flight dispatch-task set (one structure
        # serves both teardown cancellation and graceful drain)
        self._connections: dict = {}
        self._draining = False
        # TLS: an SslContextManager (utils/ssl_context_manager) — the
        # SAME context object is handed to asyncio once; cert refreshes
        # reload into it, so new handshakes pick up rotated certs.
        # _ssl_claimed tracks whether THIS server currently holds a
        # refresh-thread claim (managers are shared; an unpaired stop()
        # must not release someone else's claim).
        self._ssl_manager = ssl_manager
        self._ssl_claimed = False

    def add_handler(self, handler: object) -> None:
        self._handlers.append(handler)

    @property
    def port(self) -> int:
        return self._port

    def serving_endpoints(self) -> List[str]:
        """Every endpoint this server currently accepts on (tcp first)."""
        eps = [f"tcp://{self._host}:{self._port}"]
        for lst in self._extra_listeners:
            if getattr(lst, "path", None):
                eps.append(f"uds://{lst.path}")
            elif getattr(lst, "key", None):
                eps.append(f"loopback://{lst.key}")
        return eps

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Start serving (callable from any thread); blocks until bound."""
        try:
            self._ioloop.run_sync(self._start_async())
        except Exception:
            # a failed start has no stop() to pair with: drop this
            # server's refresh-thread claim here (outside the loop,
            # mirroring stop())
            if self._ssl_manager is not None and self._ssl_claimed:
                self._ssl_claimed = False
                self._ssl_manager.release_auto_refresh()
            raise

    async def _start_async(self) -> None:
        self._draining = False  # a restarted server serves again
        ssl_ctx = None
        if self._ssl_manager is not None:
            ssl_ctx = self._ssl_manager.get()
        self._server = await asyncio.start_server(
            self._on_tcp_connection, self._host, self._port, ssl=ssl_ctx,
        )
        if self._ssl_manager is not None and not self._ssl_claimed:
            # claim the refresh thread only for a server that actually
            # bound (a failed bind has no stop() to pair the release);
            # the background thread keeps rotated certs flowing into the
            # pinned context — servers call get() only at bind time
            self._ssl_manager.ensure_auto_refresh()
            self._ssl_claimed = True
        self._port = self._server.sockets[0].getsockname()[1]
        try:
            await self._start_extra_listeners()
        except Exception:
            # a half-started server must not keep accepting: the tcp
            # listener is already bound (and some extras may be up) when
            # an extra listener fails — close them before propagating so
            # start() raising leaves nothing serving
            self._server.close()
            self._server = None
            for listener in self._extra_listeners:
                listener.close()
            self._extra_listeners.clear()
            raise
        self._ready.set()

    async def _start_extra_listeners(self) -> None:
        """Fast-path listeners: the policy-derived endpoints for this
        port plus any explicit endpoint URLs. TLS pins tcp — a TLS
        server never exposes a plaintext side channel."""
        eps: List[Endpoint] = []
        if self._ssl_manager is not None:
            if self._extra_endpoints:
                # refuse loudly rather than silently dropping a listener
                # the operator asked for: a TLS server must not expose a
                # plaintext side channel, and a config accepted-but-
                # ignored would read as the fast path being up
                raise RpcTransportConfigError(
                    "TLS requires the tcp transport: explicit extra "
                    f"endpoints {self._extra_endpoints!r} cannot be "
                    "served by a TLS server")
        else:
            policy = transport_policy()
            if policy == "uds":
                eps.append(Endpoint(
                    "uds", path=uds_path_for_port(self._port)))
            elif policy == "loopback":
                eps.append(Endpoint("loopback", key=str(self._port)))
            eps.extend(parse_endpoint(u) for u in self._extra_endpoints)
        for ep in eps:
            listener = await get_transport(ep.scheme).accept(
                ep, self._serve_connection)
            self._extra_listeners.append(listener)

    def stop(self, drain_timeout: float = 0.0) -> None:
        """Stop serving. ``drain_timeout`` > 0 gives in-flight requests
        that long to complete before connections are cancelled (the
        reference's graceful-shutdown contract: stop accepting, drain,
        then tear down — common/tests/graceful_shutdown_test.cpp)."""
        try:
            self._ioloop.run_sync(
                self._stop_async(drain_timeout), timeout=drain_timeout + 5.0
            )
        except Exception:
            pass
        if self._ssl_manager is not None and self._ssl_claimed:
            # drop this server's claim on the refresh thread (refcounted:
            # the manager may be shared with other servers/pools; the
            # thread stops when the last user releases). Only if THIS
            # server holds a claim — double stop() or stop() without
            # start() must not release someone else's.
            self._ssl_claimed = False
            self._ssl_manager.release_auto_refresh()

    async def _stop_async(self, drain_timeout: float = 0.0) -> None:
        # Stop accepting new connections AND new work: frames arriving on
        # existing connections during the drain get a typed SHUTDOWN error
        # instead of a handler dispatch (a busy client must not defeat the
        # drain window).
        self._draining = True
        if self._server is not None:
            self._server.close()
        for listener in self._extra_listeners:
            listener.close()
        if drain_timeout > 0:
            deadline = asyncio.get_running_loop().time() + drain_timeout
            while (
                any(self._connections.values())
                and asyncio.get_running_loop().time() < deadline
            ):
                await asyncio.sleep(0.02)
        # Cancel remaining connections before wait_closed(): since Python
        # 3.12 wait_closed() also waits for connection handlers to finish,
        # and ours loop until cancelled.
        for task in list(self._connections):
            if task is not None:
                task.cancel()
        for listener in self._extra_listeners:
            await listener.wait_closed()
        self._extra_listeners = []
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    # -- connection handling ---------------------------------------------

    async def _on_tcp_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if self._ssl_manager is not None:
            # role binding: a connecting peer presenting a cert must hold
            # a CLIENT cert (utils/ssl_context_manager.check_peer_role)
            from ..utils.ssl_context_manager import (
                PeerRoleError, check_peer_role)

            try:
                check_peer_role(
                    writer.get_extra_info("ssl_object"), "client")
            except PeerRoleError as e:
                log.warning("rejecting connection: %s", e)
                writer.close()
                return
        await self._serve_connection(TcpConnection(reader, writer))

    # methods a peer's best-effort ``cancel`` frame may abort mid-flight:
    # idempotent reads only — cancelling a write task could leave the
    # commit half-acked (the client-side hedger only hedges reads, but
    # the wire frame is untrusted input and must not widen that contract)
    _CANCELLABLE = frozenset({"read"})

    async def _serve_connection(self, conn: Connection) -> None:
        """Transport-agnostic per-connection serve loop (every transport's
        accept path funnels here)."""
        task = asyncio.current_task()
        inflight: set = set()
        # req_id -> (dispatch task, method) for cancel-frame lookup
        by_id: Dict[Any, tuple] = {}
        self._connections[task] = inflight
        loop = asyncio.get_running_loop()
        try:
            while True:
                frames = await conn.recv_frames()
                # one receipt stamp per batch: queue wait measured in
                # _dispatch is (dispatch start - receipt), i.e. the
                # event-loop backlog a request sat behind — the signal
                # the deadline check charges against the budget
                recv_ts = loop.time()
                for header, payload in frames:
                    msg = decode_message(header, payload)
                    if "cancel" in msg and "method" not in msg:
                        # control frame, never replied to: abort the
                        # matching in-flight dispatch if it is still
                        # running AND its method is cancellable
                        entry = by_id.get(msg.get("cancel"))
                        if entry is not None:
                            t, m = entry
                            if m in self._CANCELLABLE and not t.done():
                                t.cancel()
                                Stats.get().incr(
                                    tagged("rpc.cancelled", method=m))
                        continue
                    # Each request runs as its own task so slow handlers
                    # (e.g. long-poll replicate) don't block the
                    # connection.
                    t = asyncio.ensure_future(
                        self._dispatch(msg, conn, recv_ts))
                    inflight.add(t)
                    req_id = msg.get("id")
                    if req_id is not None:
                        by_id[req_id] = (t, msg.get("method", ""))
                        t.add_done_callback(
                            lambda _f, rid=req_id: by_id.pop(rid, None))
                    t.add_done_callback(inflight.discard)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("rpc server connection error")
        finally:
            for t in inflight:
                t.cancel()
            self._connections.pop(task, None)
            conn.close()
            try:
                await conn.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _dispatch(self, msg: Dict[str, Any], conn: Connection,
                        recv_ts: Optional[float] = None) -> None:
        req_id = msg.get("id")
        method = msg.get("method", "")
        args = msg.get("args") or {}
        stats = Stats.get()
        stats.incr(f"rpc.{method}.received")
        # Round-19 tail armor (killswitch RSTPU_TAIL_ARMOR=0 restores
        # the bare pre-armor dispatch): measure the event-loop backlog
        # this request sat behind, then run the admission edge —
        # deadline-vs-queue-wait shedding and per-tenant token buckets
        # — BEFORE the handler, so dead or over-quota work is never
        # computed.
        armored = armor_enabled()
        tenant = msg.get(TENANT_KEY) if armored else None
        deadline: Optional[Deadline] = None
        queue_wait_ms = 0.0
        if armored and recv_ts is not None:
            queue_wait_ms = max(
                0.0,
                (asyncio.get_running_loop().time() - recv_ts) * 1e3)
        # Reattach the caller's trace context (injected by RpcClient.call
        # into the JSON frame header): the server span joins the caller's
        # trace; without a header it rolls local head sampling. This task
        # was just created, so the contextvar set inside start_span is
        # scoped to this request.
        with start_span("rpc.server", remote=msg.get(TRACE_KEY),
                        method=method) as sp:
            t0 = time.monotonic()
            try:
                if self._draining:
                    raise RpcApplicationError("SHUTDOWN", "server draining")
                if armored:
                    deadline = await self._admission_check(
                        method, msg, tenant, queue_wait_ms, stats)
                fn = self._find_handler(method)
                with request_scope(deadline=deadline, tenant=tenant):
                    result = await fn(**args)
                if deadline is not None and deadline.expired:
                    # the budget ran out while the handler was working:
                    # nobody is waiting for this reply — skip the
                    # serialization and ship the typed error instead
                    stats.incr(tagged("rpc.deadline_shed", method=method,
                                      stage="post"))
                    raise RpcApplicationError(
                        DEADLINE_EXCEEDED,
                        f"{method}: deadline expired during service "
                        f"({-deadline.remaining_ms():.1f}ms ago)")
                reply = {"id": req_id, "ok": True, "result": result}
                stats.incr(f"rpc.{method}.success")
                if tenant is not None:
                    tname = sanitize_tenant(tenant)
                    stats.incr(tagged("rpc.tenant_served", tenant=tname))
                    stats.add_metric(tagged("rpc.tenant_ms", tenant=tname),
                                     (time.monotonic() - t0) * 1e3)
            except RpcApplicationError as e:
                reply = {
                    "id": req_id,
                    "ok": False,
                    "error": {"code": e.code, "message": e.message, "data": e.data},
                }
                sp.annotate(error_code=e.code)
                stats.incr(f"rpc.{method}.app_error")
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.exception("handler %s failed", method)
                reply = {
                    "id": req_id,
                    "ok": False,
                    "error": {"code": "INTERNAL", "message": repr(e), "data": {}},
                }
                sp.annotate(error_code="INTERNAL")
                stats.incr(f"rpc.{method}.internal_error")
            header, chunks = encode_message(reply)
            if armored and tenant is not None:
                # response bytes are only known after encode: post-hoc
                # debit lets an oversized scan answer push the tenant's
                # byte bucket negative, deferring its next admission
                TenantAdmission.get().debit_bytes(
                    tenant, len(header) + sum(len(c) for c in chunks))
            try:
                # replies from concurrent dispatches coalesce in the
                # transport (no per-connection write lock needed)
                await conn.send_frames([(header, chunks)])
            except (ConnectionError, OSError):
                pass

    async def _admission_check(self, method: str, msg: Dict[str, Any],
                         tenant: Optional[str], queue_wait_ms: float,
                         stats) -> Optional[Deadline]:
        """The round-19 admission edge, run before handler dispatch.
        Raises typed errors (DEADLINE_EXCEEDED / RETRY_LATER) to shed;
        returns the re-anchored request Deadline (or None) to scope
        around the handler. Order matters: the deadline verdict first —
        a dead request must not spend tenant tokens."""
        deadline: Optional[Deadline] = None
        budget_ms = msg.get(DEADLINE_KEY)
        if budget_ms is not None:
            stats.add_metric("rpc.queue_wait_ms", queue_wait_ms)
            forced_expired = False
            try:
                await fp.async_hit("rpc.deadline.check")
            except fp.FailpointError:
                # an armed seam forces the expired verdict — chaos
                # drives the shed path itself, not an INTERNAL error
                forced_expired = True
            remaining = float(budget_ms) - queue_wait_ms
            if forced_expired or remaining <= 0.0:
                stats.incr(tagged("rpc.deadline_shed", method=method))
                raise RpcApplicationError(
                    DEADLINE_EXCEEDED,
                    f"{method}: deadline spent before dispatch (budget "
                    f"{float(budget_ms):.1f}ms, queue "
                    f"{queue_wait_ms:.1f}ms)")
            if queue_wait_ms > remaining:
                # backlog trend: we already queued longer than the whole
                # budget that is left, so service + response would land
                # dead — shed EARLY with a hint sized to the measured
                # wait (the jittered consumption lives in retry_policy)
                stats.incr(tagged("rpc.retry_later", method=method,
                                  reason="backlog"))
                raise RpcApplicationError(
                    RETRY_LATER,
                    f"{method}: queued {queue_wait_ms:.1f}ms with only "
                    f"{remaining:.1f}ms of budget left",
                    {"retry_after_ms": round(queue_wait_ms, 1)})
            deadline = Deadline.after_ms(remaining)
        if tenant is not None:
            # only TAGGED requests are metered: internal plane traffic
            # (replication pulls, coordinator RPCs) carries no tenant
            # and must never be shed by a product tenant's bucket
            adm = TenantAdmission.get()
            forced_shed = False
            try:
                # armed even with no quotas configured: chaos forces the
                # quota-shed path without env manipulation
                await fp.async_hit("admission.shed")
            except fp.FailpointError:
                forced_shed = True
            if adm.configured or forced_shed:
                ok, retry_after_ms = (
                    adm.admit(tenant,
                              _request_cost_bytes(msg.get("args") or {}))
                    if adm.configured else (True, None))
                if forced_shed or not ok:
                    tname = sanitize_tenant(tenant)
                    stats.incr(tagged("rpc.tenant_shed", tenant=tname,
                                      reason="quota"))
                    raise RpcApplicationError(
                        RETRY_LATER,
                        f"{method}: tenant {tname} over quota",
                        {"retry_after_ms":
                         round(retry_after_ms or 10.0, 1)})
        return deadline

    def _find_handler(self, method: str):
        for handler in self._handlers:
            fn = getattr(handler, f"handle_{method}", None)
            if fn is not None:
                return fn
        raise RpcApplicationError("NO_SUCH_METHOD", method)
