"""Typed async RPC — the fbthrift-equivalent transport layer.

Reference: the fbthrift header protocol over TCP with zero-copy IOBuf
payloads (rocksdb_replicator/thrift/replicator.thrift:44-49), client pools
with per-connection health tracking (common/thrift_client_pool.h), and a
shard-map-driven router (common/thrift_router.h).

TPU-first design: a single asyncio event loop in a dedicated IO thread
drives all connections (vs. the reference's N IO threads × EventBase); the
wire format is a length-prefixed frame with a JSON header and a raw binary
payload region so WAL update bytes travel without copies or base64.
"""

from .framing import FrameBuffer, FrameReader, write_frame
from .serde import encode_message, decode_message
from .errors import (RpcError, RpcTimeout, RpcConnectionError,
                     RpcApplicationError, RpcTransportConfigError)
from .ioloop import IoLoop
from .transport import (Endpoint, Connection, Transport, get_transport,
                        parse_endpoint, resolve_endpoint, transport_policy,
                        uds_path_for_port)
from .client import RpcClient
from .client_pool import RpcClientPool
from .server import RpcServer
from .router import RpcRouter, ClusterLayout, Role, Quantity

__all__ = [
    "FrameBuffer", "FrameReader", "write_frame",
    "encode_message", "decode_message",
    "RpcError", "RpcTimeout", "RpcConnectionError", "RpcApplicationError",
    "RpcTransportConfigError",
    "Endpoint", "Connection", "Transport", "get_transport",
    "parse_endpoint", "resolve_endpoint", "transport_policy",
    "uds_path_for_port",
    "IoLoop", "RpcClient", "RpcClientPool", "RpcServer",
    "RpcRouter", "ClusterLayout", "Role", "Quantity",
]
