"""Shard-map-driven request router.

Reference: common/thrift_router.h:86-534 — parses a JSON shard map
(format per thrift_router.h:536-566 / ConfigGenerator.java:
``{segment: {num_shards: N, "ip:port:az": ["00042:M", "00043:S", ...]}}``)
into a ``ClusterLayout``; ``getClientsFor(segment, role, quantity, shard)``
applies role filtering, master preference, AZ-locality sort, and a
deterministic rotation hash (thrift_router.h:384-455) so equally-good
replicas share load. The map file is hot-reloaded via the file watcher.
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import json
import logging
import os
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..testing import failpoints as fp
from ..utils.file_watcher import FileWatcher
from ..utils.stats import Stats, tagged
from ..utils.timer import Timer
from .client_pool import RpcClientPool
from .deadline import armor_enabled
from .errors import RpcApplicationError, RpcConnectionError, RpcTimeout

log = logging.getLogger(__name__)

# read-RPC application errors that mean "try the next candidate" rather
# than "the request is bad": a follower too stale for the bound, a
# replica on a deposed lineage, a non-leader asked a leader-only
# question, a replica that hasn't registered the db yet (mid-repoint),
# or one whose wrapper doesn't persist locally (CDC observer)
_READ_BOUNCE_CODES = frozenset(
    ("STALE_READ", "STALE_EPOCH", "NOT_LEADER", "SOURCE_NOT_FOUND",
     "READS_UNSUPPORTED"))
# write-RPC errors that mean "try the next mapped leader": the stale
# shard-map cases during a handoff — the old leader either demoted
# already (NOT_LEADER), is fenced by the new epoch (STALE_EPOCH), or
# dropped the db mid-repoint (SOURCE_NOT_FOUND)
_WRITE_BOUNCE_CODES = frozenset(
    ("NOT_LEADER", "STALE_EPOCH", "SOURCE_NOT_FOUND"))

# ops eligible for the round-19 tail-shaving backup request. scan is
# deliberately excluded: a hedged scan doubles an UNBOUNDED amount of
# engine work for one credit, and split-parent scans already fan out
_HEDGEABLE_OPS = frozenset(("get", "multi_get"))


def _retrieve_exception(task: "asyncio.Task") -> None:
    """Done-callback for hedge arms: a loser that errors after the
    winner returned must not log "exception was never retrieved"."""
    if not task.cancelled():
        task.exception()


class Role(enum.Enum):
    LEADER = "LEADER"       # reference: MASTER
    FOLLOWER = "FOLLOWER"   # reference: SLAVE
    ANY = "ANY"


class Quantity(enum.Enum):
    ONE = 1
    TWO = 2
    ALL = -1


@dataclass(frozen=True)
class ReadPolicy:
    """Read preference for routed reads (round 13).

    Reference mapping: ThriftRouter's role filter + AZ-locality sort
    (thrift_router.h:384-455) — the reference serves reads from SLAVE
    replicas in the local AZ by asking ``getClientsFor(..., SLAVE)``;
    here the policy also carries the staleness bound the serving replica
    must prove (``max_lag``, in sequence numbers).

    - ``leader_only()``      — only the LEADER serves (the implicit
      pre-round-13 behavior: read throughput caps at leader capacity);
    - ``follower_ok(lag)``   — followers are ACCEPTABLE: every replica
      (followers AND the leader) joins one per-request-rotated group,
      so read throughput scales with replica count; a FOLLOWER serves
      only when its applied position is within ``lag`` seqs of the
      leader's committed sequence, and a stale/deposed replica bounces
      the read down the chain — which always contains the leader;
    - ``nearest(lag)``       — AZ-locality first regardless of role
      (the reference's local-replica preference), same bound semantics.
    """

    kind: str = "leader_only"
    max_lag: Optional[int] = None

    @classmethod
    def leader_only(cls) -> "ReadPolicy":
        return cls("leader_only", None)

    @classmethod
    def follower_ok(cls, max_lag: int) -> "ReadPolicy":
        return cls("follower_ok", int(max_lag))

    @classmethod
    def nearest(cls, max_lag: Optional[int] = None) -> "ReadPolicy":
        return cls("nearest", None if max_lag is None else int(max_lag))


@dataclass(frozen=True)
class Host:
    ip: str
    port: int
    az: str
    # Replication-plane port. The reference runs its replicator on a fixed
    # port (9091) next to the service port (9090); here the convention is
    # service port + 1 unless the shard map's 4th host-key field overrides.
    repl_port: int = 0

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.ip, self.port)

    @property
    def repl_addr(self) -> Tuple[str, int]:
        return (self.ip, self.repl_port or self.port + 1)


@dataclass
class _Segment:
    num_shards: int = 0
    # shard -> [(host, role)]
    shard_to_hosts: Dict[int, List[Tuple[Host, Role]]] = field(default_factory=dict)
    # hot-shard range splits (round 20): parent shard -> (split_key,
    # low child, high child). num_shards stays the HASH width — keys
    # still hash to the parent slot; resolve_shard chases these records
    # (transitively: children can split again) to the serving child.
    splits: Dict[int, Tuple[bytes, int, int]] = field(default_factory=dict)


class ClusterLayout:
    def __init__(self) -> None:
        self.segments: Dict[str, _Segment] = {}

    @classmethod
    def parse(cls, content: bytes) -> "ClusterLayout":
        """Parse the shard-map JSON (reference thrift_router.h:536-566)."""
        layout = cls()
        raw = json.loads(content.decode("utf-8")) if content.strip() else {}
        if not isinstance(raw, dict):
            raise ValueError("shard map must be a JSON object")
        for segment, body in raw.items():
            if not isinstance(body, dict):
                raise ValueError(f"segment {segment}: body must be an object")
            seg = _Segment()
            for key, value in body.items():
                if key in ("num_shards", "num_leaf_segments"):
                    seg.num_shards = int(value)
                    continue
                if key == "__splits__":
                    # {"<parent>": {"split_key": hex, "low": n, "high": n}}
                    for parent, rec in dict(value).items():
                        seg.splits[int(parent)] = (
                            bytes.fromhex(str(rec["split_key"])),
                            int(rec["low"]), int(rec["high"]))
                    continue
                parts = key.split(":")
                if len(parts) < 2:
                    raise ValueError(f"bad host key: {key!r}")
                ip, port = parts[0], int(parts[1])
                az = parts[2] if len(parts) > 2 else ""
                repl_port = int(parts[3]) if len(parts) > 3 else 0
                host = Host(ip, port, az, repl_port)
                for shard_spec in value:
                    shard_str, _, role_str = str(shard_spec).partition(":")
                    shard = int(shard_str)
                    role = {
                        "M": Role.LEADER,
                        "S": Role.FOLLOWER,
                        "": Role.ANY,
                    }.get(role_str, Role.ANY)
                    seg.shard_to_hosts.setdefault(shard, []).append((host, role))
            layout.segments[segment] = seg
        return layout


class RpcRouter:
    """Routes requests by (segment, shard, role)."""

    def __init__(
        self,
        local_az: str = "",
        shard_map_path: Optional[str] = None,
        pool: Optional[RpcClientPool] = None,
        local_group_prefix_len: int = 0,
    ):
        self._local_az = local_az
        self._layout = ClusterLayout()
        self._pool = pool or RpcClientPool()
        # Locality tier between same-AZ and remote: hosts whose AZ shares
        # the first N chars with ours (e.g. "us-east-1a"/"us-east-1b" share
        # 9) — the reference's local-group-prefix sort.
        self._local_group_prefix_len = local_group_prefix_len
        self._shard_map_path = shard_map_path
        # per-request rotation for read spreading across equally-good
        # followers (itertools.count is GIL-atomic enough for a counter)
        self._read_seq = itertools.count()
        self._stats = Stats.get()
        # Hedge budget (round 19): every eligible follower_ok read
        # earns RSTPU_HEDGE_PCT credit; firing one hedge spends 1.0 —
        # a hard ≤PCT extra-read cap so hedging cannot amplify the very
        # overload it defends against. The small cap bounds bursts
        # after an idle stretch. Loop-thread only, no lock needed.
        self._hedge_credit = 0.0
        self._hedge_credit_cap = 5.0
        if shard_map_path is not None:
            FileWatcher.instance().add_file(shard_map_path, self._on_map_content)

    def close(self) -> None:
        """Unregister the shard-map watcher (must be called for routers
        constructed with ``shard_map_path``)."""
        if self._shard_map_path is not None:
            FileWatcher.instance().remove_file(
                self._shard_map_path, self._on_map_content
            )
            self._shard_map_path = None

    # -- config -----------------------------------------------------------

    def _on_map_content(self, content: bytes) -> None:
        try:
            self._layout = ClusterLayout.parse(content)
        except (ValueError, KeyError) as e:
            log.error("invalid shard map, keeping previous: %s", e)

    def update_layout(self, layout: ClusterLayout) -> None:
        self._layout = layout

    @property
    def layout(self) -> ClusterLayout:
        return self._layout

    def num_shards(self, segment: str) -> int:
        seg = self._layout.segments.get(segment)
        return seg.num_shards if seg else 0

    # -- host selection ---------------------------------------------------

    def get_hosts_for(
        self,
        segment: str,
        shard: int,
        role: Role = Role.ANY,
        quantity: Quantity = Quantity.ONE,
    ) -> List[Host]:
        """Ordered candidate hosts for a shard.

        Selection mirrors thrift_router.h:384-455: filter by role (ANY
        prefers the leader first), sort by AZ locality, then rotate
        equally-local groups deterministically by shard hash.
        """
        seg = self._layout.segments.get(segment)
        if seg is None:
            return []
        entries = seg.shard_to_hosts.get(shard, [])
        if role is Role.ANY:
            candidates = sorted(
                entries, key=lambda hr: 0 if hr[1] is Role.LEADER else 1
            )
        else:
            candidates = [hr for hr in entries if hr[1] is role]

        def locality(hr: Tuple[Host, Role]) -> int:
            host = hr[0]
            if self._local_az and host.az == self._local_az:
                return 0
            n = self._local_group_prefix_len
            if (
                n > 0
                and self._local_az
                and host.az[:n] == self._local_az[:n]
            ):
                return 1
            return 2

        # Stable sort keeps the leader-first ordering within locality tiers;
        # rotation spreads load across equally-good candidates.
        rot = zlib.crc32(f"{segment}:{shard}".encode()) if candidates else 0
        groups: Dict[Tuple[int, int], List[Host]] = {}
        for hr in candidates:
            key = (locality(hr), 0 if hr[1] is Role.LEADER and role is Role.ANY else 1)
            groups.setdefault(key, []).append(hr[0])
        ordered: List[Host] = []
        for key in sorted(groups):
            group = groups[key]
            r = rot % len(group)
            ordered.extend(group[r:] + group[:r])

        if quantity is Quantity.ALL:
            return ordered
        return ordered[: quantity.value]

    async def get_clients_for(
        self,
        segment: str,
        shard: int,
        role: Role = Role.ANY,
        quantity: Quantity = Quantity.ONE,
    ):
        """Connected clients for the chosen hosts; skips bad hosts
        (reference: filterBadHosts)."""
        clients = []
        want = None if quantity is Quantity.ALL else quantity.value
        for host in self.get_hosts_for(segment, shard, role, Quantity.ALL):
            try:
                clients.append(await self._pool.get_client(host.ip, host.port))
            except RpcConnectionError:
                continue
            if want is not None and len(clients) >= want:
                break
        return clients

    # -- hot-shard range splits (round 20) --------------------------------

    def resolve_shard(self, segment: str, shard: int,
                      key: Optional[bytes]) -> int:
        """Slot → serving shard: chase the segment's split records by
        range (keys < split_key → low child, >= → high) transitively
        until an unsplit shard answers. A no-split segment returns the
        input shard unchanged — the pre-split hot path costs one dict
        miss."""
        seg = self._layout.segments.get(segment)
        if seg is None or not seg.splits or key is None:
            return shard
        k = bytes(key)
        while True:
            sp = seg.splits.get(shard)
            if sp is None:
                return shard
            split_key, low, high = sp
            shard = low if k < split_key else high

    async def _split_multi_get(self, segment: str, shard: int, keys,
                               policy, epoch, timeout: float):
        """multi_get across a split parent: partition keys by serving
        child, fan the per-child multi_gets out concurrently, stitch
        values back in the caller's key order. Response metadata (lag,
        epoch, role) is per-replica and meaningless across a fan-out —
        the stitched response carries one child's, values are exact."""
        groups: Dict[int, List[Tuple[int, bytes]]] = {}
        for i, k in enumerate(keys):
            child = self.resolve_shard(segment, shard, bytes(k))
            groups.setdefault(child, []).append((i, bytes(k)))
        ordered = sorted(groups.items())
        results = await asyncio.gather(*[
            self.read(segment, child, op="multi_get",
                      keys=[k for _i, k in items], policy=policy,
                      epoch=epoch, timeout=timeout)
            for child, items in ordered])
        values: List = [None] * len(keys)
        for (child, items), r in zip(ordered, results):
            got = (r or {}).get("values") or []
            for (i, _k), v in zip(items, got):
                values[i] = v
        resp = dict(results[-1] or {}) if results else {}
        resp["values"] = values
        return resp

    async def _split_scan(self, segment: str,
                          sp: Tuple[bytes, int, int], start, count,
                          policy, epoch, timeout: float):
        """Ordered scan across a split parent: low child first (rows
        truncated at the boundary — everything a full-copy child holds
        at or past its split key is pre-split garbage the range owns to
        the OTHER child), then continue into the high child from
        max(start, split_key). Nested splits recurse through read()."""
        split_key, low, high = sp
        want = 10 if count is None else max(1, int(count))
        out: List = []
        meta = None
        s = bytes(start) if start is not None else b""
        if s < split_key:
            r = await self.read(segment, low, op="scan",
                                start=(s or None), count=want,
                                policy=policy, epoch=epoch,
                                timeout=timeout)
            meta = r
            for row in (r or {}).get("values") or []:
                if bytes(row[0]) >= split_key:
                    break  # ordered: the rest is out-of-range garbage
                out.append(row)
        if len(out) < want:
            r = await self.read(segment, high, op="scan",
                                start=max(s, split_key),
                                count=want - len(out), policy=policy,
                                epoch=epoch, timeout=timeout)
            if meta is None:
                meta = r
            out.extend((r or {}).get("values") or [])
        resp = dict(meta or {})
        resp["values"] = out[:want]
        return resp

    # -- bounded-staleness reads (round 13) -------------------------------

    def read_pick(self, segment: str, shard: int,
                  policy: ReadPolicy) -> List[Host]:
        """Ordered read candidates for a shard under a read policy. The
        last entries are the bounce targets a stale/deposed replica
        falls back to (always ending at the leader when one is mapped)."""
        if policy.kind == "leader_only":
            return self.get_hosts_for(segment, shard, Role.LEADER,
                                      Quantity.ALL)
        if policy.kind == "nearest":
            # locality-ordered ANY (leader-first within each tier —
            # the reference's local-replica preference)
            return self.get_hosts_for(segment, shard, Role.ANY,
                                      Quantity.ALL)
        if policy.kind != "follower_ok":
            raise ValueError(f"unknown read policy: {policy.kind!r}")
        # follower_ok: followers are ACCEPTABLE, not exclusive — every
        # replica (followers AND leader) joins one rotated group so read
        # throughput scales with replica count, not follower count. The
        # rotation is per-REQUEST: get_hosts_for's shard-hash rotation
        # is deterministic per shard, which would pin every read for a
        # shard to one replica — the opposite of read scaling. A
        # replica that bounces (stale lag / deposed lineage) falls
        # through to the rest of the chain, which always contains the
        # leader (lag 0 by definition).
        followers = self.get_hosts_for(segment, shard, Role.FOLLOWER,
                                       Quantity.ALL)
        leaders = self.get_hosts_for(segment, shard, Role.LEADER,
                                     Quantity.ALL)
        group = followers + [h for h in leaders if h not in followers]
        if group:
            r = next(self._read_seq) % len(group)
            group = group[r:] + group[:r]
        return group

    async def read(
        self,
        segment: str,
        shard: int,
        op: str = "get",
        keys=None,
        start=None,
        count: Optional[int] = None,
        policy: Optional[ReadPolicy] = None,
        epoch: Optional[int] = None,
        timeout: float = 10.0,
    ):
        """Routed bounded-staleness read: try the policy's candidates in
        order over the replication plane's ``read`` RPC, bouncing on
        STALE_READ (lag bound exceeded/unverifiable) and STALE_EPOCH
        (deposed lineage) toward the leader. Connection errors AND
        timeouts skip to the next candidate (reference: filterBadHosts;
        reads are idempotent, so retrying a wedged replica's read on
        another host is safe — unlike the write path, which must not
        re-commit on a timeout)."""
        policy = policy or ReadPolicy.leader_only()
        seg = self._layout.segments.get(segment)
        if seg is not None and seg.splits:
            # slot → serving child before any host is picked: the
            # parent db no longer exists once a split activates
            if op == "get":
                k = (keys[0] if keys else None) \
                    if isinstance(keys, (list, tuple)) else keys
                if k is not None:
                    shard = self.resolve_shard(segment, shard, bytes(k))
            elif op == "multi_get" and keys:
                targets = {self.resolve_shard(segment, shard, bytes(k))
                           for k in keys}
                if len(targets) == 1:
                    shard = targets.pop()
                else:
                    return await self._split_multi_get(
                        segment, shard, keys, policy, epoch, timeout)
            elif op == "scan":
                sp = seg.splits.get(shard)
                if sp is not None:
                    return await self._split_scan(
                        segment, sp, start, count, policy, epoch,
                        timeout)
        await fp.async_hit("router.read_pick")
        hosts = self.read_pick(segment, shard, policy)
        if not hosts:
            raise RpcConnectionError(
                f"no read candidates for {segment}:{shard} "
                f"under {policy.kind}")
        args = {
            "db_name": self._db_name(segment, shard),
            "op": op,
            "keys": keys,
            "start": start,
            "count": count,
            "max_lag": policy.max_lag,
            "epoch": epoch,
        }
        with Timer(tagged("router.read_ms", op=op, policy=policy.kind)):
            if (policy.kind == "follower_ok" and len(hosts) >= 2
                    and op in _HEDGEABLE_OPS and self._hedging_on()):
                return await self._hedged_read(
                    hosts, op, policy, args, timeout,
                    what=f"read {segment}:{shard}")
            return await self._failover_call(
                hosts, "read", args, _READ_BOUNCE_CODES, timeout,
                retry_timeouts=True, count_bounces=True,
                what=f"read {segment}:{shard}")

    async def write(
        self,
        segment: str,
        shard: int,
        raw_batch: bytes,
        epoch: Optional[int] = None,
        timeout: float = 30.0,
        key: Optional[bytes] = None,
    ):
        """Routed leader write over the replication plane's ``write``
        RPC (one encoded WriteBatch). NOT_LEADER / STALE_EPOCH (a
        fenced ex-leader still in the map) / SOURCE_NOT_FOUND /
        connection errors fall through to the next mapped leader
        candidate — the stale shard-map cases during a handoff.

        ``key`` opts the write into split resolution: under an active
        range split the hash slot's db is gone and the serving child
        depends on the key, which the encoded batch doesn't expose to
        the router. Callers writing to possibly-split segments pass the
        batch's key (single-key batches — a multi-key batch spanning a
        split boundary must be split by the CALLER)."""
        shard = self.resolve_shard(segment, shard, key)
        hosts = self.get_hosts_for(segment, shard, Role.LEADER,
                                   Quantity.ALL)
        if not hosts:
            raise RpcConnectionError(f"no leader for {segment}:{shard}")
        args = {
            "db_name": self._db_name(segment, shard),
            "raw_batch": raw_batch,
            "epoch": epoch,
        }
        return await self._failover_call(
            hosts, "write", args, _WRITE_BOUNCE_CODES, timeout,
            retry_timeouts=False, count_bounces=False,
            what=f"write {segment}:{shard}")

    # -- hedged bounded-staleness reads (round 19) ------------------------

    @staticmethod
    def _hedging_on() -> bool:
        """Hedging rides the RSTPU_TAIL_ARMOR killswitch with its own
        finer-grained switch (``RSTPU_HEDGE=0``): the overload bench
        A/Bs the layers independently."""
        return armor_enabled() and os.environ.get(
            "RSTPU_HEDGE", "1").strip().lower() not in ("0", "false",
                                                        "off", "no")

    def _hedge_delay_s(self, op: str, policy: ReadPolicy) -> float:
        """Backup-request delay: the streaming p95 of THIS op class's
        routed read latency (the round-13 ``router.read_ms`` log-bucket
        histogram — hedge only the slowest ~5%), floored so a cold or
        microsecond-fast histogram can't make hedging fire on every
        read. Floor via ``RSTPU_HEDGE_FLOOR_MS`` (default 5ms)."""
        try:
            floor_ms = float(
                os.environ.get("RSTPU_HEDGE_FLOOR_MS", "") or 5.0)
        except ValueError:
            floor_ms = 5.0
        p95 = self._stats.metric_percentile(
            tagged("router.read_ms", op=op, policy=policy.kind), 95)
        return max(floor_ms, p95 or 0.0) / 1e3

    def _spend_hedge_credit(self) -> bool:
        if self._hedge_credit < 1.0:
            return False
        self._hedge_credit -= 1.0
        return True

    async def _hedged_read(self, hosts: List[Host], op: str,
                           policy: ReadPolicy, args: dict,
                           timeout: float, what: str):
        """Tail-shaving backup request: if the primary failover chain
        hasn't answered within the p95-derived delay, fire the SAME
        bounded-staleness read down a rotated chain starting at the
        next replica and surface the first SUCCESS (reads are
        idempotent by construction — both arms may execute fully). The
        loser is cancelled, which rides RpcClient's cancellation path
        into a best-effort wire ``cancel`` frame; a late answer is
        discarded by the client's pending-future pop."""
        try:
            pct = float(os.environ.get("RSTPU_HEDGE_PCT", "") or 0.05)
        except ValueError:
            pct = 0.05
        self._hedge_credit = min(self._hedge_credit_cap,
                                 self._hedge_credit + pct)
        primary = asyncio.ensure_future(self._failover_call(
            hosts, "read", args, _READ_BOUNCE_CODES, timeout,
            retry_timeouts=True, count_bounces=True, what=what))
        primary.add_done_callback(_retrieve_exception)
        done, _pending = await asyncio.wait(
            {primary}, timeout=self._hedge_delay_s(op, policy))
        if done:
            # rstpu-check: allow(loop-blocking) primary is in `done` from asyncio.wait — result() on a finished task returns immediately
            return primary.result()
        if not self._spend_hedge_credit():
            # over the extra-read budget: degrade to the plain chain
            self._stats.incr(tagged("router.hedge_budget_denied", op=op))
            return await primary
        try:
            await fp.async_hit("router.hedge.fire")
        except fp.FailpointError:
            # chaos seam: the hedge failed to launch — the primary arm
            # must still win on its own (hedging is an optimization,
            # never a correctness dependency)
            return await primary
        self._stats.incr(tagged("router.hedges", op=op))
        backup = asyncio.ensure_future(self._failover_call(
            hosts[1:] + hosts[:1], "read", args, _READ_BOUNCE_CODES,
            timeout, retry_timeouts=True, count_bounces=False,
            what=what + " (hedge)"))
        backup.add_done_callback(_retrieve_exception)
        arms = {primary, backup}
        last_err: Optional[BaseException] = None
        try:
            while arms:
                done, arms = await asyncio.wait(
                    arms, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    if t.cancelled():
                        continue
                    err = t.exception()
                    if err is None:
                        if t is backup:
                            self._stats.incr(
                                tagged("router.hedge_wins", op=op))
                        # rstpu-check: allow(loop-blocking) t is in `done` from asyncio.wait — result() on a finished task returns immediately
                        return t.result()
                    # an errored arm is not the verdict while the other
                    # is still running: remember it and keep waiting
                    last_err = err
            raise last_err if last_err is not None \
                else RpcConnectionError(f"{what}: no candidate answered")
        finally:
            for t in (primary, backup):
                if not t.done():
                    t.cancel()

    async def _failover_call(
        self,
        hosts: List[Host],
        method: str,
        args: dict,
        bounce_codes,
        timeout: float,
        retry_timeouts: bool,
        count_bounces: bool,
        what: str,
    ):
        """The one sequential try-candidates loop behind routed
        reads/writes: bounce on the caller's application-error codes,
        skip dead hosts, remember the last error. Timeouts retry only
        when the caller says the call is idempotent (reads yes, writes
        no — a timed-out write may have committed)."""
        last_err: Optional[Exception] = None
        for host in hosts:
            ip, port = host.repl_addr
            try:
                return await self._pool.call(
                    ip, port, method, args, timeout=timeout)
            except RpcApplicationError as e:
                if e.code not in bounce_codes:
                    raise
                if count_bounces:
                    self._stats.incr(
                        tagged("router.read_bounces", code=e.code.lower()))
                last_err = e
            except RpcTimeout as e:
                if not retry_timeouts:
                    raise
                last_err = e
            except RpcConnectionError as e:
                last_err = e
        raise last_err if last_err is not None else RpcConnectionError(
            f"{what}: no candidate answered")

    @staticmethod
    def _db_name(segment: str, shard: int) -> str:
        from ..utils.segment_utils import segment_to_db_name

        return segment_to_db_name(segment, shard)

    async def hedged_call(
        self,
        segment: str,
        shard: int,
        method: str,
        args: Optional[dict] = None,
        role: Role = Role.ANY,
        backup_delay_sec: float = 0.05,
        timeout: float = 30.0,
    ):
        """Hedged request (reference: future_util speculative futures at the
        router level): fire at the best replica; if it hasn't answered
        within ``backup_delay_sec``, also fire at the next replica and take
        the first success."""
        from ..utils.future_util import speculate

        hosts = self.get_hosts_for(segment, shard, role, Quantity.TWO)
        if not hosts:
            raise RpcConnectionError(f"no hosts for {segment}:{shard}")
        if len(hosts) == 1:
            return await self._pool.call(
                hosts[0].ip, hosts[0].port, method, args, timeout=timeout
            )

        def make(host: Host):
            async def call():
                return await self._pool.call(
                    host.ip, host.port, method, args, timeout=timeout
                )

            return call

        return await speculate(make(hosts[0]), make(hosts[1]), backup_delay_sec)

    @property
    def pool(self) -> RpcClientPool:
        return self._pool
