"""RpcClient: one multiplexed connection with health tracking.

Reference: common/thrift_client_pool.h:107-142 — ``ClientStatusCallback``
tracks ``is_good`` via close/connectError callbacks; requests are
multiplexed on a header channel. Here: request ids multiplex concurrent
calls on one transport connection (tcp/uds/loopback — transport.py);
``is_good`` flips false on connection errors and the pool handles
reconnect throttling.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from typing import Any, Dict, Optional, Tuple

from ..testing import failpoints as fp
from .deadline import (DEADLINE_KEY, TENANT_KEY, armor_enabled,
                       current_deadline, current_tenant)
from .errors import (RpcApplicationError, RpcConnectionError, RpcTimeout,
                     RpcTransportConfigError)
from .serde import decode_message, encode_message
from .transport import Connection, get_transport, resolve_endpoint
from ..observability.context import TRACE_KEY
from ..observability.span import start_span

log = logging.getLogger(__name__)


class RpcClient:
    """Async RPC client bound to the event loop that created it."""

    def __init__(self, host: str, port: int, connect_timeout: float = 5.0,
                 ssl_manager=None):
        self.host = host
        self.port = port
        self._connect_timeout = connect_timeout
        self._ssl_manager = ssl_manager
        self._conn: Optional[Connection] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self._recv_task: Optional[asyncio.Task] = None
        self.is_good = False
        self.last_connect_attempt = 0.0
        # a remembered RpcTransportConfigError from the last connect: the
        # pool's reconnect throttle re-raises it as itself, so a misconfig
        # is never laundered into a throttled RpcConnectionError
        self.last_connect_config_error: Optional[Exception] = None

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    @property
    def transport_scheme(self) -> Optional[str]:
        """The connected transport's scheme (None before connect)."""
        return self._conn.scheme if self._conn is not None else None

    async def connect(self) -> None:
        self.last_connect_attempt = time.monotonic()
        self.last_connect_config_error = None
        # endpoint resolution is per-connect: an explicit URL in ``host``
        # wins, else the RSTPU_TRANSPORT policy applies. A transport
        # MISCONFIG (RpcTransportConfigError) propagates as itself —
        # reconnect machinery must not retry it into oblivion.
        try:
            ep = resolve_endpoint(self.host, self.port,
                                  ssl=self._ssl_manager is not None)
            transport = get_transport(ep.scheme)
        except RpcTransportConfigError as e:
            self.last_connect_config_error = e
            raise
        try:
            # inside the except net: a tripped fail policy surfaces as
            # RpcConnectionError, a delay policy is a stuck connect
            await fp.async_hit("rpc.connect")
            self._conn = await asyncio.wait_for(
                transport.connect(ep, ssl_manager=self._ssl_manager),
                self._connect_timeout,
            )
        except (OSError, asyncio.TimeoutError) as e:
            # (ssl.SSLError is an OSError subclass: handshake failures
            # funnel into RpcConnectionError too)
            self.is_good = False
            raise RpcConnectionError(f"connect {ep}: {e}") from e
        if self._ssl_manager is not None:
            # role binding: the peer must hold a SERVER cert — CA
            # membership alone would let any cluster client cert
            # impersonate a server (utils/ssl_context_manager)
            from ..utils.ssl_context_manager import (
                PeerRoleError, check_peer_role)

            try:
                check_peer_role(
                    self._conn.get_extra_info("ssl_object"), "server")
            except PeerRoleError as e:
                self._conn.close()
                self.is_good = False
                raise RpcConnectionError(f"connect {ep}: {e}") from e
        self.is_good = True
        self._recv_task = asyncio.ensure_future(self._recv_loop())

    async def _recv_loop(self) -> None:
        assert self._conn is not None
        conn = self._conn
        try:
            while True:
                for header, payload in await conn.recv_frames():
                    msg = decode_message(header, payload)
                    fut = self._pending.pop(msg.get("id"), None)
                    if fut is None or fut.done():
                        continue
                    if msg.get("ok"):
                        fut.set_result(msg.get("result"))
                    else:
                        err = msg.get("error") or {}
                        fut.set_exception(
                            RpcApplicationError(
                                err.get("code", "UNKNOWN"),
                                err.get("message", ""),
                                err.get("data"),
                            )
                        )
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self._fail_pending(RpcConnectionError(f"connection lost: {e}"))
        except asyncio.CancelledError:
            self._fail_pending(RpcConnectionError("client closed"))
            raise
        except Exception as e:  # pragma: no cover - defensive
            log.exception("rpc client recv loop error")
            self._fail_pending(RpcConnectionError(f"recv error: {e}"))
        finally:
            self.is_good = False

    def _fail_pending(self, exc: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    async def call(
        self, method: str, args: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = 30.0,
        tail_exempt: bool = False,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> Any:
        """``tail_exempt=True`` marks a call whose long RTT is BY DESIGN
        (a long-poll pull parks server-side up to max_wait_ms): the
        tracing tail-keep path must not retain it as a slow outlier.

        ``deadline_ms``/``tenant`` stamp the round-19 tail-armor frame
        headers (rpc/deadline): an explicit value wins; otherwise the
        AMBIENT request scope propagates — a handler fanning out
        downstream re-stamps its caller's decremented budget and tenant
        automatically, like the trace context."""
        if not self.is_good:
            raise RpcConnectionError(f"client {self.host}:{self.port} not connected")
        req_id = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        # The RTT span covers serialize → send → response future. When
        # sampled, the trace context rides the message's JSON frame header
        # under the reserved "trace" key; the server reattaches it before
        # dispatch, stitching the caller's trace across the process hop.
        with start_span("rpc.rtt", method=method, peer=self.host) as sp:
            if tail_exempt:
                sp.annotate(tail_exempt="long_poll")
            msg: Dict[str, Any] = {
                "id": req_id, "method": method, "args": args or {}
            }
            if sp.sampled:
                msg[TRACE_KEY] = sp.to_wire()
            if armor_enabled():
                budget_ms = deadline_ms
                if budget_ms is None:
                    ambient = current_deadline()
                    if ambient is not None:
                        budget_ms = ambient.remaining_ms()
                if budget_ms is not None:
                    # relative budget on the wire — wall clocks across
                    # processes are not comparable (deadline.py); an
                    # already-negative budget still ships so the server
                    # sheds with the TYPED error instead of serving it
                    msg[DEADLINE_KEY] = round(float(budget_ms), 3)
                wire_tenant = tenant if tenant is not None \
                    else current_tenant()
                if wire_tenant is not None:
                    msg[TENANT_KEY] = wire_tenant
            header, chunks = encode_message(msg)
            try:
                conn = self._conn
                assert conn is not None
                # no caller-side write lock: connections guarantee frame
                # atomicity + FIFO under concurrent senders, which lets
                # the vectored transports coalesce concurrent calls
                await conn.send_frames([(header, chunks)])
            except (ConnectionError, OSError) as e:
                self.is_good = False
                self._pending.pop(req_id, None)
                raise RpcConnectionError(f"send failed: {e}") from e
            try:
                if timeout is None:
                    return await fut
                return await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                self._pending.pop(req_id, None)
                raise RpcTimeout(
                    f"{method} to {self.host}:{self.port} timed out"
                ) from None
            except asyncio.CancelledError:
                # a cancelled caller (hedged-read loser) stops waiting
                # HERE: drop the pending future so the late answer is
                # discarded by _recv_loop's pop-miss, and tell the
                # server to stop working on it — best-effort, off the
                # cancellation path (the winner must not wait on the
                # loser's cancel frame reaching a slow server)
                self._pending.pop(req_id, None)
                if armor_enabled():
                    asyncio.ensure_future(self._send_cancel(req_id))
                raise

    async def _send_cancel(self, req_id: int) -> None:
        """Best-effort ``cancel`` control frame (no "method" key, never
        replied to): the server cancels the matching in-flight dispatch
        task if the request is still running. Losing the frame is fine
        — the reply is discarded client-side either way."""
        conn = self._conn
        if conn is None or not self.is_good:
            return
        try:
            header, chunks = encode_message({"cancel": req_id})
            await conn.send_frames([(header, chunks)])
        except (ConnectionError, OSError):
            pass

    async def close(self) -> None:
        self.is_good = False
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except (asyncio.CancelledError, Exception):
                pass
            self._recv_task = None
        if self._conn is not None:
            self._conn.close()
            try:
                await self._conn.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._conn = None
