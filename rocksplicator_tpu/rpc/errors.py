"""RPC error taxonomy."""

from __future__ import annotations

from typing import Any, Dict, Optional


class RpcError(Exception):
    """Base class for transport-level RPC failures."""


class RpcTimeout(RpcError):
    pass


class RpcConnectionError(RpcError):
    pass


class RpcApplicationError(RpcError):
    """A typed error raised by the remote handler (thrift exception
    equivalent). ``code`` is an application-defined error code; ``data``
    carries structured detail."""

    def __init__(self, code: str, message: str = "", data: Optional[Dict[str, Any]] = None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.data = data or {}
