"""RPC error taxonomy."""

from __future__ import annotations

from typing import Any, Dict, Optional


class RpcError(Exception):
    """Base class for transport-level RPC failures."""


class RpcTimeout(RpcError):
    pass


class RpcConnectionError(RpcError):
    pass


class RpcTransportConfigError(RpcError):
    """A transport misconfiguration — unknown ``RSTPU_TRANSPORT`` value,
    an endpoint URL with an unregistered scheme, or a transport that
    cannot apply (e.g. TLS over a non-TCP byte layer). Deliberately NOT
    a connection error: retry/reconnect machinery must not mask it."""


class RpcApplicationError(RpcError):
    """A typed error raised by the remote handler (thrift exception
    equivalent). ``code`` is an application-defined error code; ``data``
    carries structured detail."""

    def __init__(self, code: str, message: str = "", data: Optional[Dict[str, Any]] = None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.data = data or {}
