"""Deadline + tenant propagation primitives (round 19 tail armor).

Reference: common/thrift_client_pool.h carries per-call timeout/connect
budgets client-side only — the server never learns how long the caller
is still willing to wait, so an overloaded server happily computes
answers nobody is waiting for. Here the client's remaining budget rides
the JSON frame header exactly as the round-1 trace header does
(``DEADLINE_KEY``/``TENANT_KEY`` are reserved top-level message keys
next to ``TRACE_KEY``), each server hop decrements it by measured
queue-wait, and handlers consult :func:`current_deadline` to shed dead
work with a typed ``DEADLINE_EXCEEDED`` instead of serving it.

Wire format: the deadline travels as a RELATIVE budget in milliseconds
(``msg["deadline"] = remaining_ms``) — cross-process wall clocks are
not comparable, monotonic clocks even less so; each hop re-anchors the
budget against its own monotonic clock on receipt. The tenant tag is a
short opaque string (``msg["tenant"]``).

Both in-process carriers are contextvars, so a handler that fans out
through :class:`RpcClient` re-stamps the DECREMENTED budget and the
same tenant on every downstream hop without plumbing arguments through
every signature — the same mechanism the trace context uses.

Everything here is behind the ``RSTPU_TAIL_ARMOR`` killswitch
(default ON; ``0``/``false``/``off`` disarms): unarmed, clients stamp
nothing and servers check nothing, which is the A/B baseline the
overload bench's unarmed-overhead gate measures.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from dataclasses import dataclass
from typing import Optional

__all__ = [
    "DEADLINE_KEY", "TENANT_KEY", "DEADLINE_EXCEEDED", "RETRY_LATER",
    "Deadline", "armor_enabled", "current_deadline", "current_tenant",
    "request_scope",
]

# Reserved top-level frame-header keys (siblings of TRACE_KEY — see
# rpc/serde.encode_message: the header is the whole JSON message minus
# binary chunks, so any top-level key is out-of-band metadata).
DEADLINE_KEY = "deadline"
TENANT_KEY = "tenant"

# Typed application-error codes (rpc/errors.RpcApplicationError.code).
DEADLINE_EXCEEDED = "DEADLINE_EXCEEDED"
RETRY_LATER = "RETRY_LATER"

_OFF = ("0", "false", "off", "no")


def armor_enabled() -> bool:
    """The one killswitch for all three tail-armor layers (deadlines,
    hedging, admission): ``RSTPU_TAIL_ARMOR=0`` restores the exact
    pre-round-19 serving path. Read per call — the overload bench flips
    it per child process via env, and a cached module global would pin
    the first process's answer into every test in the suite."""
    return os.environ.get("RSTPU_TAIL_ARMOR", "1").strip().lower() \
        not in _OFF


@dataclass(frozen=True)
class Deadline:
    """An absolute point on THIS process's monotonic clock. Created
    from a relative wire budget on receipt; converted back to a
    relative budget when stamped onto a downstream call — so each hop's
    queue/service time is subtracted exactly once, wherever it accrued.
    """

    expires_at: float  # time.monotonic() instant

    @classmethod
    def after_ms(cls, budget_ms: float) -> "Deadline":
        return cls(time.monotonic() + float(budget_ms) / 1e3)

    def remaining_ms(self) -> float:
        """May be negative once expired — callers use the sign."""
        return (self.expires_at - time.monotonic()) * 1e3

    @property
    def expired(self) -> bool:
        return self.remaining_ms() <= 0.0


_deadline_var: contextvars.ContextVar[Optional[Deadline]] = \
    contextvars.ContextVar("rstpu_deadline", default=None)
_tenant_var: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("rstpu_tenant", default=None)


def current_deadline() -> Optional[Deadline]:
    return _deadline_var.get()


def current_tenant() -> Optional[str]:
    return _tenant_var.get()


@contextlib.contextmanager
def request_scope(deadline: Optional[Deadline] = None,
                  tenant: Optional[str] = None):
    """Scope the ambient deadline/tenant to one request's dispatch task
    (the server sets this around the handler call; per-request tasks
    make the contextvars naturally request-local, exactly like the
    trace context in start_span)."""
    t_d = _deadline_var.set(deadline)
    t_t = _tenant_var.set(tenant)
    try:
        yield
    finally:
        _deadline_var.reset(t_d)
        _tenant_var.reset(t_t)
