"""Wire serialization: JSON header + raw binary payload region.

The encoded message is ``header_json || payload`` where any ``bytes`` /
``memoryview`` value nested in the message is replaced in the header by
``{"$bin": [offset, length]}`` referencing the payload region. Decoding
returns ``memoryview`` slices into the received buffer — the zero-copy
analog of the reference's IOBuf payloads (replicator.thrift:44-49 declares
``raw_data`` as IOBuf specifically to avoid copying WAL bytes).
"""

from __future__ import annotations

import json
from typing import Any, List, Tuple

_BIN_KEY = "$bin"


def encode_message(obj: Any) -> Tuple[bytes, List[bytes]]:
    """Returns (header_json_bytes, payload_chunks)."""
    chunks: List[bytes] = []
    offset = 0

    def walk(value: Any) -> Any:
        nonlocal offset
        if isinstance(value, (bytes, bytearray, memoryview)):
            b = bytes(value) if not isinstance(value, bytes) else value
            ref = {_BIN_KEY: [offset, len(b)]}
            chunks.append(b)
            offset += len(b)
            return ref
        if isinstance(value, dict):
            if _BIN_KEY in value:
                raise ValueError(f"reserved key {_BIN_KEY!r} in message")
            return {k: walk(v) for k, v in value.items()}
        if isinstance(value, (list, tuple)):
            return [walk(v) for v in value]
        return value

    header = json.dumps(walk(obj), separators=(",", ":")).encode("utf-8")
    return header, chunks


def decode_message(header: memoryview, payload: memoryview) -> Any:
    obj = json.loads(bytes(header).decode("utf-8"))

    def walk(value: Any) -> Any:
        if isinstance(value, dict):
            if _BIN_KEY in value and len(value) == 1:
                ref = value[_BIN_KEY]
                if (
                    not isinstance(ref, list)
                    or len(ref) != 2
                    or not all(isinstance(x, int) and x >= 0 for x in ref)
                    or ref[0] + ref[1] > len(payload)
                ):
                    raise ValueError(f"invalid binary ref: {ref!r}")
                off, length = ref
                return payload[off:off + length]
            return {k: walk(v) for k, v in value.items()}
        if isinstance(value, list):
            return [walk(v) for v in value]
        return value

    return walk(obj)
