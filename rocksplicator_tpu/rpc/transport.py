"""Pluggable RPC byte transports: tcp (default), uds, in-process loopback.

The reference runs its entire serving fabric on fbthrift's pluggable
channel layer — zero-copy IOBuf chains over header-protocol TCP
(common/thrift_client_pool.h), with the transport chosen per channel.
This module is that seam for our stack: everything above it
(client.py / server.py / client_pool.py) speaks ``Connection`` objects
(``send_frames`` / ``recv_frames`` / ``close``) and never touches a
socket, so the byte layer is selected per endpoint:

- **tcp** — asyncio streams, one joined write per frame (round 6's
  ``_JOIN_MAX`` economy), TLS-capable. The default and the only
  cross-host transport.
- **uds** — unix-domain socket with VECTORED frame coalescing: every
  sender enqueues encoded frame parts (length-prefix struct, header,
  payload chunks — never joined) and a single drainer empties the whole
  pending queue into one ``sendmsg`` iovec; the receiver decodes
  multiple frames per ``recv_into`` against a reusable buffer
  (framing.FrameBuffer). Same wire format as tcp, ~0 copies above the
  kernel, and far fewer syscalls under concurrency.
- **loopback** — in-process queue pair for same-host replica
  colocation and tests: frame header/payload memoryviews are handed
  across a deque with no wire encode, no compression, and no recv copy
  — a syscall-free ceiling that de-noises small benchmark hosts.

Selection (client and server agree by construction):

- an explicit URL endpoint wins: ``tcp://host:port``,
  ``uds:///path/to.sock``, ``loopback://key``;
- else the ``RSTPU_TRANSPORT`` env policy (``tcp``|``uds``|``loopback``)
  applies to plain ``(host, port)`` addresses — ``uds`` only for
  same-host peers (socket path derived from the port, see
  ``uds_path_for_port``), ``loopback`` only within the process;
- TLS pins tcp: an ``ssl_manager`` forces the tcp transport (the
  role-binding handshake is a TLS-over-TCP contract here).

Failpoints (``rpc.connect``, ``rpc.frame.send``, ``rpc.frame.recv``,
torn frames) arm identically on all three transports: the send/recv
hits and the torn-prefix semantics live at this layer's seams.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import socket
import tempfile
from collections import deque
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, List, Optional, Sequence, Tuple

from ..testing import failpoints as fp
from ..utils.stats import Stats
from .errors import RpcTransportConfigError
from .framing import (
    FrameBuffer,
    FrameReader,
    encode_wire_parts,
    write_frame,
)

log = logging.getLogger(__name__)

# Process-wide frame counters (round 22): the fleet A/B's frames/sec
# signal — 100 per-shard pull streams vs one mux session per peer show
# up HERE first. Counted once per frame at each transport's send/recv
# choke point (thread-buffered Stats incr; negligible next to the frame
# encode itself).
M_FRAMES_SENT = "rpc.frames_sent"
M_FRAMES_RECEIVED = "rpc.frames_received"

SCHEMES = ("tcp", "uds", "loopback")

# one sendmsg's iovec cap: Linux IOV_MAX is 1024; stay comfortably under
# it (a frame contributes ≥2 iovec entries: length-prefix + header)
IOV_CAP = 512

Frame = Tuple[bytes, List[bytes]]  # (header_json, payload_chunks)
ConnectionCallback = Callable[["Connection"], Awaitable[None]]


# ---------------------------------------------------------------------------
# endpoints + selection
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Endpoint:
    scheme: str          # tcp | uds | loopback
    host: str = ""       # tcp
    port: int = 0        # tcp; also the loopback default key
    path: str = ""       # uds socket path
    key: str = ""        # loopback registry key

    def __str__(self) -> str:
        if self.scheme == "tcp":
            return f"tcp://{self.host}:{self.port}"
        if self.scheme == "uds":
            return f"uds://{self.path}"
        return f"loopback://{self.key}"


def transport_policy() -> str:
    """The process-wide default transport (``RSTPU_TRANSPORT``)."""
    v = os.environ.get("RSTPU_TRANSPORT", "").strip().lower()
    if not v:
        return "tcp"
    if v not in SCHEMES:
        raise RpcTransportConfigError(
            f"RSTPU_TRANSPORT={v!r}: unknown transport "
            f"(expected one of {'|'.join(SCHEMES)})")
    return v


def uds_default_dir() -> str:
    d = os.environ.get("RSTPU_UDS_DIR")
    if d:
        return d
    return os.path.join(
        tempfile.gettempdir(), f"rstpu-uds-{os.getuid()}")


def uds_path_for_port(port: int) -> str:
    """The well-known per-port socket path: a server that binds TCP port
    N under the uds policy also listens here, so a same-host client can
    derive the fast path from the (host, port) address alone."""
    return os.path.join(uds_default_dir(), f"{port}.sock")


_LOCAL_HOSTS = {"127.0.0.1", "localhost", "::1", "0.0.0.0", ""}


def _is_local_host(host: str) -> bool:
    if host in _LOCAL_HOSTS:
        return True
    try:
        from ..utils.misc import local_ip

        return host in (local_ip(), socket.gethostname())
    except Exception:
        return False


def parse_endpoint(url: str) -> Endpoint:
    """Parse an explicit endpoint URL (scheme://...)."""
    scheme, _, rest = url.partition("://")
    scheme = scheme.strip().lower()
    if scheme == "tcp":
        host, _, port = rest.rpartition(":")
        if not host or not port.isdigit():
            raise RpcTransportConfigError(
                f"bad tcp endpoint {url!r} (want tcp://host:port)")
        return Endpoint("tcp", host=host, port=int(port))
    if scheme == "uds":
        if not rest:
            raise RpcTransportConfigError(
                f"bad uds endpoint {url!r} (want uds:///path/to.sock)")
        # accept uds:///abs/path (canonical) and uds://abs/path
        return Endpoint(
            "uds", path=rest if rest.startswith("/") else "/" + rest)
    if scheme in ("loopback", "loop"):
        if not rest:
            raise RpcTransportConfigError(
                f"bad loopback endpoint {url!r} (want loopback://key)")
        return Endpoint("loopback", key=rest)
    raise RpcTransportConfigError(
        f"unknown transport scheme {scheme!r} in endpoint {url!r} "
        f"(expected one of {'|'.join(SCHEMES)})")


def resolve_endpoint(host: str, port: int, *, ssl: bool = False) -> Endpoint:
    """Resolve an address to a concrete endpoint: explicit URL wins, else
    the ``RSTPU_TRANSPORT`` policy applies (uds only for same-host
    peers; TLS pins tcp)."""
    if "://" in host:
        ep = parse_endpoint(host)
        if ssl and ep.scheme != "tcp":
            raise RpcTransportConfigError(
                f"TLS requires the tcp transport, got {host!r}")
        return ep
    policy = "tcp" if ssl else transport_policy()
    if policy == "uds" and _is_local_host(host):
        return Endpoint("uds", path=uds_path_for_port(port))
    if policy == "loopback" and _is_local_host(host):
        # same-host only, like uds: a remote peer can never be served by
        # this process's loopback registry, and the port-keyed endpoint
        # discards the host — falling through to tcp keeps a mixed
        # local/remote topology correct under the policy
        return Endpoint("loopback", key=str(port))
    return Endpoint("tcp", host=host, port=port)


# ---------------------------------------------------------------------------
# interface
# ---------------------------------------------------------------------------


class Connection:
    """One bidirectional frame stream. Implementations guarantee frame
    atomicity and FIFO ordering under CONCURRENT ``send_frames`` callers
    (no caller-side write lock needed — that's what lets the vectored
    transport coalesce many senders into one syscall)."""

    scheme = "?"

    async def send_frames(self, frames: Sequence[Frame]) -> None:
        raise NotImplementedError

    async def recv_frames(self) -> List[Tuple[memoryview, memoryview]]:
        """≥1 decoded (header, payload) frames, or raises
        asyncio.IncompleteReadError / ConnectionError when the stream
        ends (clean or torn)."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    async def wait_closed(self) -> None:
        pass

    def get_extra_info(self, name: str, default=None):
        return default


class Listener:
    """A bound acceptor; ``on_connection(conn)`` is spawned as a task
    per accepted peer."""

    def close(self) -> None:
        raise NotImplementedError

    async def wait_closed(self) -> None:
        pass


class Transport:
    scheme = "?"

    async def connect(self, ep: Endpoint, *, ssl_manager=None) -> Connection:
        raise NotImplementedError

    async def accept(self, ep: Endpoint, on_connection: ConnectionCallback,
                     *, ssl_manager=None) -> Listener:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# tcp — asyncio streams (the seed behavior, TLS-capable)
# ---------------------------------------------------------------------------


class TcpConnection(Connection):
    scheme = "tcp"

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self._reader = FrameReader(reader)
        self._writer = writer
        # StreamWriter interleaves concurrent writes at write() call
        # granularity; serialize whole frames
        self._lock = asyncio.Lock()

    async def send_frames(self, frames: Sequence[Frame]) -> None:
        async with self._lock:
            for header, chunks in frames:
                await write_frame(self._writer, header, chunks)
        Stats.get().incr(M_FRAMES_SENT, len(frames))

    async def recv_frames(self) -> List[Tuple[memoryview, memoryview]]:
        frame = await self._reader.read_frame()
        Stats.get().incr(M_FRAMES_RECEIVED)
        return [frame]

    def close(self) -> None:
        self._writer.close()

    async def wait_closed(self) -> None:
        try:
            await self._writer.wait_closed()
        except (ConnectionError, OSError):
            pass

    def get_extra_info(self, name: str, default=None):
        return self._writer.get_extra_info(name, default)


class _TcpListener(Listener):
    def __init__(self, server: asyncio.AbstractServer):
        self.server = server

    @property
    def port(self) -> int:
        return self.server.sockets[0].getsockname()[1]

    def close(self) -> None:
        self.server.close()

    async def wait_closed(self) -> None:
        await self.server.wait_closed()


class TcpTransport(Transport):
    scheme = "tcp"

    async def connect(self, ep: Endpoint, *, ssl_manager=None) -> Connection:
        reader, writer = await asyncio.open_connection(
            ep.host, ep.port,
            ssl=(ssl_manager.get() if ssl_manager else None),
        )
        return TcpConnection(reader, writer)

    async def accept(self, ep: Endpoint, on_connection: ConnectionCallback,
                     *, ssl_manager=None) -> Listener:
        ssl_ctx = ssl_manager.get() if ssl_manager else None

        async def on_stream(reader, writer):
            await on_connection(TcpConnection(reader, writer))

        server = await asyncio.start_server(
            on_stream, ep.host, ep.port, ssl=ssl_ctx)
        return _TcpListener(server)


# ---------------------------------------------------------------------------
# uds — vectored sendmsg batching over a unix-domain socket
# ---------------------------------------------------------------------------


class UdsConnection(Connection):
    """Vectored frame coalescing: ``send_frames`` encodes to wire parts
    and enqueues them; ONE drainer empties the whole pending queue into
    a single ``sendmsg`` iovec (length-prefix structs interleaved with
    header/payload buffers — no join-buffer materialization). This
    generalizes round 6's ``_JOIN_MAX`` single-write join from "one
    memcpy per frame" to "zero memcpy, one syscall per queue drain"."""

    scheme = "uds"

    def __init__(self, sock: socket.socket,
                 loop: asyncio.AbstractEventLoop):
        sock.setblocking(False)
        self._sock = sock
        self._loop = loop
        self._sendq: deque = deque()  # (parts, waiter)
        self._drainer: Optional[asyncio.Task] = None
        self._broken: Optional[BaseException] = None
        self._closed = False
        self._rbuf = FrameBuffer()
        # coalescing counters (introspection + tests): frames vs syscalls
        self.frames_sent = 0
        self.sendmsg_calls = 0
        self.frames_received = 0
        self.recv_calls = 0

    # -- send half ------------------------------------------------------

    async def send_frames(self, frames: Sequence[Frame]) -> None:
        if self._broken is not None:
            raise ConnectionResetError(
                f"uds connection is broken: {self._broken}")
        if self._closed:
            raise ConnectionResetError("uds connection is closed")
        parts: List[bytes] = []
        for header, chunks in frames:
            frame_parts, wire_len = encode_wire_parts(header, chunks)
            await fp.async_hit("rpc.frame.send")
            cut = fp.torn_point("rpc.frame.send", wire_len)
            if cut is not None:
                # torn frame: flush anything already encoded in this
                # call plus the torn prefix IN ORDER behind the queued
                # frames, then break the connection — the peer sees a
                # short/desynced stream (clean decode error there), we
                # see a failed send
                prefix = b"".join(
                    bytes(p) for p in frame_parts)[:cut]
                waiter = self._enqueue(parts + [prefix])
                try:
                    await waiter
                except (ConnectionError, OSError):
                    pass
                self._broken = ConnectionResetError("torn frame")
                try:
                    self._sock.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                raise fp.FailpointError(f"torn frame at +{cut}B")
            parts.extend(frame_parts)
            self.frames_sent += 1
        Stats.get().incr(M_FRAMES_SENT, len(frames))
        await self._enqueue(parts)

    def _enqueue(self, parts: List[bytes]) -> "asyncio.Future[None]":
        waiter: asyncio.Future = self._loop.create_future()
        # send_frames may have suspended (failpoint delay, torn flush)
        # between its entry checks and this call, with the connection
        # breaking meanwhile: a waiter enqueued now would spawn a drainer
        # whose loop condition is already false and hang forever
        err = self._broken if self._broken is not None else (
            ConnectionResetError("uds connection is closed")
            if self._closed else None)
        if err is not None:
            waiter.set_exception(
                ConnectionResetError(f"uds send failed: {err}"))
            return waiter
        self._sendq.append((parts, waiter))
        if self._drainer is None or self._drainer.done():
            self._drainer = self._loop.create_task(self._drain())
        return waiter

    async def _drain(self) -> None:
        while self._sendq and self._broken is None and not self._closed:
            batch = list(self._sendq)
            self._sendq.clear()
            iov: deque = deque()
            for parts, _w in batch:
                for p in parts:
                    if len(p):
                        iov.append(p if isinstance(p, memoryview)
                                   else memoryview(p))
            try:
                await self._sendmsg_all(iov)
            except asyncio.CancelledError:
                # close() cancels the drainer: the popped batch's waiters
                # are no longer reachable from _sendq, so fail them here
                # or their senders hang forever
                e = ConnectionResetError("connection closed")
                self._fail_batch(batch, e)
                self._fail_queued(e)
                raise
            except (ConnectionError, OSError) as e:
                self._broken = e
                self._fail_batch(batch, e)
                self._fail_queued(e)
                return
            for _parts, w in batch:
                if not w.done():
                    w.set_result(None)
        # belt and braces for the enqueue-vs-break race: anything still
        # queued when the loop exits on _broken/_closed must be failed,
        # not stranded
        if self._sendq:
            self._fail_queued(
                self._broken
                or ConnectionResetError("uds connection is closed"))

    def _fail_batch(self, batch, exc: BaseException) -> None:
        for _parts, w in batch:
            if not w.done():
                w.set_exception(
                    ConnectionResetError(f"uds send failed: {exc}"))

    def _fail_queued(self, exc: BaseException) -> None:
        while self._sendq:
            _parts, w = self._sendq.popleft()
            if not w.done():
                w.set_exception(
                    ConnectionResetError(f"uds send failed: {exc}"))

    async def _sendmsg_all(self, iov: deque) -> None:
        while iov:
            batch = list(itertools.islice(iov, IOV_CAP))
            sent = self._try_sendmsg(batch)
            if sent is None:
                await self._wait_writable()
                continue
            self.sendmsg_calls += 1
            while sent > 0:
                head = iov[0]
                if sent >= len(head):
                    sent -= len(head)
                    iov.popleft()
                else:
                    iov[0] = head[sent:]
                    sent = 0

    def _try_sendmsg(self, bufs: List[memoryview]) -> Optional[int]:
        try:
            # rstpu-check: allow(loop-blocking) non-blocking socket — EAGAIN returns None and the drainer awaits loop writability; the vectored send never parks the loop
            return self._sock.sendmsg(bufs)
        except (BlockingIOError, InterruptedError):
            return None

    def _wait_writable(self) -> "asyncio.Future[None]":
        fut: asyncio.Future = self._loop.create_future()
        fd = self._sock.fileno()
        if fd < 0:
            raise ConnectionResetError("uds connection is closed")
        self._loop.add_writer(fd, lambda: fut.done() or fut.set_result(None))
        fut.add_done_callback(lambda _f: self._loop.remove_writer(fd))
        return fut

    # -- recv half ------------------------------------------------------

    async def recv_frames(self) -> List[Tuple[memoryview, memoryview]]:
        frames = self._rbuf.pop_frames()
        while not frames:
            view = self._rbuf.recv_view()
            try:
                n = await self._loop.sock_recv_into(self._sock, view)
            finally:
                view.release()
            self.recv_calls += 1
            if n == 0:
                # EOF: clean between frames, short mid-frame — either way
                # the FrameReader contract is IncompleteReadError
                raise asyncio.IncompleteReadError(b"", None)
            self._rbuf.advance(n)
            frames = self._rbuf.pop_frames()
        # arm once per FRAME, not per coalesced recv batch, so fail_nth /
        # delay / seeded policies count the same logical events as the
        # tcp FrameReader (one hit per read_frame)
        for _ in frames:
            await fp.async_hit("rpc.frame.recv")
        self.frames_received += len(frames)
        Stats.get().incr(M_FRAMES_RECEIVED, len(frames))
        return frames

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._drainer is not None and not self._drainer.done():
            self._drainer.cancel()
        self._fail_queued(ConnectionResetError("connection closed"))
        try:
            self._sock.close()
        except OSError:
            pass

    async def wait_closed(self) -> None:
        if self._drainer is not None:
            try:
                await self._drainer
            except (asyncio.CancelledError, ConnectionError, OSError):
                pass


class _UdsListener(Listener):
    def __init__(self, sock: socket.socket, path: str,
                 task: asyncio.Task):
        self._sock = sock
        self.path = path
        self._task = task

    def close(self) -> None:
        self._task.cancel()
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass

    async def wait_closed(self) -> None:
        try:
            await self._task
        except (asyncio.CancelledError, Exception):
            pass


class UdsTransport(Transport):
    scheme = "uds"

    async def connect(self, ep: Endpoint, *, ssl_manager=None) -> Connection:
        if ssl_manager is not None:
            raise RpcTransportConfigError(
                "TLS requires the tcp transport (uds endpoint "
                f"{ep.path!r})")
        loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            await loop.sock_connect(sock, ep.path)
        except BaseException:
            sock.close()
            raise
        return UdsConnection(sock, loop)

    async def accept(self, ep: Endpoint, on_connection: ConnectionCallback,
                     *, ssl_manager=None) -> Listener:
        if ssl_manager is not None:
            raise RpcTransportConfigError(
                "TLS requires the tcp transport (uds endpoint "
                f"{ep.path!r})")
        loop = asyncio.get_running_loop()
        os.makedirs(os.path.dirname(ep.path) or "/", exist_ok=True)
        try:
            os.unlink(ep.path)  # stale socket from a dead process
        except OSError:
            pass
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.setblocking(False)
        sock.bind(ep.path)
        sock.listen(128)

        async def accept_loop():
            while True:
                try:
                    client, _addr = await loop.sock_accept(sock)
                except asyncio.CancelledError:
                    raise
                except OSError as e:
                    # transient accept failure (EMFILE/ENFILE under fd
                    # pressure): keep the listener alive, like the tcp
                    # path's asyncio.start_server does — a dead uds
                    # acceptor would strand every policy client on
                    # ConnectionRefused with no server-side signal
                    if sock.fileno() < 0:
                        return  # listener closed
                    log.warning("uds accept error on %s: %s", ep.path, e)
                    await asyncio.sleep(0.1)
                    continue
                conn = UdsConnection(client, loop)
                t = asyncio.ensure_future(on_connection(conn))
                t.add_done_callback(_reap_connection_task)

        task = asyncio.ensure_future(accept_loop())
        return _UdsListener(sock, ep.path, task)


def _reap_connection_task(task: asyncio.Task) -> None:
    if not task.cancelled():
        task.exception()  # connection handlers log their own errors


# ---------------------------------------------------------------------------
# loopback — in-process queue pair (syscall-free ceiling)
# ---------------------------------------------------------------------------


class LoopbackConnection(Connection):
    """Frames cross as (header, payload) memoryviews on a deque: no wire
    pack, no compression, no recv copy — the only serde work is
    encode_message/decode_message at the call layer. Failpoints arm
    exactly as on the socket transports; a torn frame becomes a poison
    entry the receiver turns into a connection-reset, so reconnect
    behavior matches byte-for-byte."""

    scheme = "loopback"

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._q: deque = deque()
        self._wakeup = asyncio.Event()
        self.peer: Optional["LoopbackConnection"] = None
        self._closed = False
        self.frames_sent = 0
        self.frames_received = 0

    async def send_frames(self, frames: Sequence[Frame]) -> None:
        peer = self.peer
        for header, chunks in frames:
            await fp.async_hit("rpc.frame.send")
            # seeded-stream parity with the socket transports: the
            # offset draw (randrange) consumes a range-dependent number
            # of rng draws, so the length passed to torn_point must be
            # the SAME wire length uds/tcp would use — pay the one-off
            # encode (incl. compression) only when the site is armed;
            # production loopback sends stay zero-copy
            if fp.is_active("rpc.frame.send"):
                _parts, wire_len = encode_wire_parts(
                    bytes(header), [bytes(c) for c in chunks])
            else:
                wire_len = 12 + len(header) + sum(len(c) for c in chunks)
            cut = fp.torn_point("rpc.frame.send", wire_len)
            if cut is not None:
                if peer is not None and not peer._closed:
                    peer._push(("torn", None, None))
                self._closed = True
                self._wakeup.set()
                raise fp.FailpointError(f"torn frame at +{cut}B")
            if self._closed or peer is None or peer._closed:
                raise ConnectionResetError("loopback peer closed")
            if len(chunks) == 1:
                payload = memoryview(chunks[0])
            else:
                payload = memoryview(b"".join(chunks))
            peer._push(("frame", memoryview(header), payload))
            self.frames_sent += 1
        Stats.get().incr(M_FRAMES_SENT, len(frames))

    def _push(self, item) -> None:
        self._q.append(item)
        self._wakeup.set()

    async def recv_frames(self) -> List[Tuple[memoryview, memoryview]]:
        while not self._q:
            if self._closed:
                raise asyncio.IncompleteReadError(b"", None)
            self._wakeup.clear()
            await self._wakeup.wait()
        frames: List[Tuple[memoryview, memoryview]] = []
        while self._q:
            kind, header, payload = self._q[0]
            if kind == "frame":
                self._q.popleft()
                frames.append((header, payload))
                continue
            if frames:
                break  # deliver completed frames before the poison
            self._q.popleft()
            if kind == "torn":
                raise ConnectionResetError("torn frame on loopback")
            raise asyncio.IncompleteReadError(b"", None)  # eof
        # arm once per FRAME (matching the tcp FrameReader's one hit per
        # read_frame), not per drained batch
        for _ in frames:
            await fp.async_hit("rpc.frame.recv")
        self.frames_received += len(frames)
        Stats.get().incr(M_FRAMES_RECEIVED, len(frames))
        return frames

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        peer = self.peer
        if peer is not None and not peer._closed:
            peer._push(("eof", None, None))
        self._wakeup.set()


class _LoopbackListener(Listener):
    def __init__(self, key: str, on_connection: ConnectionCallback,
                 loop: asyncio.AbstractEventLoop):
        self.key = key
        self._on_connection = on_connection
        self._loop = loop
        self.closed = False

    def make_connection(self) -> LoopbackConnection:
        client = LoopbackConnection(self._loop)
        server = LoopbackConnection(self._loop)
        client.peer, server.peer = server, client
        t = asyncio.ensure_future(self._on_connection(server))
        t.add_done_callback(_reap_connection_task)
        return client

    def close(self) -> None:
        self.closed = True
        if _LOOPBACK_REGISTRY.get(self.key) is self:
            del _LOOPBACK_REGISTRY[self.key]


_LOOPBACK_REGISTRY: Dict[str, _LoopbackListener] = {}


class LoopbackTransport(Transport):
    scheme = "loopback"

    async def connect(self, ep: Endpoint, *, ssl_manager=None) -> Connection:
        if ssl_manager is not None:
            raise RpcTransportConfigError(
                "TLS requires the tcp transport (loopback endpoint "
                f"{ep.key!r})")
        listener = _LOOPBACK_REGISTRY.get(ep.key)
        if listener is None or listener.closed:
            raise ConnectionRefusedError(
                f"loopback endpoint {ep.key!r} is not served by this "
                f"process (in-process transport; did you mean tcp/uds?)")
        if listener._loop is not asyncio.get_running_loop():
            raise ConnectionRefusedError(
                f"loopback endpoint {ep.key!r} is served from a "
                f"different event loop")
        return listener.make_connection()

    async def accept(self, ep: Endpoint, on_connection: ConnectionCallback,
                     *, ssl_manager=None) -> Listener:
        if ssl_manager is not None:
            raise RpcTransportConfigError(
                "TLS requires the tcp transport (loopback endpoint "
                f"{ep.key!r})")
        existing = _LOOPBACK_REGISTRY.get(ep.key)
        if existing is not None and not existing.closed:
            raise OSError(
                f"loopback endpoint {ep.key!r} already registered")
        listener = _LoopbackListener(
            ep.key, on_connection, asyncio.get_running_loop())
        _LOOPBACK_REGISTRY[ep.key] = listener
        return listener


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


_TRANSPORTS: Dict[str, Transport] = {
    "tcp": TcpTransport(),
    "uds": UdsTransport(),
    "loopback": LoopbackTransport(),
}


def get_transport(scheme: str) -> Transport:
    tr = _TRANSPORTS.get(scheme)
    if tr is None:
        raise RpcTransportConfigError(
            f"unknown transport scheme {scheme!r} "
            f"(expected one of {'|'.join(SCHEMES)})")
    return tr
