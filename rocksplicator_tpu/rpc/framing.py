"""Length-prefixed framing over asyncio streams.

Frame layout (all little-endian):
    magic   u16  = 0x5254 ("RT")
    flags   u16  (bit 0 = payload zlib-compressed)
    hlen    u32  header length
    plen    u32  payload length (on-wire, i.e. compressed when flagged)
    header  [hlen] JSON
    payload [plen] raw binary region

The reference's analog is the fbthrift header protocol with optional
snappy/zstd channel transforms (common/thrift_client_pool.h:277-284);
payloads above a threshold are transparently zlib-compressed here (zlib is
the in-image codec; the flag word leaves room for others).

The JSON header doubles as the out-of-band metadata channel (the fbthrift
THeader analog): sampled trace context rides it under the reserved
top-level ``"trace"`` key (observability/context.py) — injected by
rpc/client.py, restored by rpc/server.py, and printed by tools/rpcgrep.py
so wire captures join in-process traces on one id.
"""

from __future__ import annotations

import asyncio
import struct
import zlib
from typing import List, Tuple

from ..storage import rlz
from ..testing import failpoints as fp

MAGIC = 0x5254
FLAG_PAYLOAD_ZLIB = 1
# RLZ1 transform (storage/rlz.py): snappy-class speed — the preferred
# codec when the native module is loaded; receivers always handle both.
FLAG_PAYLOAD_RLZ = 2
_HEADER = struct.Struct("<HHII")
MAX_FRAME_BYTES = 256 * 1024 * 1024
# payloads in this size band are compressed (WAL batches and other mid-size
# messages); tiny ones aren't worth the CPU and huge ones would stall the
# event loop with synchronous zlib (bulk data rides the object store, not
# RPC frames)
COMPRESS_THRESHOLD = 4096
COMPRESS_MAX = 8 * 1024 * 1024
# frames at or below this size are sent as ONE transport write (single
# send syscall) instead of one write per header/chunk
_JOIN_MAX = 256 * 1024


async def write_frame(
    writer: asyncio.StreamWriter, header: bytes, payload_chunks: List[bytes]
) -> None:
    plen = sum(len(c) for c in payload_chunks)
    flags = 0
    if COMPRESS_THRESHOLD <= plen <= COMPRESS_MAX:
        raw = b"".join(payload_chunks)
        # rlz only with the native codec: the pure-Python encoder would
        # stall the event loop far longer than zlib's C one
        if rlz.native_available():
            compressed, flag = rlz.compress(raw), FLAG_PAYLOAD_RLZ
        else:
            compressed, flag = zlib.compress(raw, 1), FLAG_PAYLOAD_ZLIB
        if len(compressed) < plen:
            payload_chunks = [compressed]
            plen = len(compressed)
            flags |= flag
    await fp.async_hit("rpc.frame.send")
    cut = fp.torn_point(
        "rpc.frame.send", _HEADER.size + len(header) + plen)
    if cut is not None:
        # torn frame: a prefix reaches the peer (short/desynced stream →
        # clean decode error + reconnect there), the sender sees a
        # failed send (OSError) and must treat the connection as dead
        frame = b"".join(
            [_HEADER.pack(MAGIC, flags, len(header), plen), header,
             *payload_chunks])[:cut]
        writer.write(frame)
        await writer.drain()
        raise fp.FailpointError(f"torn frame at +{cut}B")
    # ONE transport write: each StreamWriter.write() attempts an eager
    # send syscall when the buffer is empty, so the old 3..N-write frame
    # cost 3..N sends. Joining costs one memcpy of an already-small
    # (usually compressed) frame; on sandboxed/virtualized kernels where
    # a syscall is micro-seconds, this is a large share of RPC latency.
    # Frames above the join cap keep per-chunk writes (no big copies).
    if plen <= _JOIN_MAX:
        writer.write(b"".join(
            [_HEADER.pack(MAGIC, flags, len(header), plen), header,
             *payload_chunks]))
    else:
        writer.write(_HEADER.pack(MAGIC, flags, len(header), plen))
        writer.write(header)
        for chunk in payload_chunks:
            writer.write(chunk)
    await writer.drain()


class FrameReader:
    def __init__(self, reader: asyncio.StreamReader):
        self._reader = reader

    async def read_frame(self) -> Tuple[memoryview, memoryview]:
        """Returns (header, payload) memoryviews. Raises
        asyncio.IncompleteReadError on clean EOF."""
        await fp.async_hit("rpc.frame.recv")
        head = await self._reader.readexactly(_HEADER.size)
        magic, flags, hlen, plen = _HEADER.unpack(head)
        if magic != MAGIC:
            raise ValueError(f"bad frame magic: {magic:#x}")
        if flags & ~(FLAG_PAYLOAD_ZLIB | FLAG_PAYLOAD_RLZ):
            # a transform this reader doesn't know: fail loudly instead
            # of handing compressed bytes up as a valid payload
            raise ValueError(f"unknown frame flags: {flags:#x}")
        if hlen + plen > MAX_FRAME_BYTES:
            raise ValueError(f"frame too large: {hlen + plen}")
        body = await self._reader.readexactly(hlen + plen)
        view = memoryview(body)
        header, payload = view[:hlen], view[hlen:]
        if flags & FLAG_PAYLOAD_ZLIB:
            # bounded decompression: never materialize more than the frame
            # cap no matter what the peer claims (zip-bomb guard)
            d = zlib.decompressobj()
            raw = d.decompress(bytes(payload), MAX_FRAME_BYTES + 1)
            if len(raw) > MAX_FRAME_BYTES or d.unconsumed_tail or d.unused_data:
                raise ValueError("malformed or oversized compressed frame")
            payload = memoryview(raw)
        elif flags & FLAG_PAYLOAD_RLZ:
            # rlz.decompress is bounded by construction (same guard)
            payload = memoryview(
                rlz.decompress(bytes(payload), MAX_FRAME_BYTES))
        return header, payload
