"""Length-prefixed framing over asyncio streams.

Frame layout (all little-endian):
    magic   u16  = 0x5254 ("RT")
    flags   u16  (bit 0 = payload zlib-compressed)
    hlen    u32  header length
    plen    u32  payload length (on-wire, i.e. compressed when flagged)
    header  [hlen] JSON
    payload [plen] raw binary region

The reference's analog is the fbthrift header protocol with optional
snappy/zstd channel transforms (common/thrift_client_pool.h:277-284);
payloads above a threshold are transparently zlib-compressed here (zlib is
the in-image codec; the flag word leaves room for others).

The JSON header doubles as the out-of-band metadata channel (the fbthrift
THeader analog): sampled trace context rides it under the reserved
top-level ``"trace"`` key (observability/context.py) — injected by
rpc/client.py, restored by rpc/server.py, and printed by tools/rpcgrep.py
so wire captures join in-process traces on one id.
"""

from __future__ import annotations

import asyncio
import struct
import zlib
from typing import List, Tuple

from ..storage import rlz
from ..testing import failpoints as fp

MAGIC = 0x5254
FLAG_PAYLOAD_ZLIB = 1
# RLZ1 transform (storage/rlz.py): snappy-class speed — the preferred
# codec when the native module is loaded; receivers always handle both.
FLAG_PAYLOAD_RLZ = 2
_HEADER = struct.Struct("<HHII")
MAX_FRAME_BYTES = 256 * 1024 * 1024
# payloads in this size band are compressed (WAL batches and other mid-size
# messages); tiny ones aren't worth the CPU and huge ones would stall the
# event loop with synchronous zlib (bulk data rides the object store, not
# RPC frames)
COMPRESS_THRESHOLD = 4096
COMPRESS_MAX = 8 * 1024 * 1024
# frames at or below this size are sent as ONE transport write (single
# send syscall) instead of one write per header/chunk
_JOIN_MAX = 256 * 1024


def _maybe_compress(
    payload_chunks: List[bytes], plen: int
) -> Tuple[List[bytes], int, int]:
    """The channel transform: returns (chunks, plen, flags)."""
    if COMPRESS_THRESHOLD <= plen <= COMPRESS_MAX:
        raw = b"".join(payload_chunks)
        # rlz only with the native codec: the pure-Python encoder would
        # stall the event loop far longer than zlib's C one
        if rlz.native_available():
            compressed, flag = rlz.compress(raw), FLAG_PAYLOAD_RLZ
        else:
            compressed, flag = zlib.compress(raw, 1), FLAG_PAYLOAD_ZLIB
        if len(compressed) < plen:
            return [compressed], len(compressed), flag
    return payload_chunks, plen, 0


def encode_wire_parts(
    header: bytes, payload_chunks: List[bytes]
) -> Tuple[List[bytes], int]:
    """One frame as a list of wire buffers (length-prefix struct, header,
    payload chunks) plus the total on-wire length — WITHOUT joining them,
    so a vectored transport can hand the list straight to ``sendmsg`` as
    an iovec (headers interleaved zero-copy) and a stream transport can
    decide whether a join is worth one memcpy."""
    plen = sum(len(c) for c in payload_chunks)
    payload_chunks, plen, flags = _maybe_compress(payload_chunks, plen)
    parts = [_HEADER.pack(MAGIC, flags, len(header), plen), header,
             *payload_chunks]
    return parts, _HEADER.size + len(header) + plen


def _decode_payload(flags: int, payload: memoryview) -> memoryview:
    if flags & FLAG_PAYLOAD_ZLIB:
        # bounded decompression: never materialize more than the frame
        # cap no matter what the peer claims (zip-bomb guard)
        d = zlib.decompressobj()
        raw = d.decompress(bytes(payload), MAX_FRAME_BYTES + 1)
        if len(raw) > MAX_FRAME_BYTES or d.unconsumed_tail or d.unused_data:
            raise ValueError("malformed or oversized compressed frame")
        return memoryview(raw)
    if flags & FLAG_PAYLOAD_RLZ:
        # rlz.decompress is bounded by construction (same guard)
        return memoryview(rlz.decompress(bytes(payload), MAX_FRAME_BYTES))
    return payload


def _check_frame_head(magic: int, flags: int, hlen: int, plen: int) -> None:
    if magic != MAGIC:
        raise ValueError(f"bad frame magic: {magic:#x}")
    if flags & ~(FLAG_PAYLOAD_ZLIB | FLAG_PAYLOAD_RLZ):
        # a transform this reader doesn't know: fail loudly instead
        # of handing compressed bytes up as a valid payload
        raise ValueError(f"unknown frame flags: {flags:#x}")
    if hlen + plen > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {hlen + plen}")


async def write_frame(
    writer: asyncio.StreamWriter, header: bytes, payload_chunks: List[bytes]
) -> None:
    parts, wire_len = encode_wire_parts(header, payload_chunks)
    plen = wire_len - _HEADER.size - len(header)
    await fp.async_hit("rpc.frame.send")
    cut = fp.torn_point("rpc.frame.send", wire_len)
    if cut is not None:
        # torn frame: a prefix reaches the peer (short/desynced stream →
        # clean decode error + reconnect there), the sender sees a
        # failed send (OSError) and must treat the connection as dead
        writer.write(b"".join(parts)[:cut])
        await writer.drain()
        raise fp.FailpointError(f"torn frame at +{cut}B")
    # ONE transport write: each StreamWriter.write() attempts an eager
    # send syscall when the buffer is empty, so the old 3..N-write frame
    # cost 3..N sends. Joining costs one memcpy of an already-small
    # (usually compressed) frame; on sandboxed/virtualized kernels where
    # a syscall is micro-seconds, this is a large share of RPC latency.
    # Frames above the join cap keep per-chunk writes (no big copies).
    if plen <= _JOIN_MAX:
        writer.write(b"".join(parts))
    else:
        for part in parts:
            writer.write(part)
    await writer.drain()


class FrameReader:
    def __init__(self, reader: asyncio.StreamReader):
        self._reader = reader

    async def read_frame(self) -> Tuple[memoryview, memoryview]:
        """Returns (header, payload) memoryviews. Raises
        asyncio.IncompleteReadError on clean EOF."""
        await fp.async_hit("rpc.frame.recv")
        head = await self._reader.readexactly(_HEADER.size)
        magic, flags, hlen, plen = _HEADER.unpack(head)
        _check_frame_head(magic, flags, hlen, plen)
        body = await self._reader.readexactly(hlen + plen)
        view = memoryview(body)
        return view[:hlen], _decode_payload(flags, view[hlen:])


class FrameBuffer:
    """Reusable receive buffer decoding MULTIPLE frames per ``recv_into``
    (the vectored-transport receive half: one syscall can complete many
    coalesced frames). Usage per receive round::

        view = fb.recv_view()          # writable tail of the buffer
        n = await loop.sock_recv_into(sock, view)
        view.release()                 # allow the bytearray to grow later
        fb.advance(n)
        frames = fb.pop_frames()       # [] if no complete frame yet

    Each popped frame's header/payload views reference a per-frame copy,
    so the underlying buffer is immediately reusable (the same ownership
    contract as ``FrameReader``'s readexactly result)."""

    def __init__(self, capacity: int = 64 * 1024):
        self._buf = bytearray(max(capacity, _HEADER.size))
        self._start = 0
        self._end = 0

    def pending(self) -> int:
        return self._end - self._start

    def recv_view(self, min_free: int = 16 * 1024) -> memoryview:
        """A writable view of the free tail, compacting/growing so at
        least ``min_free`` bytes (or the known remainder of a partially
        received frame) are available."""
        need = min_free
        avail = self.pending()
        if avail >= _HEADER.size:
            _magic, _flags, hlen, plen = _HEADER.unpack_from(
                self._buf, self._start)
            # size the buffer for the in-progress frame (validation is
            # pop_frames' job; a bogus length fails there, and the cap
            # bounds what we would ever allocate)
            total = _HEADER.size + min(hlen + plen, MAX_FRAME_BYTES)
            need = max(need, total - avail)
        if len(self._buf) - self._end < need:
            if self._start:
                self._buf[0:avail] = self._buf[self._start:self._end]
                self._start, self._end = 0, avail
            shortfall = need - (len(self._buf) - self._end)
            if shortfall > 0:
                self._buf.extend(bytes(shortfall))
        return memoryview(self._buf)[self._end:]

    def advance(self, n: int) -> None:
        self._end += n

    def feed(self, data: bytes) -> None:
        """Test/compat convenience: append already-received bytes."""
        view = self.recv_view(min_free=len(data))
        view[: len(data)] = data
        view.release()
        self.advance(len(data))

    def pop_frames(self) -> List[Tuple[memoryview, memoryview]]:
        """Decode every complete frame currently buffered. Raises
        ValueError on a corrupt head (desynced/torn stream) — the
        connection must be treated as dead, same as ``FrameReader``."""
        frames: List[Tuple[memoryview, memoryview]] = []
        while True:
            avail = self._end - self._start
            if avail < _HEADER.size:
                break
            magic, flags, hlen, plen = _HEADER.unpack_from(
                self._buf, self._start)
            _check_frame_head(magic, flags, hlen, plen)
            if avail < _HEADER.size + hlen + plen:
                break
            a = self._start + _HEADER.size
            body = bytes(memoryview(self._buf)[a:a + hlen + plen])
            view = memoryview(body)
            frames.append((view[:hlen], _decode_payload(flags, view[hlen:])))
            self._start = a + hlen + plen
        if self._start == self._end:
            self._start = self._end = 0
        return frames
