"""Length-prefixed framing over asyncio streams.

Frame layout (all little-endian):
    magic   u16  = 0x5254 ("RT")
    flags   u16  (reserved; bit 0 = header compressed — not yet used)
    hlen    u32  header length
    plen    u32  payload length
    header  [hlen] JSON
    payload [plen] raw binary region

The reference's analog is the fbthrift header protocol with optional
snappy/zstd transforms (common/thrift_client_pool.h:277-284); compression
flags are reserved in the header for the same purpose.
"""

from __future__ import annotations

import asyncio
import struct
from typing import List, Tuple

MAGIC = 0x5254
_HEADER = struct.Struct("<HHII")
MAX_FRAME_BYTES = 256 * 1024 * 1024


async def write_frame(
    writer: asyncio.StreamWriter, header: bytes, payload_chunks: List[bytes]
) -> None:
    plen = sum(len(c) for c in payload_chunks)
    writer.write(_HEADER.pack(MAGIC, 0, len(header), plen))
    writer.write(header)
    for chunk in payload_chunks:
        writer.write(chunk)
    await writer.drain()


class FrameReader:
    def __init__(self, reader: asyncio.StreamReader):
        self._reader = reader

    async def read_frame(self) -> Tuple[memoryview, memoryview]:
        """Returns (header, payload) memoryviews. Raises
        asyncio.IncompleteReadError on clean EOF."""
        head = await self._reader.readexactly(_HEADER.size)
        magic, _flags, hlen, plen = _HEADER.unpack(head)
        if magic != MAGIC:
            raise ValueError(f"bad frame magic: {magic:#x}")
        if hlen + plen > MAX_FRAME_BYTES:
            raise ValueError(f"frame too large: {hlen + plen}")
        body = await self._reader.readexactly(hlen + plen)
        view = memoryview(body)
        return view[:hlen], view[hlen:]
