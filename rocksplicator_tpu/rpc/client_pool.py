"""RpcClientPool: addr → healthy client cache with reconnect throttling.

Reference: common/thrift_client_pool.h:104-479 — per-IO-thread addr→channel
maps with health callbacks, reconnect throttling, and stale-channel cleanup.
Here one pool per process (single IO loop), same contract: ``get_client``
returns a connected client, reuses healthy ones, throttles reconnect storms
to a bad host, and evicts dead clients.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Tuple

from .client import RpcClient
from .errors import RpcConnectionError, RpcTransportConfigError
from ..observability.span import start_span

RECONNECT_THROTTLE_SEC = 1.0


class RpcClientPool:
    def __init__(self, connect_timeout: float = 5.0, ssl_manager=None):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._connect_timeout = connect_timeout
        self._locks: Dict[Tuple[str, int], asyncio.Lock] = {}
        # client-side SslContextManager: enables TLS (and presents the
        # client cert for mutual-TLS auth) on every pooled connection.
        # The pool claims the manager's background refresh thread so that
        # get() on the event loop never does cert-file IO inline.
        self._ssl_manager = ssl_manager
        self._ssl_claimed = ssl_manager is not None
        if ssl_manager is not None:
            ssl_manager.ensure_auto_refresh()

    async def get_client(self, host: str, port: int) -> RpcClient:
        addr = (host, port)
        client = self._clients.get(addr)
        if client is not None and client.is_good:
            # healthy-client fast path stays span-free: this is the per-call
            # hot path; only the slow (lock + connect) path is attributed
            return client
        lock = self._locks.setdefault(addr, asyncio.Lock())
        # The acquire span splits the slow path into queue wait (callers
        # serialized behind a peer's connect/throttle) vs the connect
        # itself — the ISSUE's "queue wait vs connect vs RTT" breakdown
        # (RTT lives in RpcClient.call).
        with start_span("rpc.pool.acquire", peer=host, port=port) as sp:
            t0 = time.monotonic()
            async with lock:
                sp.annotate(
                    queue_wait_ms=round((time.monotonic() - t0) * 1e3, 3))
                client = self._clients.get(addr)
                if client is not None and client.is_good:
                    sp.annotate(reused=True)
                    return client
                # Reconnect throttling: if we very recently failed to
                # connect to this addr, fail fast instead of hammering it.
                if (
                    client is not None
                    and time.monotonic() - client.last_connect_attempt
                    < RECONNECT_THROTTLE_SEC
                ):
                    # the throttle must not re-classify the failure: a
                    # remembered misconfig stays RpcTransportConfigError
                    # (callers like the pull loop route it away from the
                    # leader-resolver escalation path)
                    if client.last_connect_config_error is not None:
                        raise RpcTransportConfigError(
                            f"{host}:{port} throttled after transport "
                            f"misconfig: {client.last_connect_config_error}")
                    raise RpcConnectionError(
                        f"{host}:{port} recently failed; throttled"
                    )
                if client is not None:
                    await client.close()
                client = RpcClient(host, port, self._connect_timeout,
                                   ssl_manager=self._ssl_manager)
                # Register before connecting so a failed attempt is
                # remembered for throttling.
                self._clients[addr] = client
                with start_span("rpc.pool.connect"):
                    await client.connect()
                return client

    async def call(self, host: str, port: int, method: str, args=None,
                   timeout: Optional[float] = 30.0,
                   tail_exempt: bool = False,
                   deadline_ms: Optional[float] = None,
                   tenant: Optional[str] = None):
        client = await self.get_client(host, port)
        return await client.call(method, args, timeout,
                                 tail_exempt=tail_exempt,
                                 deadline_ms=deadline_ms, tenant=tenant)

    def peek(self, host: str, port: int) -> Optional[RpcClient]:
        return self._clients.get((host, port))

    async def close(self) -> None:
        for client in list(self._clients.values()):
            await client.close()
        self._clients.clear()
        if self._ssl_manager is not None and self._ssl_claimed:
            # claim released exactly once even if close() is called again
            self._ssl_claimed = False
            self._ssl_manager.release_auto_refresh()
