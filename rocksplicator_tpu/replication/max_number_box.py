"""MaxNumberBox: tracks the max ACKed sequence number and wakes waiters.

Reference: rocksdb_replicator/max_number_box.h:38-83 — ``post(n)`` raises
the box's number and wakes waiters whose target ≤ n; ``wait(num, timeout)``
blocks leader writes in semi-sync/sync mode until the box reaches ``num``.
"""

from __future__ import annotations

import threading


class MaxNumberBox:
    def __init__(self, initial: int = 0):
        self._max = initial
        self._cond = threading.Condition()

    @property
    def value(self) -> int:
        with self._cond:
            return self._max

    def post(self, number: int) -> None:
        with self._cond:
            if number > self._max:
                self._max = number
                self._cond.notify_all()

    def wait(self, number: int, timeout_sec: float) -> bool:
        """True iff the box reached ``number`` within the timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: self._max >= number, timeout_sec)
