"""Compatibility shim: MaxNumberBox moved to ack_window.py.

The leader ack path now uses :class:`~.ack_window.AckWindow` (windowed
in-flight writes with ack futures); the plain max-watermark box remains
available here for existing importers and tests.
"""

from __future__ import annotations

from .ack_window import MaxNumberBox

__all__ = ["MaxNumberBox"]
