"""Replication wire protocol types.

Reference: rocksdb_replicator/thrift/replicator.thrift:21-92 —
``ReplicateRequest{seq_no, db_name, max_wait_ms, max_updates, role}``,
``Update{raw_data (zero-copy IOBuf), timestamp, seq_no}``,
``ReplicaRole{NOOP, FOLLOWER, LEADER, OBSERVER}``,
``ErrorCode{SOURCE_NOT_FOUND, SOURCE_READ_ERROR, SOURCE_REMOVED}``.

On the wire these travel as the RPC layer's dict messages; raw_data rides
the binary payload region (no copies, no base64).
"""

from __future__ import annotations

import enum


class ReplicaRole(str, enum.Enum):
    NOOP = "NOOP"          # serve locally, no replication
    FOLLOWER = "FOLLOWER"  # pull from upstream, ACK counts (mode 1/2)
    LEADER = "LEADER"      # accept writes, serve updates
    OBSERVER = "OBSERVER"  # pull from upstream, ACK does NOT count (CDC)


class ReplicateErrorCode(str, enum.Enum):
    SOURCE_NOT_FOUND = "SOURCE_NOT_FOUND"
    SOURCE_READ_ERROR = "SOURCE_READ_ERROR"
    SOURCE_REMOVED = "SOURCE_REMOVED"
    # Fencing (the ZK-zxid-epoch analog, threaded end to end from the
    # controller's assignment epoch): a replicate/ack frame carrying a
    # NEWER epoch than the serving db proves a newer leader was promoted
    # — the server is deposed and must reject the frame, fail its
    # pending ack window, and refuse further writes. A frame carrying an
    # OLDER epoch than the puller's known epoch marks a stale (deposed)
    # upstream whose updates must not be applied.
    STALE_EPOCH = "STALE_EPOCH"
    # Bounded-staleness follower reads (round 13): the serving replica's
    # applied position is (or cannot be proven to be) within the
    # client's lag bound of the leader's committed sequence. NOT a
    # lineage error — the client should bounce the read to the leader
    # (or another replica); the router's follower-ok policy does exactly
    # that.
    STALE_READ = "STALE_READ"
    # The write/read entry was asked of a non-leader (reads with a
    # leader-only requirement, writes anywhere but the leader).
    NOT_LEADER = "NOT_LEADER"
    # The puller's position predates the oldest WAL record this server
    # can still serve (purge outran the puller): WAL catch-up can NEVER
    # succeed — the puller must flag itself stalled so the control
    # plane rebuilds it from a snapshot (rocksdb GetUpdatesSince
    # NotFound parity; round 15, found by the reshard chaos).
    WAL_GAP = "WAL_GAP"
    # Live shard move (round 15): the leader briefly refuses NEW writes
    # while a move's cutover drains the WAL tail to the target — the
    # write-pause that BOUNDS catch-up on a hot shard. Always
    # auto-expiring (a crashed move coordinator can never wedge the
    # shard); clients retry after the pause window, reads are unaffected.
    WRITE_PAUSED = "WRITE_PAUSED"


# Read-path counters (round 13 — bounded-staleness follower reads).
# Names follow the tools/rstpu_check.py dotted.name grammar.
READ_METRICS = dict(
    leader_served="reads.leader_served",
    follower_served="reads.follower_served",
    # lag-bound bounce (the follower is too far behind the client's
    # max_lag, or its view of the leader's commit point is too old to
    # verify the bound) — distinct from the fencing rejection below
    stale_rejected="reads.stale_rejected",
    # lineage (fencing) rejection: the read carried a newer epoch than
    # the serving replica knows — the replica is on a deposed lineage
    stale_epoch_rejected="reads.stale_epoch_rejected",
    # upstream commit-point probes issued by bounded follower reads
    # whose cached estimate was older than read_info_ttl_ms
    probes="reads.upstream_probes",
)


# Counter/metric names (reference rocksdb_replicator/replicator_stats.{h,cpp})
REPLICATOR_METRICS = dict(
    leader_writes="replicator.leader_writes",
    leader_write_bytes="replicator.leader_write_bytes",
    leader_write_ms="replicator.leader_write_ms",
    ack_waits="replicator.ack_waits",
    ack_timeouts="replicator.ack_timeouts",
    ack_degraded="replicator.ack_degraded_mode",
    replicate_requests="replicator.replicate_requests",
    replicate_updates_sent="replicator.replicate_updates_sent",
    replicate_bytes_sent="replicator.replicate_bytes_sent",
    pull_requests="replicator.pull_requests",
    pull_updates_applied="replicator.pull_updates_applied",
    pull_bytes_applied="replicator.pull_bytes_applied",
    pull_errors="replicator.pull_errors",
    upstream_resets="replicator.upstream_resets",
    stale_epoch_rejects="replicator.stale_epoch_rejects",
    fenced="replicator.fenced",
    write_window_full="replicator.write_window_full_rejects",
    write_paused="replicator.write_paused_rejects",
    wal_gap_stalls="replicator.wal_gap_stalls",
    diverged_stalls="replicator.diverged_stalls",
    replication_lag_ms="replicator.replication_lag_ms",
    iter_cache_hits="replicator.iter_cache_hits",
    iter_cache_misses="replicator.iter_cache_misses",
    # Multiplexed per-peer pull sessions (round 22). A mux pull is ONE
    # long-poll frame carrying every shard this node pulls from that
    # peer; the park counters are the fleet-density A/B's primary
    # signal — at 100 idle shards the per-shard path parks 100 serves
    # per poll window, the mux path parks one per peer session.
    mux_pulls="replicator.mux_pulls",                # client: mux rounds
    mux_requests="replicator.mux_requests",          # server: mux serves
    mux_sections="replicator.mux_sections_served",   # server: sections
    mux_parks="replicator.mux_parks",                # server: session parks
    longpoll_parks="replicator.longpoll_parks",      # server: per-shard parks
    mux_fallbacks="replicator.mux_fallbacks",        # legacy-peer fallbacks
)
