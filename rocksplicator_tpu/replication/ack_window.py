"""AckWindow: a windowed registry of in-flight leader writes awaiting
follower ACKs.

Replaces :class:`MaxNumberBox` on the leader write path. The box's
``wait(num, timeout)`` blocked the writer thread per write — exactly one
write per shard could be in flight, so the ack round-trip (pull long-poll
RTT, ~95% of a semi-sync write per the round-6 traces) was paid serially
by every write. The window instead hands the writer a *future*:

- ``register(target_seq, ...)`` parks a waiter in a min-heap keyed by
  ``target_seq`` and returns immediately (flow control aside);
- ``post(n)`` resolves **every** waiter with ``target_seq <= n`` in one
  heap-pop pass — no Condition broadcast, no thundering herd of waiters
  re-checking a predicate (each ``MaxNumberBox.post`` woke all waiters;
  here each waiter is touched exactly once, when it resolves);
- a per-waiter deadline (min-heap keyed by deadline) preserves the
  reference's ack-timeout semantics (replicated_db.cpp:236-273) without
  a blocked thread: expiry is driven by the owner's event-loop timer via
  :meth:`expire_due`, so a pure-async writer's future still resolves
  when no follower ever acks;
- ``capacity`` bounds in-flight writes per shard (default from
  ``ReplicationFlags.write_window``): ``register`` blocks only when the
  window is full, which is the back-pressure that keeps an unacked
  backlog from growing without bound.

Resolution (ack, timeout, or close) is reported through the owner's
``on_resolve(waiter, acked)`` callback, invoked OUTSIDE the window lock
in target_seq order — the one place stats, the degradation state
machine, deferred ``repl.ack_wait`` spans, and the public future are
settled.

``MaxNumberBox`` itself now lives here too (the general max-watermark
utility is still used by tests and stays exported);
``max_number_box.py`` re-exports it for compatibility.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple


class MaxNumberBox:
    """Tracks the max ACKed sequence number and wakes waiters.

    Reference: rocksdb_replicator/max_number_box.h:38-83 — ``post(n)``
    raises the box's number and wakes waiters whose target ≤ n;
    ``wait(num, timeout)`` blocks until the box reaches ``num``.
    """

    def __init__(self, initial: int = 0):
        self._max = initial
        self._cond = threading.Condition()

    @property
    def value(self) -> int:
        with self._cond:
            return self._max

    def post(self, number: int) -> None:
        with self._cond:
            if number > self._max:
                self._max = number
                self._cond.notify_all()

    def wait(self, number: int, timeout_sec: float) -> bool:
        """True iff the box reached ``number`` within the timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: self._max >= number, timeout_sec)


class AckWaiter:
    """One in-flight write awaiting its follower ACK.

    ``future`` resolves to the write's start seq once the ack arrived OR
    the per-write timeout expired (mirroring the blocking path, which
    returned the seq either way and left timeout accounting to the
    degradation state machine); ``acked`` records which it was. ``span``
    optionally holds a deferred ``repl.ack_wait`` span finished at
    resolution time, so sampled traces show the real (overlapping)
    ack-wait intervals under pipelining.
    """

    __slots__ = ("target_seq", "seq", "deadline", "future", "acked",
                 "span", "done")

    def __init__(self, target_seq: int, seq: int, deadline: float,
                 span=None):
        self.target_seq = target_seq
        self.seq = seq
        self.deadline = deadline
        self.future: Future = Future()
        self.acked = False
        self.span = span
        self.done = False

    def result(self, timeout: Optional[float] = None) -> int:
        """Block until resolution; returns the write's start seq."""
        return self.future.result(timeout)


# resolved immediately at creation: mode-0 / non-leader writes need no ack
def resolved_waiter(seq: int) -> AckWaiter:
    w = AckWaiter(seq, seq, 0.0)
    w.done = True
    w.acked = True
    w.future.set_result(seq)
    return w


class AckWindow:
    """Min-heap ack-future registry with per-shard flow control."""

    def __init__(
        self,
        capacity: int,
        on_resolve: Optional[Callable[[AckWaiter, bool], None]] = None,
        initial: int = 0,
    ):
        self._capacity = max(1, int(capacity))
        self._on_resolve = on_resolve
        self._max = initial
        self._cond = threading.Condition()
        self._tie = itertools.count()  # heap tiebreaker (waiters not orderable)
        self._by_seq: List[Tuple[int, int, AckWaiter]] = []
        self._by_deadline: List[Tuple[float, int, AckWaiter]] = []
        self._inflight = 0
        self._closed = False

    # -- introspection (lock-free reads of ints are atomic enough) --------

    @property
    def value(self) -> int:
        """Max ACKed sequence number (MaxNumberBox-compatible)."""
        return self._max

    @property
    def depth(self) -> int:
        """Current number of in-flight (unresolved) waiters."""
        return self._inflight

    @property
    def capacity(self) -> int:
        return self._capacity

    # -- registration (writer threads) ------------------------------------

    def register(self, target_seq: int, seq: int, timeout_sec: float,
                 span=None) -> AckWaiter:
        """Park a waiter for ``target_seq``. Blocks only while the window
        is at capacity (flow control); a closed window resolves the
        waiter immediately as not-acked."""
        now = time.monotonic()
        w = AckWaiter(target_seq, seq, now + timeout_sec, span)
        with self._cond:
            while self._inflight >= self._capacity and not self._closed:
                # Slots free on ack (post) or expiry (the owner's loop
                # timer). Bounded waits keep this robust if the timer is
                # torn down mid-shutdown: each wakeup re-checks _closed.
                self._cond.wait(0.05)
            if self._closed:
                w.done = True
                self._settle([(w, False)])
                return w
            if self._max >= target_seq:
                # ack already arrived (e.g. a mode-2 pull confirmed past
                # this seq before the writer registered)
                w.done = True
                w.acked = True
                self._settle([(w, True)])
                return w
            tie = next(self._tie)
            heapq.heappush(self._by_seq, (target_seq, tie, w))
            heapq.heappush(self._by_deadline, (w.deadline, tie, w))
            self._inflight += 1
        return w

    # -- resolution (loop thread / server path) ----------------------------

    def post(self, number: int) -> int:
        """Raise the ack watermark; resolve every waiter ≤ number in one
        pass. Returns how many waiters resolved."""
        settled: List[Tuple[AckWaiter, bool]] = []
        with self._cond:
            if number > self._max:
                self._max = number
            while self._by_seq and self._by_seq[0][0] <= self._max:
                _, _, w = heapq.heappop(self._by_seq)
                if w.done:
                    continue  # lazily-deleted (expired) entry
                w.done = True
                w.acked = True
                self._inflight -= 1
                settled.append((w, True))
            if settled:
                self._cond.notify_all()  # free flow-control waiters
        self._settle(settled)
        return len(settled)

    def expire_due(self, now: Optional[float] = None) -> Optional[float]:
        """Resolve (not-acked) every waiter whose deadline passed.
        Returns the next pending deadline, or None when idle — the
        owner's timer re-arms off this."""
        if now is None:
            now = time.monotonic()
        settled: List[Tuple[AckWaiter, bool]] = []
        next_deadline: Optional[float] = None
        with self._cond:
            while self._by_deadline:
                deadline, _, w = self._by_deadline[0]
                if w.done:
                    heapq.heappop(self._by_deadline)
                    continue
                if deadline > now:
                    next_deadline = deadline
                    break
                heapq.heappop(self._by_deadline)
                w.done = True
                self._inflight -= 1
                settled.append((w, False))
            if settled:
                self._cond.notify_all()
        self._settle(settled)
        return next_deadline

    def close(self) -> None:
        """Resolve everything still in flight (not-acked) and refuse new
        registrations — no writer may hang across a stop()."""
        settled: List[Tuple[AckWaiter, bool]] = []
        with self._cond:
            self._closed = True
            while self._by_seq:
                _, _, w = heapq.heappop(self._by_seq)
                if w.done:
                    continue
                w.done = True
                self._inflight -= 1
                settled.append((w, False))
            self._by_deadline.clear()
            self._cond.notify_all()
        self._settle(settled)

    # -- internal ----------------------------------------------------------

    def _settle(self, settled: List[Tuple[AckWaiter, bool]]) -> None:
        """Run owner accounting + resolve futures OUTSIDE the lock, in
        target_seq order (post pops in seq order already; expiry batches
        are sorted here so the degradation counter sees writes in order)."""
        if not settled:
            return
        settled.sort(key=lambda pair: pair[0].target_seq)
        cb = self._on_resolve
        for w, acked in settled:
            if cb is not None:
                try:
                    cb(w, acked)
                except Exception:  # owner accounting must never wedge acks
                    pass
            w.future.set_result(w.seq)
