"""ReplicatedDB: the per-shard replication state machine.

Reference: rocksdb_replicator/replicated_db.cpp (613 LoC) — three faces:
- **leader write path** (``write``): stamp wall-clock ms into the batch,
  write via DbWrapper, wake parked long-polls, and in mode 1/2 wait for a
  follower ACK with fail-fast degradation (replicated_db.cpp:103-166,
  236-273);
- **server path** (``handle_replicate_request``): post ACKs from follower
  pulls, park on the notifier up to max_wait_ms, then serve ≤ max_updates
  batches from a cached WAL cursor (replicated_db.cpp:435-575);
- **follower path** (``pull loop``): long-poll the upstream, apply raw
  batches via DbWrapper, track lag from embedded timestamps, and on errors
  back off with randomized delay / reset upstream via the leader resolver
  (replicated_db.cpp:314-433, 278-312).

Replication modes (replicated_db.cpp:59-64): 0 async, 1 semi-sync (ACK
when the response carrying the write is sent to a follower), 2 sync (ACK
when a follower's next pull confirms the seq was applied).
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional, Tuple

from ..observability.context import wire_context
from ..observability.span import start_span
from ..rpc.client_pool import RpcClientPool
from ..rpc.errors import RpcApplicationError, RpcConnectionError, RpcError
from ..storage.records import WriteBatch, decode_batch
from ..utils.misc import now_ms
from ..utils.stats import Stats, tagged
from .cond_var import AsyncNotifier
from .db_wrapper import DbWrapper
from .iter_cache import IterCache
from .max_number_box import MaxNumberBox
from .wire import REPLICATOR_METRICS as M
from .wire import ReplicaRole, ReplicateErrorCode

log = logging.getLogger(__name__)

LeaderResolver = Callable[[str], Optional[Tuple[str, int]]]


@dataclass
class ReplicationFlags:
    """Defaults mirror the reference gflags (replicated_db.cpp:36-90)."""

    max_updates_per_response: int = 50
    server_long_poll_ms: int = 10_000
    pull_error_delay_min_ms: int = 5_000
    pull_error_delay_max_ms: int = 10_000
    ack_timeout_ms: int = 2_000
    degraded_ack_timeout_ms: int = 10
    consecutive_timeouts_to_degrade: int = 100
    upstream_reset_sample_rate: float = 0.1
    # pulls from a non-leader that return nothing this many times in a row
    # trigger an upstream reset (replicated_db.cpp:392-408 heuristic)
    empty_pulls_before_reset: int = 5
    # consecutive CONNECTION errors to the same upstream force a resolver
    # query (no sampling): a steady follower whose leader died gets no
    # state transition — without escalation its repoint waits on the 10%
    # sample × 5-10s backoff (~75 s expected; observed blowing the soak
    # failover convergence window at 4000 shards)
    conn_errors_before_forced_reset: int = 3
    pull_rpc_margin_ms: int = 5_000


class ReplicatedDB:
    def __init__(
        self,
        name: str,
        wrapper: DbWrapper,
        role: ReplicaRole,
        loop: asyncio.AbstractEventLoop,
        executor: ThreadPoolExecutor,
        pool: RpcClientPool,
        upstream_addr: Optional[Tuple[str, int]] = None,
        replication_mode: int = 0,
        flags: Optional[ReplicationFlags] = None,
        leader_resolver: Optional[LeaderResolver] = None,
    ):
        self.name = name
        self.wrapper = wrapper
        self.role = role
        self.replication_mode = replication_mode
        self.upstream_addr = upstream_addr
        self.flags = flags or ReplicationFlags()
        self._loop = loop
        self._executor = executor
        self._pool = pool
        self._leader_resolver = leader_resolver
        self._notifier = AsyncNotifier(loop)
        self._acked = MaxNumberBox()
        self._iter_cache = IterCache()
        self._removed = False
        self._pull_task: Optional[asyncio.Task] = None
        # ACK degradation state (replicated_db.cpp:236-273)
        self._consecutive_ack_timeouts = 0
        self._degraded = False
        self._empty_pulls = 0
        self._conn_errors = 0
        self._stats = Stats.get()
        # seq -> wire trace context of a SAMPLED write at that seq: lets the
        # serve path attach the originating write's trace to the updates it
        # ships, so a follower's apply span joins the LEADER's write trace
        # (and re-records here for chained downstreams) — one stitched
        # trace across the whole replication chain. Bounded; empty when
        # tracing is off, so the hot serve/apply paths pay one falsy check.
        self._write_traces: dict = {}
        self._write_traces_lock = threading.Lock()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.role in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER):
            if self.upstream_addr is None:
                raise ValueError(f"{self.name}: {self.role} requires an upstream")
            self._pull_task = asyncio.run_coroutine_threadsafe(
                self._pull_loop(), self._loop
            )

    def stop(self) -> None:
        self._removed = True
        task = self._pull_task
        if task is not None:
            self._loop.call_soon_threadsafe(task.cancel)
            self._pull_task = None
        self._notifier.notify_all_threadsafe()
        self._iter_cache.clear()

    @property
    def removed(self) -> bool:
        return self._removed

    # ------------------------------------------------------------------
    # leader write path (any thread)
    # ------------------------------------------------------------------

    def write(self, batch: WriteBatch) -> int:
        if self.role not in (ReplicaRole.LEADER, ReplicaRole.NOOP):
            raise RpcApplicationError(
                "NOT_LEADER", f"{self.name} role is {self.role.value}"
            )
        start = time.monotonic()
        # The per-write trace (ISSUE: "profile one write's 4.6 ms"): root
        # span with wal_write (through fsync) and ack_wait phases. Head
        # sampled — with sampling off this costs one contextvar set/reset.
        with start_span("repl.write", db=self.name) as sp:
            batch.stamp_timestamp_ms()
            with start_span("repl.wal_write"):
                seq = self.wrapper.write_to_leader(batch)
            end_seq = seq + batch.count() - 1
            if sp.sampled:
                sp.annotate(seq=seq, bytes=batch.byte_size())
                self._remember_write_trace(seq, sp)
            self._stats.incr(M["leader_writes"])
            self._stats.incr(M["leader_write_bytes"], batch.byte_size())
            # Wake parked follower long-polls (no thread was held by them).
            self._notifier.notify_all_threadsafe()
            if (self.replication_mode in (1, 2)
                    and self.role is ReplicaRole.LEADER):
                self._write_wait_follower_ack(end_seq)
        self._stats.add_metric(M["leader_write_ms"], (time.monotonic() - start) * 1e3)
        return seq

    _WRITE_TRACE_CAP = 512

    def _remember_write_trace(self, seq: int, span) -> None:
        """Record a sampled write's (or applied update's) trace context by
        its start seq so downstream serving can propagate it in-band."""
        ctx = span.to_wire()
        with self._write_traces_lock:
            self._write_traces[seq] = ctx
            while len(self._write_traces) > self._WRITE_TRACE_CAP:
                self._write_traces.pop(next(iter(self._write_traces)))

    def _write_wait_follower_ack(self, target_seq: int) -> None:
        """replicated_db.cpp:236-273: 2000ms timeout normally; after 100
        consecutive timeouts drop to 10ms to fail fast; recover on the
        first success."""
        f = self.flags
        timeout_ms = (
            f.degraded_ack_timeout_ms if self._degraded else f.ack_timeout_ms
        )
        self._stats.incr(M["ack_waits"])
        with start_span("repl.ack_wait", target_seq=target_seq,
                        timeout_ms=timeout_ms) as sp:
            ok = self._acked.wait(target_seq, timeout_ms / 1000.0)
            sp.annotate(acked=ok, degraded=self._degraded)
        if ok:
            self._consecutive_ack_timeouts = 0
            if self._degraded:
                self._degraded = False
                log.info("%s: ACK degradation recovered", self.name)
        else:
            self._stats.incr(M["ack_timeouts"])
            self._consecutive_ack_timeouts += 1
            if (
                not self._degraded
                and self._consecutive_ack_timeouts
                >= f.consecutive_timeouts_to_degrade
            ):
                self._degraded = True
                self._stats.incr(M["ack_degraded"])
                log.warning("%s: entering degraded ACK mode", self.name)

    # ------------------------------------------------------------------
    # server path (loop thread)
    # ------------------------------------------------------------------

    async def handle_replicate_request(
        self,
        seq_no: int,
        max_wait_ms: Optional[int] = None,
        max_updates: Optional[int] = None,
        role: str = ReplicaRole.FOLLOWER.value,
    ) -> dict:
        """Serve updates after ``seq_no`` (the puller's latest applied seq).
        Returns {updates, latest_seq, source_role}; updates is empty on a
        long-poll timeout. source_role lets pullers detect they're polling
        a non-leader (upstream-reset heuristic, replicated_db.cpp:385-399)."""
        f = self.flags
        max_wait_ms = f.server_long_poll_ms if max_wait_ms is None else max_wait_ms
        max_updates = (
            f.max_updates_per_response if max_updates is None else max_updates
        )
        self._stats.incr(M["replicate_requests"])
        # Child of the puller's rpc.server span when the pull was sampled:
        # per-phase serve breakdown (seq read vs long-poll park vs WAL
        # read) — where a 10 s long-poll hides inside one "slow RPC".
        with start_span("repl.serve", db=self.name, from_role=role) as sp:
            # Mode-2 ACK: the puller's request proves it applied through
            # seq_no (replicated_db.cpp:450-456); OBSERVERs never count.
            if role != ReplicaRole.OBSERVER.value and self.replication_mode == 2:
                self._acked.post(seq_no)
            # latest_sequence_number takes the storage lock, which flush/
            # compaction can hold for seconds — never block the shared IO
            # loop on it.
            with start_span("repl.seq_read"):
                latest = await self._loop.run_in_executor(
                    self._executor, self.wrapper.latest_sequence_number
                )
            if latest <= seq_no and max_wait_ms > 0:
                with start_span("repl.longpoll_wait", max_wait_ms=max_wait_ms):
                    await self._notifier.wait(max_wait_ms / 1000.0)
                if self._removed:
                    raise RpcApplicationError(
                        ReplicateErrorCode.SOURCE_REMOVED.value, self.name
                    )
                with start_span("repl.seq_read"):
                    latest = await self._loop.run_in_executor(
                        self._executor, self.wrapper.latest_sequence_number
                    )
            if latest <= seq_no:
                return {"updates": [], "latest_seq": latest,
                        "source_role": self.role.value}
            try:
                with start_span("repl.wal_read") as sp_read:
                    updates = await self._loop.run_in_executor(
                        self._executor, self._read_updates, seq_no + 1,
                        max_updates
                    )
                    sp_read.annotate(updates=len(updates))
            except Exception as e:
                log.exception("%s: WAL read failed", self.name)
                raise RpcApplicationError(
                    ReplicateErrorCode.SOURCE_READ_ERROR.value, repr(e)
                ) from e
            # In-band trace propagation: updates whose originating write
            # (or upstream apply) was sampled carry that trace context, so
            # the puller's apply joins the write's trace across processes.
            if self._write_traces:
                with self._write_traces_lock:
                    for u in updates:
                        ctx = self._write_traces.get(u["seq_no"])
                        if ctx is not None:
                            u["trace"] = ctx
            # Mode-1 semi-sync ACK: posted when the response is handed to
            # the transport (replicated_db.cpp:543-546).
            if (
                updates
                and self.replication_mode == 1
                and role != ReplicaRole.OBSERVER.value
            ):
                last = updates[-1]
                self._acked.post(last["seq_no"] + last["count"] - 1)
            self._stats.incr(M["replicate_updates_sent"], len(updates))
            self._stats.incr(
                M["replicate_bytes_sent"],
                sum(len(u["raw_data"]) for u in updates),
            )
            sp.annotate(latest_seq=latest)
            return {"updates": updates, "latest_seq": latest,
                    "source_role": self.role.value}

    def _read_updates(self, from_seq: int, max_updates: int) -> List[dict]:
        """Executor-side WAL read using the cursor cache.

        Raises on a WAL gap (requested updates already purged) — the analog
        of rocksdb GetUpdatesSince returning NotFound, which tells the
        puller it must rebuild from a snapshot rather than silently skip."""
        it = self._iter_cache.take(from_seq)
        if it is None:
            it = self.wrapper.get_updates_from_leader(from_seq)
        updates: List[dict] = []
        next_seq = from_seq
        exhausted = True
        first = True
        for start_seq, raw in it:
            if first:
                first = False
                if start_seq > from_seq:
                    raise ValueError(
                        f"WAL gap: requested seq {from_seq}, oldest available "
                        f"{start_seq} (purged — puller must rebuild)"
                    )
            batch = decode_batch(raw)
            count = batch.count()
            updates.append(
                {
                    "seq_no": start_seq,
                    "count": count,
                    "raw_data": bytes(raw),
                    "timestamp": batch.extract_timestamp_ms(),
                }
            )
            next_seq = start_seq + count
            if len(updates) >= max_updates:
                exhausted = False
                break
        if not exhausted:
            self._iter_cache.put(next_seq, it)
        return updates

    # ------------------------------------------------------------------
    # follower pull path (loop thread)
    # ------------------------------------------------------------------

    async def _pull_loop(self) -> None:
        f = self.flags
        while not self._removed:
            try:
                applied, source_role = await self._pull_once()
                self._conn_errors = 0
                if (
                    applied == 0
                    and self.role is ReplicaRole.FOLLOWER
                    and source_role not in (None, ReplicaRole.LEADER.value)
                ):
                    # Empty pulls FROM A NON-LEADER mean leadership moved
                    # (replicated_db.cpp:385-399); idle leaders are normal
                    # and never trigger resets.
                    self._empty_pulls += 1
                    if self._empty_pulls >= f.empty_pulls_before_reset:
                        self._empty_pulls = 0
                        await self._maybe_reset_upstream(force_sample=False)
                else:
                    self._empty_pulls = 0
            except asyncio.CancelledError:
                raise
            except RpcApplicationError as e:
                self._stats.incr(M["pull_errors"])
                self._conn_errors = 0
                if e.code == ReplicateErrorCode.SOURCE_NOT_FOUND.value:
                    await self._maybe_reset_upstream(force_sample=False)
                await self._pull_error_delay()
            except (RpcError, Exception) as e:
                self._stats.incr(M["pull_errors"])
                log.warning("%s: pull error from %s: %r", self.name,
                            self.upstream_addr, e)
                # A dead upstream looks like CONNECTION errors; consult
                # the leader resolver — sampled at first, FORCED after a
                # few in a row (a steady follower gets no transition when
                # its leader dies; only this path repoints it). Only
                # connection-class errors escalate: a local apply/decode
                # failure loop must not hammer the control plane
                # unsampled.
                forced = False
                if isinstance(e, (RpcConnectionError, ConnectionError,
                                  OSError)):
                    self._conn_errors += 1
                    forced = (self._conn_errors
                              >= f.conn_errors_before_forced_reset)
                    if forced:
                        self._conn_errors = 0
                else:
                    self._conn_errors = 0
                await self._maybe_reset_upstream(force_sample=forced)
                await self._pull_error_delay()

    async def _pull_once(self) -> Tuple[int, Optional[str]]:
        f = self.flags
        assert self.upstream_addr is not None
        host, port = self.upstream_addr
        # Follower-rooted pull trace: pool acquire + RPC RTT (which carries
        # the context to the upstream's serve span) + the apply phase.
        with start_span("repl.pull", db=self.name) as sp:
            client = await self._pool.get_client(host, port)
            with start_span("repl.seq_read"):
                latest = await self._loop.run_in_executor(
                    self._executor, self.wrapper.latest_sequence_number
                )
            self._stats.incr(M["pull_requests"])
            result = await client.call(
                "replicate",
                {
                    "db_name": self.name,
                    "seq_no": latest,
                    "max_wait_ms": f.server_long_poll_ms,
                    "max_updates": f.max_updates_per_response,
                    "role": self.role.value,
                },
                timeout=(f.server_long_poll_ms + f.pull_rpc_margin_ms) / 1000.0,
            )
            updates = result.get("updates", []) if result else []
            source_role = result.get("source_role") if result else None
            if not updates:
                return 0, source_role
            sp.annotate(updates=len(updates))
            # run_in_executor does not carry contextvars: hand the pull
            # context across the hop explicitly (observability/context.py).
            pull_ctx = wire_context()
            await self._loop.run_in_executor(
                self._executor, self._apply_updates, updates, pull_ctx
            )
            return len(updates), source_role

    def _apply_updates(self, updates: List[dict],
                       pull_ctx: Optional[dict] = None) -> None:
        """Executor-side ordered apply of one response's updates."""
        now = now_ms()
        total_bytes = 0
        with start_span("repl.apply_batch", remote=pull_ctx, db=self.name,
                        updates=len(updates)):
            # Sequence-continuity guard: applying out of order would shift
            # the local numbering below the leader's and silently diverge
            # (re-fetch + double-apply). One storage-lock read, then track
            # incrementally.
            expected = self.wrapper.latest_sequence_number() + 1
            for u in updates:
                raw = bytes(u["raw_data"])
                ts = u.get("timestamp")
                got = int(u.get("seq_no", expected))
                if got != expected:
                    raise ValueError(
                        f"{self.name}: replication seq discontinuity: expected "
                        f"{expected}, got {got} — rebuild required"
                    )
                tctx = u.get("trace")
                if tctx is not None:
                    # the update carried its originating write's sampled
                    # context: this apply joins the WRITE's trace (child of
                    # the leader's repl.write), and re-records the context
                    # so chained downstreams stitch onto the same trace
                    with start_span("repl.apply", remote=tctx, db=self.name,
                                    seq=got) as asp:
                        if pull_ctx is not None:
                            asp.annotate(pull_trace=pull_ctx["trace_id"])
                        self.wrapper.handle_replicate_response(raw, ts)
                        if asp.sampled:
                            self._remember_write_trace(got, asp)
                else:
                    self.wrapper.handle_replicate_response(raw, ts)
                expected += int(u.get("count") or decode_batch(raw).count())
                total_bytes += len(raw)
                if ts is not None:
                    self._stats.add_metric(
                        M["replication_lag_ms"], max(0, now - ts))
        self._stats.incr(M["pull_updates_applied"], len(updates))
        self._stats.incr(M["pull_bytes_applied"], total_bytes)
        # Wake OUR parked long-polls so chained downstream followers see the
        # new updates immediately (reference replicated_db.cpp:391).
        self._notifier.notify_all_threadsafe()

    async def _pull_error_delay(self) -> None:
        f = self.flags
        delay_ms = random.uniform(
            f.pull_error_delay_min_ms, f.pull_error_delay_max_ms
        )
        await asyncio.sleep(delay_ms / 1000.0)

    async def _maybe_reset_upstream(self, force_sample: bool) -> None:
        """Query the leader resolver (reference: Helix GetLeaderInstanceId,
        sampled at 10% to avoid hammering the control plane)."""
        f = self.flags
        if self._leader_resolver is None:
            return
        if not force_sample and random.random() > f.upstream_reset_sample_rate:
            return
        try:
            new_addr = await self._loop.run_in_executor(
                self._executor, self._leader_resolver, self.name
            )
        except Exception:
            log.exception("%s: leader resolver failed", self.name)
            return
        if new_addr and tuple(new_addr) != tuple(self.upstream_addr or ()):
            log.info("%s: resetting upstream %s -> %s", self.name,
                     self.upstream_addr, new_addr)
            self.upstream_addr = tuple(new_addr)
            self._conn_errors = 0  # fresh upstream, fresh error budget
            self._stats.incr(M["upstream_resets"])

    def reset_upstream(self, addr: Tuple[str, int]) -> None:
        """Explicit upstream repoint (changeDBRoleAndUpStream path)."""
        self.upstream_addr = tuple(addr)
        self._conn_errors = 0

    # ------------------------------------------------------------------
    # introspection (replicated_db.cpp:168-182)
    # ------------------------------------------------------------------

    def introspect(self) -> str:
        return (
            f"db={self.name} role={self.role.value} "
            f"mode={self.replication_mode} "
            f"latest_seq={self.wrapper.latest_sequence_number()} "
            f"acked_seq={self._acked.value} "
            f"upstream={self.upstream_addr} "
            f"degraded={self._degraded} removed={self._removed}"
        )
