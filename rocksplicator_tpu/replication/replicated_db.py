"""ReplicatedDB: the per-shard replication state machine.

Reference: rocksdb_replicator/replicated_db.cpp (613 LoC) — three faces:
- **leader write path** (``write``): stamp wall-clock ms into the batch,
  write via DbWrapper, wake parked long-polls, and in mode 1/2 wait for a
  follower ACK with fail-fast degradation (replicated_db.cpp:103-166,
  236-273);
- **server path** (``handle_replicate_request``): post ACKs from follower
  pulls, park on the notifier up to max_wait_ms, then serve ≤ max_updates
  batches from a cached WAL cursor (replicated_db.cpp:435-575);
- **follower path** (``pull loop``): long-poll the upstream, apply raw
  batches via DbWrapper, track lag from embedded timestamps, and on errors
  back off with randomized delay / reset upstream via the leader resolver
  (replicated_db.cpp:314-433, 278-312).

Replication modes (replicated_db.cpp:59-64): 0 async, 1 semi-sync (ACK
when the response carrying the write is sent to a follower), 2 sync (ACK
when a follower's next pull confirms the seq was applied).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional, Tuple

from ..observability.context import current_span, wire_context
from ..observability.span import detached_span, start_span
from ..rpc.client_pool import RpcClientPool
from ..rpc.errors import (RpcApplicationError, RpcConnectionError, RpcError,
                          RpcTransportConfigError)
from ..storage.records import WriteBatch, decode_batch, scan_batch_meta
from ..testing import failpoints as fp
from ..utils.misc import now_ms
from ..utils.retry_policy import RetryPolicy
from ..utils.stats import Stats, tagged
from .ack_window import AckWaiter, AckWindow, resolved_waiter
from .cond_var import AsyncNotifier
from .db_wrapper import DbWrapper
from .iter_cache import IterCache
from .wire import READ_METRICS as R
from .wire import REPLICATOR_METRICS as M
from .wire import ReplicaRole, ReplicateErrorCode

log = logging.getLogger(__name__)

LeaderResolver = Callable[[str], Optional[Tuple[str, int]]]


@dataclass
class ReplicationFlags:
    """Defaults mirror the reference gflags (replicated_db.cpp:36-90)."""

    max_updates_per_response: int = 50
    server_long_poll_ms: int = 10_000
    pull_error_delay_min_ms: int = 5_000
    pull_error_delay_max_ms: int = 10_000
    ack_timeout_ms: int = 2_000
    degraded_ack_timeout_ms: int = 10
    consecutive_timeouts_to_degrade: int = 100
    upstream_reset_sample_rate: float = 0.1
    # pulls from a non-leader that return nothing this many times in a row
    # trigger an upstream reset (replicated_db.cpp:392-408 heuristic)
    empty_pulls_before_reset: int = 5
    # consecutive CONNECTION errors to the same upstream force a resolver
    # query (no sampling): a steady follower whose leader died gets no
    # state transition — without escalation its repoint waits on the 10%
    # sample × 5-10s backoff (~75 s expected; observed blowing the soak
    # failover convergence window at 4000 shards)
    conn_errors_before_forced_reset: int = 3
    pull_rpc_margin_ms: int = 5_000
    # leader write pipelining: max in-flight (unacked) writes per shard.
    # write_async blocks only when the window is full — the back-pressure
    # that bounds the unacked backlog. 1 degenerates to the old
    # one-write-in-flight blocking behavior.
    write_window: int = 64
    # follower pull adaptivity: when the upstream reports a backlog, the
    # next pull asks for up to this many updates (instead of the fixed
    # max_updates_per_response) so one response acks a whole write
    # window; also the server-side clamp on any requested max_updates
    adaptive_max_updates_cap: int = 1024
    # bounded-staleness follower reads (round 13): how old the cached
    # upstream commit-point estimate may be before a bounded read must
    # refresh it with a seq probe (serving on a stale estimate is how a
    # partitioned follower silently blows the client's lag bound); and
    # the probe RPC's timeout — a probe that can't reach the upstream
    # means the bound is unverifiable and the read bounces. The client's
    # total staleness window is max_lag seqs + this TTL of time. The
    # default sits ABOVE server_long_poll_ms: an idle follower's
    # estimate refreshes on every long-poll expiry (~10 s), so the
    # sync (probe-free) ApplicationDB.read gate stays serveable on an
    # idle caught-up cluster; deployments wanting a tighter time window
    # lower BOTH knobs together (the bench and chaos flags do).
    read_info_ttl_ms: int = 12_000
    read_probe_timeout_ms: int = 1000
    # Fast-first-connect backoff tier (round 22): the 5-10s error floor
    # is right for a STEADY follower whose upstream died, but a fleet
    # cold start races pullers against their leaders' process spin-up —
    # with only the steady floor, a 100-shard node staggers its first
    # convergence across minutes. The first N attempts of a shard that
    # has NEVER completed a pull retry on a jittered fast tier instead;
    # once any pull succeeds (or N attempts burn), the steady floor
    # rules. Jitter rides the same RSTPU_PULL_RETRY_SEED rng.
    pull_fast_first_attempts: int = 5
    pull_fast_min_ms: int = 100
    pull_fast_max_ms: int = 500
    # Multiplexed per-peer pull sessions (round 22): one long-poll
    # carries every shard pulled from that peer. None = obey the
    # RSTPU_PULL_MUX env killswitch (default off); True/False override.
    pull_mux: Optional[bool] = None
    # server-side cap on the TOTAL updates one mux response may carry
    # across all sections (each section is additionally clamped by its
    # own requested max_updates and adaptive_max_updates_cap)
    mux_session_budget: int = 4096


class ReplicatedDB:
    def __init__(
        self,
        name: str,
        wrapper: DbWrapper,
        role: ReplicaRole,
        loop: asyncio.AbstractEventLoop,
        executor: ThreadPoolExecutor,
        pool: RpcClientPool,
        upstream_addr: Optional[Tuple[str, int]] = None,
        replication_mode: int = 0,
        flags: Optional[ReplicationFlags] = None,
        leader_resolver: Optional[LeaderResolver] = None,
        epoch: int = 0,
        stat_tags: Optional[dict] = None,
        mux=None,
    ):
        self.name = name
        self.wrapper = wrapper
        self.role = role
        self.replication_mode = replication_mode
        self.upstream_addr = upstream_addr
        # Fencing epoch (the controller-stamped assignment epoch; the
        # ZK-zxid-epoch analog). Every replicate request/response and
        # replicate_ack frame carries one; see _reject_stale_epoch for
        # the rules. 0 = unfenced legacy plumbing (epoch checks only
        # engage when a frame carries a strictly newer epoch).
        self.epoch = int(epoch or 0)
        self._epoch_lock = threading.Lock()
        self._fenced_by: Optional[int] = None
        self.flags = flags or ReplicationFlags()
        # Live shard move (round 15): monotonic deadline until which NEW
        # leader writes are refused (WRITE_PAUSED, retryable). The move
        # cutover arms this so WAL-tail catch-up has a bounded tail on a
        # hot shard; ALWAYS auto-expiring — a crashed move coordinator
        # can never wedge the shard. 0.0 = not paused.
        self._write_paused_until = 0.0
        self._loop = loop
        self._executor = executor
        self._pool = pool
        self._leader_resolver = leader_resolver
        self._notifier = AsyncNotifier(loop)
        self._acked = AckWindow(
            capacity=self.flags.write_window, on_resolve=self._on_ack_resolve
        )
        self._iter_cache = IterCache()
        self._removed = False
        self._pull_task: Optional[asyncio.Task] = None
        # ACK degradation state (replicated_db.cpp:236-273); resolutions
        # arrive from writer threads AND the loop's expiry timer, so the
        # counters live behind a lock now that writes pipeline
        self._ack_state_lock = threading.Lock()
        self._consecutive_ack_timeouts = 0
        self._degraded = False
        # ack-expiry timer: one loop timer per shard, armed for the
        # earliest pending waiter deadline (uniform timeouts ⇒ FIFO
        # deadlines ⇒ the common registration path skips the loop hop)
        self._expiry_lock = threading.Lock()
        self._expiry_deadline: Optional[float] = None
        self._expiry_handle: Optional[asyncio.TimerHandle] = None
        # follower pull pipeline state (loop thread only)
        self._apply_future = None
        self._apply_target: Optional[int] = None
        self._applied_through: Optional[int] = None
        self._cur_max_updates = self.flags.max_updates_per_response
        self._upstream_mode: Optional[int] = None  # learned from responses
        # commit-point estimate for bounded-staleness reads: the
        # upstream's latest_seq as carried on the most recent pull/probe
        # response, plus when we heard it. ONE tuple swapped atomically
        # (GIL attribute store): a torn (old seq, fresh mono) pair would
        # let the sync read gate serve past the bound — pairing an old
        # lower-bound estimate with a fresh age is a wrong SERVE, not a
        # spurious bounce.
        self._upstream_latest: Optional[Tuple[int, float]] = None
        # single-flight probe: concurrent bounded reads hitting a stale
        # estimate share ONE refresh RPC instead of stampeding the
        # upstream (loop thread only)
        self._probe_task: Optional[asyncio.Task] = None
        self._empty_pulls = 0
        self._conn_errors = 0
        # set when the upstream answered WAL_GAP: our position predates
        # its oldest surviving WAL record, so pulling can NEVER catch up
        # — the participant's periodic loop reads this (via check_db)
        # and forces a snapshot rebuild; cleared by any successful pull
        # (an upstream repoint may land on a deeper-WAL donor)
        self.pull_stalled_wal_gap = False
        # set when this follower is PERSISTENTLY ahead of a direct
        # LEADER upstream's own committed seq: it applied writes from a
        # deposed leader inside the r11 visibility window (before the
        # new epoch reached it), so its suffix is not in the lineage
        # and pulling can never reconcile it. The participant loop
        # clears + rejoins the replica (the follower analog of the
        # deposed-leader resync). Never reset by success — the flag
        # dies with the resync's reopen.
        self.pull_diverged = False
        self._ahead_pulls = 0
        # pull-error backoff: exp backoff + jitter via the unified
        # RetryPolicy (utils/retry_policy.py) — jittered within
        # [min, cap], cap growing from the reference's min delay toward
        # max across consecutive errors, reset on the first successful
        # pull. The min flag stays a HARD floor (the reference's
        # uniform(min, max) contract): an error loop must never hammer
        # the upstream/control plane at sub-floor intervals.
        # RSTPU_PULL_RETRY_SEED pins the jitter for reproducible chaos.
        f = self.flags
        self._pull_retry = RetryPolicy(
            max_attempts=1 << 30,
            base_delay=f.pull_error_delay_min_ms / 1000.0,
            max_delay=f.pull_error_delay_max_ms / 1000.0,
            floor=f.pull_error_delay_min_ms / 1000.0,
        )
        self._pull_retry_attempt = 0
        _seed = os.environ.get("RSTPU_PULL_RETRY_SEED")
        self._pull_rng = random.Random(int(_seed) if _seed else None)
        # first-connect detection for the fast backoff tier: flips true
        # on the first successful pull (solo loop or mux section)
        self._ever_pulled = False
        # mux pull session manager (replication/pull_mux.py) — when set
        # and the killswitch allows, start() registers with it instead
        # of spawning the per-shard _pull_loop
        self._mux = mux
        # serves currently PARKED in this shard's long-poll (loop thread
        # only) — the per-shard half of the parked-longpolls gauge the
        # fleet A/B reads; the mux session park has its own counter
        self._parked_serves = 0
        self._stats = Stats.get()
        # per-shard load counters (round 14): the spectator's hot-spot
        # ranking input. Names precomputed — tagged() is a string join
        # and these sit on the write/read hot paths. stat_tags carries
        # the replicator's port so the series stays per-REPLICA even in
        # in-process multi-replicator topologies sharing one Stats
        # registry (the aggregator dedupes scraped series by full name).
        _tags = stat_tags or {}
        self._m_shard_writes = tagged("replicator.shard_writes", db=name,
                                      **_tags)
        self._m_shard_reads = tagged("replicator.shard_reads", db=name,
                                     **_tags)
        # serves handled since start: benches/ops gate their write phase
        # on every shard having a live puller (a shard whose pullers are
        # all in connect backoff times out its whole first write window)
        self.serve_count = 0
        # seq -> wire trace context of a SAMPLED write at that seq: lets the
        # serve path attach the originating write's trace to the updates it
        # ships, so a follower's apply span joins the LEADER's write trace
        # (and re-records here for chained downstreams) — one stitched
        # trace across the whole replication chain. Bounded; empty when
        # tracing is off, so the hot serve/apply paths pay one falsy check.
        self._write_traces: dict = {}
        self._write_traces_lock = threading.Lock()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.role in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER):
            if self.upstream_addr is None:
                raise ValueError(f"{self.name}: {self.role} requires an upstream")
            if self._mux is not None:
                # multiplexed pulls: one session per upstream PEER, not
                # per shard — the manager routes this shard into (or
                # spawns) its peer's session; shards whose peer predates
                # replicate_mux come back through start_solo_pull()
                self._mux.register(self)
            else:
                self.start_solo_pull()

    def start_solo_pull(self) -> None:
        """Spawn the classic per-shard pull loop (the non-mux path, and
        the mux manager's automatic fallback for legacy peers)."""
        if self._removed or self._pull_task is not None:
            return
        self._pull_task = asyncio.run_coroutine_threadsafe(
            self._pull_loop(), self._loop
        )

    def stop(self) -> None:
        self._removed = True
        if self._mux is not None:
            self._mux.deregister(self)
        task = self._pull_task
        if task is not None:
            self._loop.call_soon_threadsafe(task.cancel)
            self._pull_task = None
        self._acked.close()  # no writer may hang on an in-flight ack
        with self._expiry_lock:
            handle, self._expiry_handle = self._expiry_handle, None
            self._expiry_deadline = None
        if handle is not None:
            self._loop.call_soon_threadsafe(handle.cancel)
        self._notifier.notify_all_threadsafe()
        self._iter_cache.clear()

    @property
    def removed(self) -> bool:
        return self._removed

    # ------------------------------------------------------------------
    # fencing (monotonic epoch, end to end)
    # ------------------------------------------------------------------

    @property
    def fenced(self) -> bool:
        return self._fenced_by is not None

    def adopt_epoch(self, epoch: int) -> None:
        """Raise this db's epoch (never lowers). Used by followers
        adopting a newer epoch from upstream responses and by the admin
        set_db_epoch path (a sticky leader whose assignment epoch moved
        without a role transition).

        RE-ANOINTMENT: adopting an epoch STRICTLY ABOVE the one that
        fenced us clears the fence — the controller mints a fresh epoch
        exactly when it issues leadership, so an assignment carrying
        one means this node is the legitimate leader again under it
        (and any peer still at the fencing epoch is now the stale one).
        Without this, a fenced-then-sticky-re-elected leader satisfied
        the control plane while its data plane refused every write and
        serve forever (found wedged by the reshard chaos: lineages=[])."""
        epoch = int(epoch)
        unfenced = False
        with self._epoch_lock:
            if epoch > self.epoch:
                self.epoch = epoch
            if (self._fenced_by is not None
                    and self.epoch > self._fenced_by):
                self._fenced_by = None
                unfenced = True
        if unfenced:
            log.warning(
                "%s: UNFENCED — re-anointed at epoch %d (above the "
                "deposing epoch); serving resumes", self.name, self.epoch)

    def _reject_stale_epoch(self, remote_epoch) -> bool:
        """Process the epoch carried on an inbound replicate/ack frame.

        Followers/observers ADOPT a newer epoch (assignments flow
        controller → participant, but a chained or raced promotion can
        reach the data plane first) and never reject. A LEADER (or NOOP)
        seeing a newer epoch has been deposed — a new leader was
        promoted under that epoch — so it fences itself: every pending
        ack waiter resolves un-acked, and this and every future
        replicate/ack/write is refused. Returns True when the caller
        must raise STALE_EPOCH and post no acks.

        This method is the no-split-brain guard the chaos harness's
        ``--break-guard fencing`` tooth disables to prove the harness
        catches a leader that ignores epochs."""
        if remote_epoch is not None:
            remote = int(remote_epoch)
            if remote > self.epoch:
                if self.role in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER):
                    self.adopt_epoch(remote)
                    return False
                self._fence(remote)
        return self._fenced_by is not None

    def _fence(self, remote_epoch: int) -> None:
        with self._epoch_lock:
            first = self._fenced_by is None
            self._fenced_by = max(self._fenced_by or 0, int(remote_epoch))
        if first:
            self._stats.incr(M["fenced"])
            log.warning(
                "%s: FENCED — epoch %d deposed by %d; failing %d pending "
                "acks, refusing further writes", self.name, self.epoch,
                self._fenced_by, self._acked.depth)
            # every in-flight waiter resolves un-acked NOW: a deposed
            # leader must not sit out ack timeouts pretending its window
            # might still land
            self._acked.close()

    def _check_fenced(self) -> None:
        fenced_by = self._fenced_by
        if fenced_by is not None:
            raise RpcApplicationError(
                ReplicateErrorCode.STALE_EPOCH.value,
                f"{self.name}: leader epoch {self.epoch} deposed by "
                f"epoch {fenced_by}",
            )

    # ------------------------------------------------------------------
    # cutover write pause (live shard moves, round 15)
    # ------------------------------------------------------------------

    @property
    def write_paused(self) -> bool:
        return time.monotonic() < self._write_paused_until

    def pause_writes(self, duration_ms: float) -> None:
        """Refuse NEW leader writes for ``duration_ms`` — the shard-move
        cutover's tail bound: with the ingress paused, WAL-tail catch-up
        converges to exact seq equality instead of chasing a hot shard
        forever. Auto-expires (never latched), so a mover that dies
        mid-cutover leaves the shard serving again within the window;
        ``duration_ms <= 0`` resumes immediately. In-flight writes and
        their acks are untouched — the pause only gates NEW admissions,
        so it can never turn an acked write into a lost one."""
        if duration_ms <= 0:
            self._write_paused_until = 0.0
            log.info("%s: write pause cleared", self.name)
            return
        self._write_paused_until = time.monotonic() + duration_ms / 1000.0
        log.info("%s: writes paused for %.0f ms (move cutover)",
                 self.name, duration_ms)

    def _check_write_paused(self) -> None:
        if time.monotonic() < self._write_paused_until:
            self._stats.incr(M["write_paused"])
            raise RpcApplicationError(
                ReplicateErrorCode.WRITE_PAUSED.value,
                f"{self.name}: writes paused for move cutover "
                f"({max(0.0, self._write_paused_until - time.monotonic()) * 1e3:.0f} ms left)",
            )

    # ------------------------------------------------------------------
    # leader write path (any thread)
    # ------------------------------------------------------------------

    def write(self, batch: WriteBatch) -> int:
        """Blocking write: pipeline entry + wait for the ack future.
        Exactly the old semantics (returns the seq whether the ack landed
        or timed out; timeouts feed the degradation state machine) but
        expressed over write_async, so sync and async writers share one
        code path."""
        start = time.monotonic()
        waiter = self.write_async(batch)
        try:
            # Belt and braces on the old MaxNumberBox.wait(num, timeout)
            # contract: the future normally resolves via ack or the
            # loop's expiry timer, but a wedged/stopped loop must not
            # turn a 2000ms ack timeout into an unbounded hang. The
            # margin covers timer latency; on expiry the degradation
            # accounting still runs whenever the window resolves.
            waiter.result(max(0.0, waiter.deadline - time.monotonic()) + 2.0)
        except FuturesTimeoutError:
            log.warning("%s: ack expiry timer overdue; returning after "
                        "local wait deadline", self.name)
        self._stats.add_metric(M["leader_write_ms"], (time.monotonic() - start) * 1e3)
        return waiter.seq

    def write_async(self, batch: WriteBatch) -> AckWaiter:
        """Pipelined write: stamp + WAL-write immediately (fsync is
        group-committed by the engine), register an ack waiter in the
        AckWindow, and return without blocking on the follower
        round-trip. The returned waiter's ``future`` resolves to the
        batch's start seq when the ack arrives or its timeout expires;
        ``.acked`` records which. Blocks only when the shard's write
        window (flags.write_window) is full — the flow control that
        bounds the unacked backlog. Must not be called from the IO loop
        thread (it may block on flow control; the loop drives acks).
        """
        if self.role not in (ReplicaRole.LEADER, ReplicaRole.NOOP):
            raise RpcApplicationError(
                "NOT_LEADER", f"{self.name} role is {self.role.value}"
            )
        self._check_fenced()
        self._check_write_paused()
        # The per-write trace: root span with wal_write through fsync;
        # the ack_wait phase becomes a DEFERRED child span finished at
        # ack resolution, so sampled traces show the real (overlapping)
        # in-flight windows. Head sampled — with sampling off this costs
        # one contextvar set/reset.
        with start_span("repl.write", db=self.name) as sp:
            batch.stamp_timestamp_ms()
            with start_span("repl.wal_write"):
                seq = self.wrapper.write_to_leader(batch)
            end_seq = seq + batch.count() - 1
            if sp.sampled:
                sp.annotate(seq=seq, bytes=batch.byte_size())
                self._remember_write_trace(seq, sp)
            self._stats.incr(M["leader_writes"])
            self._stats.incr(M["leader_write_bytes"], batch.byte_size())
            self._stats.incr(self._m_shard_writes)
            # Wake parked follower long-polls (no thread was held by them).
            self._notifier.notify_all_threadsafe()
            if (self.replication_mode in (1, 2)
                    and self.role is ReplicaRole.LEADER):
                return self._register_ack_wait(end_seq, seq, sp)
        return resolved_waiter(seq)

    def write_async_many(self, batches: List[WriteBatch]) -> List[AckWaiter]:
        """Pipelined GROUP write: commit every batch with one storage
        lock pass and ONE WAL flush (engine ``write_many``), one
        follower wakeup, and one stats update — then register one ack
        waiter per batch. The per-write flush syscall + notify + stats
        were the dominant leader-side issue cost once writes pipelined;
        a writer topping up a shard's window issues its writes
        back-to-back, which is exactly the shape this amortizes. Same
        per-batch ack/timeout/degradation semantics as N
        ``write_async`` calls; may block on window flow control."""
        if not batches:
            return []
        if self.role not in (ReplicaRole.LEADER, ReplicaRole.NOOP):
            raise RpcApplicationError(
                "NOT_LEADER", f"{self.name} role is {self.role.value}"
            )
        self._check_fenced()
        self._check_write_paused()
        with start_span("repl.write_group", db=self.name,
                        n=len(batches)) as sp:
            total_bytes = 0
            for b in batches:
                b.stamp_timestamp_ms()
                total_bytes += b.byte_size()
            with start_span("repl.wal_write"):
                first_seq = self.wrapper.write_to_leader_many(batches)
            if sp.sampled:
                sp.annotate(seq=first_seq, bytes=total_bytes)
                self._remember_write_trace(first_seq, sp)
            self._stats.incr(M["leader_writes"], len(batches))
            self._stats.incr(M["leader_write_bytes"], total_bytes)
            self._stats.incr(self._m_shard_writes, len(batches))
            self._notifier.notify_all_threadsafe()
            acking = (self.replication_mode in (1, 2)
                      and self.role is ReplicaRole.LEADER)
            waiters: List[AckWaiter] = []
            seq = first_seq
            for b in batches:
                end_seq = seq + b.count() - 1
                if acking:
                    waiters.append(self._register_ack_wait(end_seq, seq, sp))
                else:
                    waiters.append(resolved_waiter(seq))
                seq = end_seq + 1
        return waiters

    @property
    def ack_window_depth(self) -> int:
        """Current in-flight (unacked) writes in this shard's window."""
        return self._acked.depth

    def applied_seq_lag(self) -> float:
        """Gauge value: how many committed sequence numbers this replica
        is behind the leader's last-heard commit point (0 on the leader
        by definition; 0 when no estimate has been heard yet — a fresh
        follower reports lag only once it has an upstream attestation,
        matching the bounded-read gate's 'unverifiable ≠ infinitely
        stale' stance)."""
        applied, est, _age = self._read_lag_state()
        if est is None:
            return 0.0
        return float(max(0, est - applied))

    @property
    def ack_window_free(self) -> int:
        """Free slots in the write window: how many write_async calls are
        guaranteed not to block on flow control right now. Writers
        pumping MANY shards use this to top up every shard's window
        round-robin instead of head-of-line blocking on one full
        window."""
        return max(0, self._acked.capacity - self._acked.depth)

    def _register_ack_wait(self, target_seq: int, seq: int,
                           write_span) -> AckWaiter:
        """Park an ack waiter (replicated_db.cpp:236-273 timeouts: 2000ms
        normally; 10ms once degraded — fail fast)."""
        f = self.flags
        timeout_ms = (
            f.degraded_ack_timeout_ms if self._degraded else f.ack_timeout_ms
        )
        self._stats.incr(M["ack_waits"])
        # detached: the waiter resolves on another thread (loop expiry /
        # follower ack); AckWindow's resolution funnel finishes+records
        ack_span = detached_span(
            "repl.ack_wait", write_span,
            target_seq=target_seq, timeout_ms=timeout_ms,
            window_depth=self._acked.depth + 1)
        waiter = self._acked.register(
            target_seq, seq, timeout_ms / 1000.0, span=ack_span
        )
        if not waiter.done:
            self._request_expiry(waiter.deadline)
        return waiter

    def _on_ack_resolve(self, waiter: AckWaiter, acked: bool) -> None:
        """AckWindow resolution callback (writer thread, loop expiry
        timer, or server ack path): stats + the 100-consecutive-timeouts
        degradation state machine + the deferred ack_wait span."""
        if acked:
            with self._ack_state_lock:
                self._consecutive_ack_timeouts = 0
                if self._degraded:
                    self._degraded = False
                    log.info("%s: ACK degradation recovered", self.name)
        elif not self._removed and self._fenced_by is None:
            # fence-failed waiters are not timeouts: the leader is
            # deposed, not degraded — keep the degradation machine clean
            f = self.flags
            self._stats.incr(M["ack_timeouts"])
            with self._ack_state_lock:
                self._consecutive_ack_timeouts += 1
                if (
                    not self._degraded
                    and self._consecutive_ack_timeouts
                    >= f.consecutive_timeouts_to_degrade
                ):
                    self._degraded = True
                    self._stats.incr(M["ack_degraded"])
                    log.warning("%s: entering degraded ACK mode", self.name)
        span = waiter.span
        if span is not None:
            waiter.span = None
            span.annotate(acked=acked, degraded=self._degraded,
                          window_depth_at_resolve=self._acked.depth)
            span.finish()
            from ..observability.collector import SpanCollector

            SpanCollector.get().record(span)

    # -- ack-expiry timer (per-future timeouts without a blocked thread) --

    def _request_expiry(self, deadline: float) -> None:
        """Ensure the loop's expiry timer fires by ``deadline``. With
        uniform timeouts deadlines are FIFO, so the common case is a
        lock-check and no loop hop."""
        with self._expiry_lock:
            cur = self._expiry_deadline
            if cur is not None and cur <= deadline:
                return
            self._expiry_deadline = deadline
        self._loop.call_soon_threadsafe(self._arm_expiry, deadline)

    def _arm_expiry(self, deadline: float) -> None:
        """Loop thread: (re)schedule the timer for an earlier deadline."""
        if self._removed:
            return
        delay = max(0.0, deadline - time.monotonic())
        when = self._loop.time() + delay
        with self._expiry_lock:
            handle = self._expiry_handle
            if (handle is not None and not handle.cancelled()
                    and self._loop.time() < handle.when() <= when + 1e-4):
                return  # an earlier-or-equal fire is already armed
            if handle is not None:
                handle.cancel()
            self._expiry_handle = self._loop.call_later(
                delay, self._fire_expiry)

    def _fire_expiry(self) -> None:
        """Loop thread: resolve overdue waiters, re-arm for the next."""
        with self._expiry_lock:
            self._expiry_handle = None
            self._expiry_deadline = None
        if self._removed:
            return
        try:
            # delay = a LATE timer (rescheduled, not a blocked loop);
            # fail = a LOST one — the next register re-arms, and write()
            # carries a belt-and-braces local deadline either way
            late = fp.pending_delay("ack.expire")
        except OSError:
            return
        if late > 0.0:
            self._loop.call_later(late, self._fire_expiry)
            return
        next_deadline = self._acked.expire_due()
        if next_deadline is not None:
            self._request_expiry(next_deadline)

    _WRITE_TRACE_CAP = 512

    def _remember_write_trace(self, seq: int, span) -> None:
        """Record a sampled write's (or applied update's) trace context by
        its start seq so downstream serving can propagate it in-band."""
        ctx = span.to_wire()
        with self._write_traces_lock:
            self._write_traces[seq] = ctx
            while len(self._write_traces) > self._WRITE_TRACE_CAP:
                self._write_traces.pop(next(iter(self._write_traces)))

    # ------------------------------------------------------------------
    # server path (loop thread)
    # ------------------------------------------------------------------

    async def handle_replicate_request(
        self,
        seq_no: int,
        max_wait_ms: Optional[int] = None,
        max_updates: Optional[int] = None,
        role: str = ReplicaRole.FOLLOWER.value,
        applied_seq: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> dict:
        """Serve updates after ``seq_no`` (the puller's WAL cursor).
        Returns {updates, latest_seq, source_role}; updates is empty on a
        long-poll timeout. source_role lets pullers detect they're polling
        a non-leader (upstream-reset heuristic, replicated_db.cpp:385-399).

        ``applied_seq`` is the puller's durably-APPLIED position, which a
        pipelined puller reports separately: its cursor runs ahead of its
        apply executor (the next pull is issued while the previous
        response is still applying), so acking off ``seq_no`` would
        over-claim in mode 2. Absent (legacy pullers), the cursor IS the
        applied position.

        ``epoch`` is the puller's fencing epoch. A pull carrying a newer
        epoch than ours proves a newer leader was promoted: we are
        deposed — reject the frame (STALE_EPOCH), post NO acks, fail the
        pending ack window, refuse further writes. This is what stops a
        demoted-but-still-running leader from acking a write after the
        new leader's epoch is visible to its followers."""
        if self._reject_stale_epoch(epoch):
            self._stats.incr(M["stale_epoch_rejects"])
            raise RpcApplicationError(
                ReplicateErrorCode.STALE_EPOCH.value,
                f"{self.name}: serving epoch {self.epoch} < puller epoch "
                f"{epoch}" if epoch is not None else
                f"{self.name}: fenced by epoch {self._fenced_by}",
            )
        f = self.flags
        max_wait_ms = f.server_long_poll_ms if max_wait_ms is None else max_wait_ms
        max_updates = (
            f.max_updates_per_response if max_updates is None else max_updates
        )
        # bound what one response can pin in memory regardless of what
        # the (possibly adaptive, possibly buggy) puller asked for
        max_updates = min(max_updates, f.adaptive_max_updates_cap)
        self.serve_count += 1
        self._stats.incr(M["replicate_requests"])
        # Child of the puller's rpc.server span when the pull was sampled:
        # per-phase serve breakdown (seq read vs long-poll park vs WAL
        # read) — where a 10 s long-poll hides inside one "slow RPC".
        with start_span("repl.serve", db=self.name, from_role=role) as sp:
            # Mode-2 ACK: the puller's request proves it applied through
            # applied_seq (replicated_db.cpp:450-456); OBSERVERs never
            # count.
            if role != ReplicaRole.OBSERVER.value and self.replication_mode == 2:
                self._acked.post(
                    seq_no if applied_seq is None else applied_seq)
            # RELAXED seq reads: the locking read would park behind flush/
            # compaction holding the storage lock (the old code paid an
            # executor hop per read to avoid blocking the loop on it — two
            # hops per serve, pure scheduling latency on the hot path). A
            # stale value is safe: the reserve-then-recheck protocol below
            # guarantees any write bumping the seq after reserve() also
            # notifies the reserved slot, so a stale "nothing new" can
            # only park until that notify, never for the full long-poll.
            latest = self.wrapper.latest_sequence_number_relaxed()
            if latest <= seq_no and max_wait_ms > 0:
                slot = self._notifier.reserve()
                latest = self.wrapper.latest_sequence_number_relaxed()
                if latest <= seq_no:
                    # this serve is about to PARK by design — the
                    # enclosing rpc.server root must not be tail-kept
                    # as a slow outlier (it would fill the tail ring
                    # with idle long-polls)
                    root = current_span()
                    if root is not None:
                        root.annotate(tail_exempt="longpoll_serve")
                    self._stats.incr(M["longpoll_parks"])
                    self._parked_serves += 1
                    try:
                        with start_span("repl.longpoll_wait",
                                        max_wait_ms=max_wait_ms):
                            await self._notifier.wait_reserved(
                                slot, max_wait_ms / 1000.0)
                    finally:
                        self._parked_serves -= 1
                    if self._removed:
                        raise RpcApplicationError(
                            ReplicateErrorCode.SOURCE_REMOVED.value, self.name
                        )
                    latest = self.wrapper.latest_sequence_number_relaxed()
                else:
                    self._notifier.cancel_reserved(slot)
            if latest <= seq_no:
                return {"updates": [], "latest_seq": latest,
                        "source_role": self.role.value,
                        "replication_mode": self.replication_mode,
                        "epoch": self.epoch,
                        **self._commit_point_fields()}
            try:
                with start_span("repl.wal_read") as sp_read:
                    # Cached-cursor fast path: serve INLINE on the loop.
                    # A parked tail cursor reads freshly-appended (page-
                    # cache-resident) bytes in microseconds; the executor
                    # round-trip (self-pipe wakeup + future + two context
                    # switches) costs more than the read itself and was a
                    # measurable share of serve latency under pipelined
                    # load. The cursor is TAKEN here (not peeked) so a
                    # concurrent serve or idle eviction can never leave
                    # the inline path opening a fresh cursor — a cold
                    # segment scan must never run on the loop; no-cursor
                    # serves go to the executor, which may touch disk.
                    it = self._iter_cache.take(seq_no + 1)
                    if it is not None:
                        updates = self._read_updates(
                            seq_no + 1, max_updates, it=it)
                    else:
                        updates = await self._loop.run_in_executor(
                            self._executor, self._read_updates, seq_no + 1,
                            max_updates
                        )
                    sp_read.annotate(updates=len(updates))
            except RpcApplicationError:
                # already typed for the puller — WAL_GAP above all: the
                # SOURCE_READ_ERROR wrapper below would mask the code
                # the puller's stall detection keys on, leaving a
                # behind-the-purge-horizon follower retrying seq 1
                # forever instead of flagging the snapshot rebuild
                # (found by the rebalance chaos harness: a fresh
                # split-child follower wedged exactly this way)
                raise
            except Exception as e:
                log.exception("%s: WAL read failed", self.name)
                raise RpcApplicationError(
                    ReplicateErrorCode.SOURCE_READ_ERROR.value, repr(e)
                ) from e
            # In-band trace propagation: updates whose originating write
            # (or upstream apply) was sampled carry that trace context, so
            # the puller's apply joins the write's trace across processes.
            if self._write_traces:
                with self._write_traces_lock:
                    for u in updates:
                        ctx = self._write_traces.get(u["seq_no"])
                        if ctx is not None:
                            u["trace"] = ctx
            # Mode-1 semi-sync ACK: posted when the response is handed to
            # the transport (replicated_db.cpp:543-546).
            if (
                updates
                and self.replication_mode == 1
                and role != ReplicaRole.OBSERVER.value
            ):
                last = updates[-1]
                self._acked.post(last["seq_no"] + last["count"] - 1)
            self._stats.incr(M["replicate_updates_sent"], len(updates))
            self._stats.incr(
                M["replicate_bytes_sent"],
                sum(len(u["raw_data"]) for u in updates),
            )
            sp.annotate(latest_seq=latest)
            return {"updates": updates, "latest_seq": latest,
                    "source_role": self.role.value,
                    "replication_mode": self.replication_mode,
                    "epoch": self.epoch,
                    **self._commit_point_fields()}

    def _read_updates(self, from_seq: int, max_updates: int,
                      it=None) -> List[dict]:
        """WAL read using the cursor cache (executor-side, unless the
        caller already took a cached cursor and passes it for an inline
        loop-side read).

        Raises on a WAL gap (requested updates already purged) — the analog
        of rocksdb GetUpdatesSince returning NotFound, which tells the
        puller it must rebuild from a snapshot rather than silently skip."""
        if it is None:
            it = self._iter_cache.take(from_seq)
        if it is None:
            it = self.wrapper.get_updates_from_leader(from_seq)
        updates: List[dict] = []
        next_seq = from_seq
        exhausted = True
        first = True
        # batch read when the cursor supports it (WalTailCursor): one
        # call parses the whole response's records out of the read-ahead
        # buffer instead of paying iterator overhead per record
        read_many = getattr(it, "read_many", None)
        if read_many is not None:
            records = read_many(max_updates)
            exhausted = len(records) < max_updates
        else:
            records = it
        for start_seq, raw in records:
            if first:
                first = False
                if start_seq > from_seq:
                    raise RpcApplicationError(
                        ReplicateErrorCode.WAL_GAP.value,
                        f"WAL gap: requested seq {from_seq}, oldest "
                        f"available {start_seq} (purged — puller must "
                        f"rebuild)",
                    )
            # header skim, not decode_batch + extract_timestamp_ms: the
            # serve path needs only (count, stamp) per shipped update
            count, ts = scan_batch_meta(raw)
            updates.append(
                {
                    "seq_no": start_seq,
                    "count": count,
                    "raw_data": bytes(raw),
                    "timestamp": ts,
                }
            )
            next_seq = start_seq + count
            if read_many is None and len(updates) >= max_updates:
                exhausted = False
                break
        # Resumable cursors (WalTailCursor) stay valid at the live tail,
        # so cache them even when this response drained the WAL — the
        # steady pipelined state — instead of re-scanning the active
        # segment on every pull. One-shot iterators keep the old rule.
        if not exhausted or getattr(it, "resumable", False):
            self._iter_cache.put(next_seq, it)
        return updates

    # ------------------------------------------------------------------
    # serving reads (round 13: bounded-staleness follower reads)
    # ------------------------------------------------------------------

    _READ_OPS = ("get", "multi_get", "scan")
    # a cursor pinned past any real sequence: the upstream answers the
    # probe inline from a relaxed seq read (max_wait_ms=0 skips the
    # long-poll park, nothing to serve skips the WAL read)
    _SEQ_PROBE_CURSOR = 1 << 60

    def _note_upstream_latest(self, seq: int, age_ms: float = 0.0) -> None:
        """Record a LEADER-ORIGIN commit-point attestation: "the leader
        had committed ≥ seq as of (now − age_ms)". ``age_ms`` is the
        attestation's age already accumulated upstream (a chained
        follower forwards its own estimate plus ITS age, so staleness
        COMPOUNDS down the chain instead of resetting per hop).
        Because leader commit is monotonic, "leader ≥ S as of t" stays
        true for every t' > t — so max-merging seq and timestamp
        independently is sound. The (seq, heard_at) pair is swapped as
        ONE tuple so concurrent sync-gate readers can never observe an
        old estimate wearing a fresh timestamp."""
        heard_at = time.monotonic() - max(0.0, age_ms) / 1000.0
        cur = self._upstream_latest
        if cur is not None:
            seq = max(seq, cur[0])
            heard_at = max(heard_at, cur[1])
        self._upstream_latest = (seq, heard_at)

    def _commit_point_fields(self) -> dict:
        """What THIS node can honestly attest about the leader's commit
        point, for downstream pullers' bounded reads: a LEADER attests
        its own committed seq (age 0); a chained FOLLOWER forwards its
        upstream estimate WITH its accumulated age (never its own
        applied seq — that would let a downstream caught up to a lagging
        middle hop serve reads violating the leader-relative bound).
        ``leader_seq`` is explicitly None when a follower has no
        estimate yet, so new downstreams never fall back to the legacy
        latest_seq (= this hop's applied position)."""
        applied, est, age = self._read_lag_state()
        return {
            "leader_seq": None if est is None else int(est),
            "leader_seq_age_ms": 0.0 if not age else round(age * 1e3, 1),
        }

    def _adopt_commit_point(self, result) -> None:
        """Shared pull/probe response handling for the commit-point
        estimate. New upstreams attest a leader-origin (seq, age) pair;
        legacy responses (no ``leader_seq`` key) fall back to
        latest_seq — correct for a direct-from-leader pull, the only
        shape legacy servers produced bounded reads for."""
        if not result:
            return
        if "leader_seq" in result:
            if result["leader_seq"] is not None:
                self._note_upstream_latest(
                    int(result["leader_seq"]),
                    float(result.get("leader_seq_age_ms") or 0.0))
        elif result.get("latest_seq") is not None:
            self._note_upstream_latest(int(result["latest_seq"]))

    def _read_lag_state(self) -> Tuple[int, Optional[int], Optional[float]]:
        """(applied, leader_est, age_sec): this replica's durably-visible
        engine position (relaxed read — same contract as the serve
        path), the last commit point heard from upstream, and how long
        ago it was heard. Leaders ARE the commit point (lag 0 by
        definition)."""
        applied = self.wrapper.latest_sequence_number_relaxed()
        if self.role in (ReplicaRole.LEADER, ReplicaRole.NOOP):
            return applied, applied, 0.0
        cur = self._upstream_latest
        if cur is None:
            return applied, None, None
        est, heard_at = cur
        return applied, est, time.monotonic() - heard_at

    def _read_epoch_gate(self, epoch) -> None:
        """Lineage check for reads — the read-path analog of
        ``_reject_stale_epoch``, with one asymmetry: a FOLLOWER must
        never ADOPT an epoch from a read request. A client's epoch claim
        is not authoritative (assignments flow controller→participant
        and pull responses come from the upstream we replicate from); a
        bogus inflated epoch here would make the real leader's frames
        look stale and wedge a healthy replica. It still REJECTS: a read
        carrying a newer epoch proves a newer leader was promoted, and
        this replica's applied prefix may end in the deposed lineage's
        divergent un-acked suffix — exactly the stale-epoch-pull rule."""
        if epoch is not None and int(epoch) > self.epoch:
            if self.role in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER):
                self._stats.incr(R["stale_epoch_rejected"])
                raise RpcApplicationError(
                    ReplicateErrorCode.STALE_EPOCH.value,
                    f"{self.name}: replica epoch {self.epoch} < read "
                    f"epoch {epoch} — possibly deposed lineage",
                )
            # leader/NOOP: a newer epoch deposes it, same as pulls/acks
            self._reject_stale_epoch(epoch)
        if self._fenced_by is not None:
            self._stats.incr(R["stale_epoch_rejected"])
        self._check_fenced()

    def read_gate(self, max_lag: Optional[int] = None,
                  epoch=None) -> dict:
        """Admission control for serving a read from THIS replica:
        lineage (fencing epoch) first, then the client's staleness
        bound. Raises STALE_EPOCH (deposed lineage — reject exactly as a
        stale-epoch pull is rejected) or STALE_READ (lag bound exceeded,
        or unverifiable because the commit-point estimate is older than
        ``read_info_ttl_ms``); returns the lag bookkeeping the response
        reports. Sync and probe-free so in-process callers
        (ApplicationDB.read) can gate without an event-loop hop; the
        async RPC handler refreshes a stale estimate with an upstream
        seq probe before gating.

        Boundary contract (tested): lag == max_lag SERVES,
        lag == max_lag + 1 bounces."""
        self._read_epoch_gate(epoch)
        applied, est, age = self._read_lag_state()
        lag = max(0, est - applied) if est is not None else None
        if (max_lag is not None
                and self.role not in (ReplicaRole.LEADER, ReplicaRole.NOOP)):
            ttl = self.flags.read_info_ttl_ms / 1000.0
            if est is None or age is None or age > ttl:
                self._stats.incr(R["stale_rejected"])
                raise RpcApplicationError(
                    ReplicateErrorCode.STALE_READ.value,
                    f"{self.name}: lag bound {max_lag} unverifiable "
                    f"(commit-point estimate "
                    f"{'missing' if est is None else f'{age * 1e3:.0f}ms old'})",
                )
            if lag > int(max_lag):
                self._stats.incr(R["stale_rejected"])
                raise RpcApplicationError(
                    ReplicateErrorCode.STALE_READ.value,
                    f"{self.name}: lag {lag} exceeds bound {max_lag} "
                    f"(applied {applied}, leader {est})",
                )
        return {"applied_seq": applied, "leader_seq": est, "lag": lag}

    async def _probe_upstream_seq(self) -> None:
        """Refresh the commit-point estimate (single-flight: concurrent
        stale reads share one probe)."""
        task = self._probe_task
        if task is None or task.done():
            task = self._probe_task = asyncio.ensure_future(
                self._probe_upstream_seq_once())
        await task

    async def _probe_upstream_seq_once(self) -> None:
        """Refresh the commit-point estimate with one lightweight
        replicate RPC. Failure leaves the estimate stale and the gate
        bounces the read: a partitioned follower must not serve bounded
        reads on memories. Probes ride role=OBSERVER so a mode-1/2
        upstream never counts them toward acks."""
        if self.upstream_addr is None:
            return
        self._stats.incr(R["probes"])
        host, port = self.upstream_addr
        try:
            client = await self._pool.get_client(host, port)
            result = await client.call(
                "replicate",
                {
                    "db_name": self.name,
                    "seq_no": self._SEQ_PROBE_CURSOR,
                    "max_wait_ms": 0,
                    "max_updates": 1,
                    "role": ReplicaRole.OBSERVER.value,
                    "epoch": self.epoch,
                },
                timeout=self.flags.read_probe_timeout_ms / 1000.0,
            )
        except Exception as e:
            log.debug("%s: upstream seq probe failed: %r", self.name, e)
            return
        resp_epoch = result.get("epoch") if result else None
        if resp_epoch is not None and int(resp_epoch) > self.epoch:
            self.adopt_epoch(int(resp_epoch))
        if resp_epoch is not None and int(resp_epoch) < self.epoch:
            # deposed-lineage attestation: the pull path raises
            # STALE_EPOCH before adopting anything from an older-epoch
            # upstream — the probe must be exactly as deaf, or a fresh
            # wrong-lineage estimate lets bounded reads serve past the
            # REAL leader's commit point (a wrong serve, not a bounce)
            log.debug("%s: ignoring seq probe from deposed upstream "
                      "epoch %s < ours %d", self.name, resp_epoch,
                      self.epoch)
            return
        self._adopt_commit_point(result)

    async def handle_read_request(
        self,
        op: str = "get",
        keys=None,
        start=None,
        count: Optional[int] = None,
        max_lag: Optional[int] = None,
        epoch=None,
    ) -> dict:
        """Serve a get/multi_get/scan from THIS replica under the
        client's staleness bound (``max_lag``, in sequence numbers;
        None = unbounded — any live replica serves) and fencing epoch.
        The read-scaling half of round 13: any FOLLOWER within the bound
        serves, so read throughput scales with replica count instead of
        saturating the leader."""
        await fp.async_hit("repl.read")
        if self._removed:
            raise RpcApplicationError(
                ReplicateErrorCode.SOURCE_REMOVED.value, self.name)
        if op not in self._READ_OPS:
            raise RpcApplicationError(
                "BAD_READ_OP",
                f"{self.name}: unknown read op {op!r} "
                f"(want one of {self._READ_OPS})",
            )
        t0 = time.monotonic()
        with start_span("repl.read", db=self.name, op=op) as sp:
            if (max_lag is not None
                    and self.role in (ReplicaRole.FOLLOWER,
                                      ReplicaRole.OBSERVER)):
                _applied, est, age = self._read_lag_state()
                if (est is None or age is None
                        or age > self.flags.read_info_ttl_ms / 1000.0):
                    # stale estimate: verify against the upstream BEFORE
                    # gating, so the serve decision is exact as of the
                    # probe's answer — the chaos invariant's foundation
                    await self._probe_upstream_seq()
            gate = self.read_gate(max_lag=max_lag, epoch=epoch)
            values = await self._loop.run_in_executor(
                self._executor, self._do_read, op, keys, start, count)
            if op in ("multi_get", "scan"):
                # round-19 tail armor: re-check the request deadline
                # before a potentially large response is serialized —
                # the engine read may have spent the whole budget, and
                # encoding N values nobody is waiting for only delays
                # live requests behind this connection
                from ..rpc.deadline import current_deadline

                dl = current_deadline()
                if dl is not None and dl.expired:
                    self._stats.incr(tagged("reads.deadline_shed", op=op))
                    raise RpcApplicationError(
                        "DEADLINE_EXCEEDED",
                        f"{self.name}: {op} deadline expired "
                        f"{-dl.remaining_ms():.1f}ms ago before "
                        "response serialization")
            if self.role in (ReplicaRole.LEADER, ReplicaRole.NOOP):
                self._stats.incr(R["leader_served"])
            else:
                self._stats.incr(R["follower_served"])
            self._stats.incr(self._m_shard_reads)
            if sp.sampled:
                sp.annotate(lag=gate["lag"], applied_seq=gate["applied_seq"])
            # SERVED reads only enter the latency histogram (a Timer
            # context would also record gate bounces — a bounced probe's
            # upstream RTT is not a serve latency, and at p99 a handful
            # of them would make the fleet-merged histogram disagree
            # with what clients actually experienced; bounces have their
            # own counters). The SAME value rides the response as
            # serve_ms, so a client's pooled samples and the merged
            # histogram measure the identical quantity — the
            # macro-bench's p99 agreement check is exact by
            # construction, up to bucket resolution.
            serve_ms = (time.monotonic() - t0) * 1e3
            self._stats.add_metric(tagged("reads.latency_ms", op=op),
                                   serve_ms)
            return {
                **gate,
                "values": values,
                "source_role": self.role.value,
                "epoch": self.epoch,
                "serve_ms": round(serve_ms, 3),
            }

    def _do_read(self, op: str, keys, start, count):
        """Executor-side read execution (engine reads may touch disk —
        never on the loop). Wrapper/argument problems surface as typed
        RPC errors, never as INTERNAL stack traces: a non-persisting
        wrapper (CDC observer) bounces cleanly down the router's chain."""
        from .db_wrapper import execute_read_op

        # sync hit ON the executor thread (unlike the loop-side
        # repl.read seam above): a delay policy here OCCUPIES a
        # dispatch slot without burning CPU — the hot-shift bench's
        # deterministic per-read service cost, so the serving knee is
        # rate-derived rather than host-derived even on a 1-core box
        fp.hit("repl.read.serve")
        try:
            return execute_read_op(self.wrapper, op, keys=keys,
                                   start=start, count=count)
        except NotImplementedError as e:
            raise RpcApplicationError(
                "READS_UNSUPPORTED",
                f"{self.name}: wrapper does not serve reads ({e})",
            ) from e
        except (ValueError, TypeError) as e:
            raise RpcApplicationError(
                "BAD_READ_OP", f"{self.name}: {e}") from e

    async def handle_write_request(self, raw_batch, epoch=None) -> dict:
        """Remote entry to the leader write path (the macro-bench's
        full-stack put op class): fence-check the carried epoch, commit
        via write_async OFF the loop (it may block on window flow
        control), and await the ack condition. Returns the batch's start
        seq and whether the replication ack condition was met."""
        if self.role not in (ReplicaRole.LEADER, ReplicaRole.NOOP):
            # role check BEFORE any epoch processing: a FOLLOWER must
            # never adopt a client-claimed epoch (_reject_stale_epoch
            # would — and the bogus epoch would then ride this
            # follower's pulls upstream and fence the HEALTHY leader).
            # Same no-adopt rule as _read_epoch_gate: client claims are
            # not authoritative.
            raise RpcApplicationError(
                ReplicateErrorCode.NOT_LEADER.value,
                f"{self.name} role is {self.role.value}",
            )
        if self._reject_stale_epoch(epoch):
            self._stats.incr(M["stale_epoch_rejects"])
            raise RpcApplicationError(
                ReplicateErrorCode.STALE_EPOCH.value,
                f"{self.name}: write epoch {epoch} fences serving epoch "
                f"{self.epoch}",
            )
        # Fail fast on a full write window instead of parking an
        # executor thread inside write_async's flow-control block: with
        # followers partitioned, enough concurrent write RPCs would
        # otherwise exhaust the SHARED executor and starve every read
        # and cold-cursor WAL serve behind stalled writes. The depth
        # check is advisory (a racing writer can still fill the window
        # and briefly park the executor task — bounded by the race, not
        # systematic); the client sees a typed, retryable error.
        if self.ack_window_free <= 0:
            self._stats.incr(M["write_window_full"])
            raise RpcApplicationError(
                "WRITE_WINDOW_FULL",
                f"{self.name}: {self._acked.depth}/{self._acked.capacity} "
                f"writes in flight — retry with backoff",
            )
        batch = decode_batch(bytes(raw_batch))
        # server-side latency per op class (the write sibling of
        # reads.latency_ms): the fleet p50/p99 the spectator merge
        # reports for puts, measured commit → ack condition; recorded on
        # COMPLETED writes only (same served-only contract as reads)
        t0 = time.monotonic()
        waiter = await self._loop.run_in_executor(
            self._executor, self.write_async, batch)
        await asyncio.wrap_future(waiter.future)
        self._stats.add_metric(tagged("writes.latency_ms", op="put"),
                               (time.monotonic() - t0) * 1e3)
        return {"seq": waiter.seq, "acked": waiter.acked,
                "epoch": self.epoch}

    # ------------------------------------------------------------------
    # follower pull path (loop thread)
    # ------------------------------------------------------------------

    async def _pull_loop(self) -> None:
        f = self.flags
        while not self._removed:
            try:
                applied, source_role = await self._pull_once()
                self._mark_pull_ok()
                if (
                    applied == 0
                    and self.role is ReplicaRole.FOLLOWER
                    and source_role not in (None, ReplicaRole.LEADER.value)
                ):
                    # Empty pulls FROM A NON-LEADER mean leadership moved
                    # (replicated_db.cpp:385-399); idle leaders are normal
                    # and never trigger resets.
                    self._empty_pulls += 1
                    if self._empty_pulls >= f.empty_pulls_before_reset:
                        self._empty_pulls = 0
                        await self._maybe_reset_upstream(force_sample=False)
                else:
                    self._empty_pulls = 0
            except asyncio.CancelledError:
                # do not await the in-flight apply here — stop() must not
                # block on executor work; just forget the pipeline state
                self._apply_future = None
                self._apply_target = None
                self._applied_through = None
                raise
            except RpcApplicationError as e:
                await self._drain_pending_apply()
                self._stats.incr(M["pull_errors"])
                self._conn_errors = 0
                if e.code == ReplicateErrorCode.SOURCE_NOT_FOUND.value:
                    await self._maybe_reset_upstream(force_sample=False)
                elif e.code == ReplicateErrorCode.WAL_GAP.value:
                    # the upstream's WAL was purged past our position:
                    # no amount of pulling can ever catch us up. Flag
                    # the stall (the participant loop turns it into a
                    # snapshot rebuild) and still consult the resolver
                    # — a repoint to a deeper-WAL donor may heal it
                    # without a rebuild.
                    if not self.pull_stalled_wal_gap:
                        self.pull_stalled_wal_gap = True
                        self._stats.incr(M["wal_gap_stalls"])
                        log.warning(
                            "%s: WAL-tail catch-up STALLED (%s) — "
                            "snapshot rebuild required", self.name, e)
                    await self._maybe_reset_upstream(force_sample=True)
                elif e.code == ReplicateErrorCode.STALE_EPOCH.value:
                    # a KNOWN-deposed upstream (or one that outran us):
                    # consult the resolver unsampled — faster pulls at
                    # the stale leader cannot help
                    await self._maybe_reset_upstream(force_sample=True)
                await self._pull_error_delay()
            except RpcTransportConfigError as e:
                # a MISCONFIG, not a connection error: loud (ERROR, not
                # the routine pull warning), never escalated to the
                # leader resolver, and retried only on the growing
                # backoff — faster retries cannot heal a bad transport
                # config, but the loop stays alive so reset_upstream /
                # changeDBRoleAndUpStream can repoint past it
                await self._drain_pending_apply()
                self._stats.incr(M["pull_errors"])
                self._conn_errors = 0
                log.error("%s: transport misconfig pulling from %s: %s",
                          self.name, self.upstream_addr, e)
                await self._pull_error_delay()
            except (RpcError, Exception) as e:
                await self._drain_pending_apply()
                self._stats.incr(M["pull_errors"])
                log.warning("%s: pull error from %s: %r", self.name,
                            self.upstream_addr, e)
                # A dead upstream looks like CONNECTION errors; consult
                # the leader resolver — sampled at first, FORCED after a
                # few in a row (a steady follower gets no transition when
                # its leader dies; only this path repoints it). Only
                # connection-class errors escalate: a local apply/decode
                # failure loop must not hammer the control plane
                # unsampled.
                forced = False
                if isinstance(e, (RpcConnectionError, ConnectionError,
                                  OSError)):
                    self._conn_errors += 1
                    forced = (self._conn_errors
                              >= f.conn_errors_before_forced_reset)
                    if forced:
                        self._conn_errors = 0
                else:
                    self._conn_errors = 0
                await self._maybe_reset_upstream(force_sample=forced)
                await self._pull_error_delay()

    def _mark_pull_ok(self) -> None:
        """Reset the error machinery after a successful pull (solo loop
        or mux section): error counters, backoff attempt, and the
        WAL-gap stall flag (an upstream repoint may have landed on a
        deeper-WAL donor)."""
        self._ever_pulled = True
        self._conn_errors = 0
        self._pull_retry_attempt = 0
        self.pull_stalled_wal_gap = False

    async def _pull_once(self) -> Tuple[int, Optional[str]]:
        """One pull iteration, DOUBLE-BUFFERED: the pull RPC for the next
        batch is issued while the PREVIOUS response is still applying in
        the executor, so network long-poll/RTT and storage apply overlap
        instead of alternating. The request cursor (``seq_no``) runs from
        the in-flight apply's target; the durably-applied position rides
        along as ``applied_seq`` so mode-2 acks never over-claim."""
        f = self.flags
        assert self.upstream_addr is not None
        await fp.async_hit("repl.pull")
        host, port = self.upstream_addr
        # Follower-rooted pull trace: pool acquire + RPC RTT (which carries
        # the context to the upstream's serve span) + the apply handoff.
        with start_span("repl.pull", db=self.name) as sp:
            if f.server_long_poll_ms > 0:
                # a pull's duration is dominated by the deliberate
                # server-side long-poll park — exempt from tail-keep
                sp.annotate(tail_exempt="long_poll")
            client = await self._pool.get_client(host, port)
            if self._applied_through is None:
                # cold pipeline: one storage-lock read seeds the cursor;
                # afterwards apply completions keep it current without
                # touching the storage lock per pull
                with start_span("repl.seq_read"):
                    self._applied_through = await self._loop.run_in_executor(
                        self._executor, self.wrapper.latest_sequence_number
                    )
            from_seq = (
                self._apply_target if self._apply_target is not None
                else self._applied_through
            )
            self._stats.incr(M["pull_requests"])
            call_coro = client.call(
                "replicate",
                {
                    "db_name": self.name,
                    "seq_no": from_seq,
                    "applied_seq": self._applied_through,
                    "max_wait_ms": f.server_long_poll_ms,
                    "max_updates": self._cur_max_updates,
                    "role": self.role.value,
                    # fencing: our epoch rides the request frame header —
                    # a deposed upstream seeing a newer one fences itself
                    "epoch": self.epoch,
                },
                timeout=(f.server_long_poll_ms + f.pull_rpc_margin_ms) / 1000.0,
                # the RTT of a long poll IS the long poll: a parked
                # pull must not be tail-kept as a slow outlier
                tail_exempt=f.server_long_poll_ms > 0,
            )
            if self._apply_future is None:
                result = await call_coro
            else:
                result = await self._call_racing_apply(client, call_coro)
            updates = result.get("updates", []) if result else []
            source_role = result.get("source_role") if result else None
            resp_epoch = result.get("epoch") if result else None
            if resp_epoch is not None:
                if int(resp_epoch) > self.epoch:
                    # a promotion reached the data plane before our
                    # assignment did — adopt; epochs only move forward
                    self.adopt_epoch(int(resp_epoch))
                elif int(resp_epoch) < self.epoch:
                    # deposed upstream: its updates may carry a divergent
                    # un-acked suffix — apply NOTHING, repoint instead
                    self._stats.incr(M["stale_epoch_rejects"])
                    raise RpcApplicationError(
                        ReplicateErrorCode.STALE_EPOCH.value,
                        f"{self.name}: upstream {host}:{port} epoch "
                        f"{resp_epoch} < ours {self.epoch}",
                    )
            if result and result.get("replication_mode") is not None:
                self._upstream_mode = int(result["replication_mode"])
            # every pull response refreshes the commit-point estimate
            # bounded follower reads check their lag against
            self._adopt_commit_point(result)
            self._note_divergence(result, source_role)
            self._adapt_max_updates(result, updates)
            if not updates:
                # idle upstream: let the pipeline drain so apply errors
                # surface here rather than lingering across long-polls
                await self._drain_pending_apply(reraise=True)
                return 0, source_role
            sp.annotate(updates=len(updates),
                        pipelined=self._apply_future is not None)
            # in-order apply: the previous response must land before this
            # one is handed to the executor (and its failure must surface
            # BEFORE we commit to a cursor built on top of it)
            await self._drain_pending_apply(reraise=True)
            # run_in_executor does not carry contextvars: hand the pull
            # context across the hop explicitly (observability/context.py).
            pull_ctx = wire_context()
            last = updates[-1]
            self._apply_target = int(last["seq_no"]) + int(
                last.get("count") or 1) - 1
            self._apply_future = self._loop.run_in_executor(
                self._executor, self._apply_updates, updates, pull_ctx
            )
            return len(updates), source_role

    async def _call_racing_apply(self, client, call_coro):
        """Await the pull RPC while the previous apply runs. If the apply
        lands first and the RPC is a parked long-poll, roll the cursor
        forward immediately and — for a mode-2 upstream — push the fresh
        applied position via a lightweight replicate_ack RPC, so the
        leader's pipelined ack waiters for the burst tail resolve at
        apply time instead of waiting out the park."""
        rpc_task = asyncio.ensure_future(call_coro)
        apply_fut = self._apply_future
        try:
            await asyncio.wait(
                {rpc_task, apply_fut}, return_when=asyncio.FIRST_COMPLETED
            )
        except asyncio.CancelledError:
            rpc_task.cancel()
            raise
        if not rpc_task.done():
            try:
                await self._drain_pending_apply(reraise=True)
            except Exception:
                rpc_task.cancel()
                raise
            if self._upstream_mode == 2 and self._applied_through:
                await self._send_applied_ack(client)
        return await rpc_task

    async def _send_applied_ack(self, client) -> None:
        """Best-effort ack push (mode-2 upstreams): the next pull carries
        applied_seq anyway, so failures only cost ack latency."""
        try:
            await client.call(
                "replicate_ack",
                {
                    "db_name": self.name,
                    "applied_seq": self._applied_through,
                    "role": self.role.value,
                    "epoch": self.epoch,
                },
                timeout=2.0,
            )
        except Exception:
            log.debug("%s: replicate_ack push failed", self.name,
                      exc_info=True)

    def post_applied(self, applied_seq: int, role: str,
                     epoch: Optional[int] = None) -> None:
        """Server side of the replicate_ack push: count the follower's
        durably-applied position toward mode-2 acks (OBSERVERs never
        count, same as the pull path). Same epoch fencing as the pull
        path: an ack carrying a newer epoch deposes this leader and must
        never resolve a waiter."""
        if self._reject_stale_epoch(epoch):
            self._stats.incr(M["stale_epoch_rejects"])
            raise RpcApplicationError(
                ReplicateErrorCode.STALE_EPOCH.value,
                f"{self.name}: ack epoch {epoch} fences serving epoch "
                f"{self.epoch}",
            )
        if role != ReplicaRole.OBSERVER.value and self.replication_mode == 2:
            self._acked.post(int(applied_seq))

    def _note_divergence(self, result, source_role) -> None:
        """Detect a lineage-divergent suffix: a FOLLOWER persistently
        AHEAD of a direct LEADER upstream's own committed seq holds
        records that are not in the lineage — it applied them from a
        deposed leader inside the visibility window, before the new
        epoch reached it. Pulling can never reconcile this (the
        upstream serves only seqs above ours, and our extra seqs shadow
        the lineage's), so flag it for the participant's resync loop.
        Requires several CONSECUTIVE ahead observations from a LEADER
        source: a momentarily-lagging middle hop or a racing estimate
        must never trigger a data-destroying resync."""
        if (self.role is not ReplicaRole.FOLLOWER
                or source_role != ReplicaRole.LEADER.value):
            self._ahead_pulls = 0
            return
        latest = (result or {}).get("latest_seq")
        applied = self._applied_through
        if latest is None or applied is None \
                or int(latest) >= int(applied):
            self._ahead_pulls = 0
            return
        self._ahead_pulls += 1
        if self._ahead_pulls >= 3 and not self.pull_diverged:
            self.pull_diverged = True
            self._stats.incr(M["diverged_stalls"])
            log.warning(
                "%s: applied %d is AHEAD of the leader's committed %d "
                "for %d consecutive pulls — divergent suffix (deposed-"
                "leader window write); resync required",
                self.name, applied, int(latest), self._ahead_pulls)

    def _adapt_max_updates(self, result, updates) -> None:
        """Size the NEXT pull to the upstream's reported backlog: behind
        by a window, ask for the whole window in one response (one pull
        round-trip then acks many pipelined writes at once); caught up,
        fall back to the reference's fixed max_updates_per_response."""
        f = self.flags
        base = f.max_updates_per_response
        latest_up = (result or {}).get("latest_seq")
        if updates and latest_up is not None:
            last = updates[-1]
            served_through = int(last["seq_no"]) + int(
                last.get("count") or 1) - 1
            backlog = int(latest_up) - served_through
            if backlog > 0:
                self._cur_max_updates = min(
                    f.adaptive_max_updates_cap, max(base, backlog))
                return
        self._cur_max_updates = base

    async def _drain_pending_apply(self, reraise: bool = False) -> None:
        """Wait out the in-flight apply (if any) and roll the cached
        applied-through cursor forward; on apply failure the cache is
        invalidated (next pull re-reads storage) and the error either
        propagates (pull path) or is swallowed (error-path cleanup —
        the pull loop is already backing off)."""
        fut = self._apply_future
        if fut is None:
            return
        self._apply_future = None
        target, self._apply_target = self._apply_target, None
        try:
            await fut
        except Exception:
            self._applied_through = None
            if reraise:
                raise
            log.exception("%s: pipelined apply failed", self.name)
            return
        self._applied_through = target

    def _apply_updates(self, updates: List[dict],
                       pull_ctx: Optional[dict] = None) -> None:
        """Executor-side ordered apply of one response's updates."""
        fp.hit("repl.apply")
        now = now_ms()
        total_bytes = 0
        with start_span("repl.apply_batch", remote=pull_ctx, db=self.name,
                        updates=len(updates)):
            # Sequence-continuity guard: applying out of order would shift
            # the local numbering below the leader's and silently diverge
            # (re-fetch + double-apply). One storage-lock read, then the
            # whole group is validated arithmetically BEFORE any of it is
            # applied — a bad response applies nothing.
            expected = self.wrapper.latest_sequence_number() + 1
            for u in updates:
                got = int(u.get("seq_no", expected))
                if got != expected:
                    raise ValueError(
                        f"{self.name}: replication seq discontinuity: expected "
                        f"{expected}, got {got} — rebuild required"
                    )
                expected += int(u.get("count")
                                or decode_batch(bytes(u["raw_data"])).count())
                total_bytes += len(u["raw_data"])
            # Apply: consecutive UNTRACED updates flow through the
            # wrapper's batched group path (one storage-lock pass + one
            # WAL flush per run — the per-record flush dominated the
            # apply side once leader writes pipelined); a traced update
            # breaks the run so its apply span records individually and
            # re-propagates to chained downstreams.
            run: List[dict] = []

            def flush_run():
                if run:
                    self.wrapper.handle_replicate_updates(run)
                    run.clear()

            for u in updates:
                tctx = u.get("trace")
                if tctx is None:
                    run.append(u)
                    continue
                flush_run()
                got = int(u["seq_no"])
                # the update carried its originating write's sampled
                # context: this apply joins the WRITE's trace (child of
                # the leader's repl.write), and re-records the context
                # so chained downstreams stitch onto the same trace
                with start_span("repl.apply", remote=tctx, db=self.name,
                                seq=got) as asp:
                    if pull_ctx is not None:
                        asp.annotate(pull_trace=pull_ctx["trace_id"])
                    self.wrapper.handle_replicate_response(
                        bytes(u["raw_data"]), u.get("timestamp"))
                    if asp.sampled:
                        self._remember_write_trace(got, asp)
            flush_run()
            for u in updates:
                ts = u.get("timestamp")
                if ts is not None:
                    self._stats.add_metric(
                        M["replication_lag_ms"], max(0, now - ts))
        self._stats.incr(M["pull_updates_applied"], len(updates))
        self._stats.incr(M["pull_bytes_applied"], total_bytes)
        # Wake OUR parked long-polls so chained downstream followers see the
        # new updates immediately (reference replicated_db.cpp:391).
        self._notifier.notify_all_threadsafe()

    def _next_pull_delay(self) -> float:
        """Compute (and account) the next pull-error backoff in seconds.
        A shard that has NEVER completed a pull rides the jittered
        fast-first-connect tier for its first few attempts — fleet cold
        start races pullers against leader spin-up, and the steady 5-10s
        floor would stagger 100-shard convergence across minutes. After
        that (or after any successful pull) the steady RetryPolicy floor
        rules. Shared by the solo loop and the mux session's per-shard
        error handling."""
        f = self.flags
        if (not self._ever_pulled
                and self._pull_retry_attempt < f.pull_fast_first_attempts):
            delay = self._pull_rng.uniform(
                f.pull_fast_min_ms / 1000.0, f.pull_fast_max_ms / 1000.0)
        else:
            delay = self._pull_retry.delay(
                self._pull_retry_attempt, self._pull_rng)
        self._pull_retry_attempt += 1
        self._stats.add_metric(
            "replicator.pull_backoff_ms", delay * 1000.0)
        return delay

    async def _pull_error_delay(self) -> None:
        await asyncio.sleep(self._next_pull_delay())

    async def _maybe_reset_upstream(self, force_sample: bool) -> None:
        """Query the leader resolver (reference: Helix GetLeaderInstanceId,
        sampled at 10% to avoid hammering the control plane)."""
        f = self.flags
        if self._leader_resolver is None:
            return
        if not force_sample and random.random() > f.upstream_reset_sample_rate:
            return
        try:
            new_addr = await self._loop.run_in_executor(
                self._executor, self._leader_resolver, self.name
            )
        except Exception:
            log.exception("%s: leader resolver failed", self.name)
            return
        if new_addr and tuple(new_addr) != tuple(self.upstream_addr or ()):
            log.info("%s: resetting upstream %s -> %s", self.name,
                     self.upstream_addr, new_addr)
            self.upstream_addr = tuple(new_addr)
            self._conn_errors = 0  # fresh upstream, fresh error budget
            self._stats.incr(M["upstream_resets"])

    def reset_upstream(self, addr: Tuple[str, int]) -> None:
        """Explicit upstream repoint (changeDBRoleAndUpStream path)."""
        self.upstream_addr = tuple(addr)
        self._conn_errors = 0

    # ------------------------------------------------------------------
    # introspection (replicated_db.cpp:168-182)
    # ------------------------------------------------------------------

    def introspect(self) -> str:
        # RELAXED seq read: the blocking read takes the storage lock,
        # which flush/compaction can hold for seconds — the serve path
        # already keeps it off the loop thread; the status-server path
        # must not hang on it either. Staleness is fine for status text.
        return (
            f"db={self.name} role={self.role.value} "
            f"mode={self.replication_mode} "
            f"latest_seq={self.wrapper.latest_sequence_number_relaxed()} "
            f"acked_seq={self._acked.value} "
            f"ack_window={self._acked.depth}/{self._acked.capacity} "
            f"upstream={self.upstream_addr} "
            f"epoch={self.epoch} fenced_by={self._fenced_by} "
            f"degraded={self._degraded} removed={self._removed}"
        )
