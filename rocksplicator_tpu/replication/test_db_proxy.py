"""TestDbProxy: a DbWrapper test double.

Reference: rocksdb_replicator/test_db_proxy.{h,cpp} — a tiny wrapper
delegating to the default wrapper, used to exercise wrapper-based addDB
(proving the DbWrapper seam composes). Also counts calls for assertions.
"""

from __future__ import annotations

from typing import Iterator, Tuple

from .db_wrapper import DbWrapper, StorageDbWrapper


class TestDbProxy(DbWrapper):
    def __init__(self, db):
        self._inner = StorageDbWrapper(db)
        self.writes = 0
        self.reads = 0
        self.applies = 0

    def write_to_leader(self, batch) -> int:
        self.writes += 1
        return self._inner.write_to_leader(batch)

    def get_updates_from_leader(self, since_seq: int) -> Iterator[Tuple[int, bytes]]:
        self.reads += 1
        return self._inner.get_updates_from_leader(since_seq)

    def latest_sequence_number(self) -> int:
        return self._inner.latest_sequence_number()

    def handle_replicate_response(self, raw_data, timestamp_ms) -> None:
        self.applies += 1
        self._inner.handle_replicate_response(raw_data, timestamp_ms)
