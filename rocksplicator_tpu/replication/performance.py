"""Replication throughput benchmark harness.

Reference: rocksdb_replicator/performance.cpp:57-207 — a two-process
benchmark (leader + follower binaries) writing N shards × M writer threads
× K keys of fixed-size values, reporting bytes/s and a stats dump.

Run the follower first, then the leader:

    python -m rocksplicator_tpu.replication.performance \
        --role follower --port 9092 --upstream_port 9091 --db_dir /tmp/f
    python -m rocksplicator_tpu.replication.performance \
        --role leader --port 9091 --db_dir /tmp/l \
        --num_shards 200 --num_write_threads 2 \
        --num_keys_per_shard_thread 10240 --value_size 1024
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from ..storage import DB, DBOptions, WriteBatch
from ..utils.stats import Stats
from .db_wrapper import StorageDbWrapper
from .replicated_db import ReplicationFlags
from .replicator import Replicator
from .wire import ReplicaRole


def _print_slowest_write_trace() -> None:
    """Print the slowest sampled write's span tree (the bench's --trace
    deliverable: per-phase attribution of ONE acked write — wal fsync vs
    ack wait — instead of only an aggregate writes/s). Emitted BEFORE the
    throughput line so harnesses that stop relaying output at that line
    still capture it; markers make it machine-extractable."""
    from ..observability.collector import SpanCollector, render_trace

    snap = SpanCollector.get().snapshot()  # one consistent ring view
    # repl.write = single pipelined/blocking write; repl.write_group =
    # one batched write_async_many commit (its ack_wait children are the
    # per-batch waits)
    writes = [s for s in snap
              if s["name"] in ("repl.write", "repl.write_group")]
    if not writes:
        print("TRACE-SLOWEST-WRITE-BEGIN none sampled", flush=True)
        print("TRACE-SLOWEST-WRITE-END", flush=True)
        _print_ack_window_depth(snap)
        return
    slowest = max(writes, key=lambda s: s["duration_ms"])
    trace = [s for s in snap if s["trace_id"] == slowest["trace_id"]]
    print(
        f"TRACE-SLOWEST-WRITE-BEGIN trace_id={slowest['trace_id']} "
        f"duration_ms={slowest['duration_ms']:.3f} "
        f"sampled_writes={len(writes)}",
        flush=True,
    )
    for line in render_trace(trace):
        print(line, flush=True)
    print("TRACE-SLOWEST-WRITE-END", flush=True)
    _print_ack_window_depth(snap)


def _print_ack_window_depth(snap) -> None:
    """Report the max number of OVERLAPPING sampled repl.ack_wait spans
    (sweep over the span intervals): pipelining proof. The serial write
    path can never exceed depth 1 per shard — depth > 1 means multiple
    writes were genuinely in flight awaiting acks at once."""
    acks = [s for s in snap if s["name"] == "repl.ack_wait"]
    events = []
    for s in acks:
        events.append((s["start_ms"], 1))
        events.append((s["start_ms"] + s["duration_ms"], -1))
    events.sort()
    depth = max_depth = 0
    for _t, d in events:
        depth += d
        max_depth = max(max_depth, depth)
    # registration-time window depth annotated on each span: per-shard
    # view (the sweep above spans all shards)
    per_shard = max(
        (int(s["annotations"].get("window_depth") or 0) for s in acks),
        default=0,
    )
    print(
        f"TRACE-ACK-WINDOW sampled_ack_waits={len(acks)} "
        f"max_overlapping={max_depth} max_window_depth={per_shard}",
        flush=True,
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--role", choices=["leader", "follower", "cluster"],
                   required=True,
                   help="cluster = leader + 2 followers COLOCATED in this "
                        "process (one IoLoop): the in-process loopback "
                        "transport's deployment shape, also a syscall-"
                        "free ceiling for uds/tcp on noisy hosts")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--upstream_ip", default="127.0.0.1")
    p.add_argument("--upstream_port", type=int, default=0)
    p.add_argument("--db_dir", required=True)
    # defaults mirror performance.cpp:57-66
    p.add_argument("--num_shards", type=int, default=200)
    p.add_argument("--num_write_threads", type=int, default=2)
    p.add_argument("--num_keys_per_shard_thread", type=int, default=10240)
    p.add_argument("--value_size", type=int, default=1024)
    p.add_argument("--replication_mode", type=int, default=0)
    p.add_argument("--write_window", type=int, default=64,
                   help="leader: max in-flight (unacked) writes per shard "
                        "(ReplicationFlags.write_window). 1 = the old "
                        "serial blocking write path, for A/B comparison")
    p.add_argument("--wait_sec", type=int, default=3600,
                   help="follower: how long to serve before exiting")
    p.add_argument("--warmup_wait_sec", type=float, default=20.0,
                   help="leader, ack modes only: wait until followers are "
                        "actually pulling (≥1 replicate request per shard) "
                        "before the timed write phase. Followers spawned "
                        "before the leader sit in 5-10s connect backoff; "
                        "the serial write path hid that race by blocking "
                        "on the first ack, a pipelined write phase would "
                        "otherwise complete before any puller connects "
                        "and measure nothing but timeouts")
    p.add_argument("--linger_sec", type=int, default=30,
                   help="leader: keep serving WAL after the write phase so "
                        "followers (possibly in connect backoff) catch up")
    p.add_argument("--trace", action="store_true",
                   help="sample per-write traces (observability/) and print "
                        "the slowest sampled write's span tree after the "
                        "write phase")
    p.add_argument("--trace_rate", type=float, default=1.0 / 64.0,
                   help="head-sampling rate for --trace")
    p.add_argument("--executor_threads", type=int, default=8,
                   help="replicator CPU executor size. The library "
                        "default (16, reference parity) thrashes the GIL "
                        "on small benchmark hosts: executor work here is "
                        "short WAL reads/applies, so a few threads keep "
                        "the disk busy without starving the IO loop")
    p.add_argument("--gil_switch_interval_ms", type=float, default=20.0,
                   help="sys.setswitchinterval for this process (0 = "
                        "leave Python's 5ms default). Write/serve/apply "
                        "threads are all short-quantum GIL contenders; "
                        "a longer quantum trades fairness for fewer "
                        "forced handoffs on the hot paths")
    args = p.parse_args(argv)

    if args.gil_switch_interval_ms > 0:
        sys.setswitchinterval(args.gil_switch_interval_ms / 1000.0)

    if args.trace:
        from ..observability.collector import SpanCollector

        # capacity sized so a default run's sampled spans survive to the
        # report (they'd otherwise rotate out of the 4096-slot ring)
        SpanCollector.get().configure(
            sample_rate=args.trace_rate, capacity=1 << 15,
            process=f"{args.role}:{args.port}")

    is_cluster = args.role == "cluster"
    replicator = Replicator(
        port=args.port,
        flags=ReplicationFlags(write_window=max(1, args.write_window)),
        executor_threads=max(1, args.executor_threads),
    )
    dbs = {}
    role = (ReplicaRole.FOLLOWER if args.role == "follower"
            else ReplicaRole.LEADER)
    upstream = (
        (args.upstream_ip, args.upstream_port) if args.upstream_port else None
    )
    leader_dir = os.path.join(args.db_dir, "l") if is_cluster else args.db_dir
    for shard in range(args.num_shards):
        name = f"perf{shard:05d}"
        db = DB(os.path.join(leader_dir, name),
                DBOptions(wal_ttl_seconds=3600.0))
        dbs[name] = db
        replicator.add_db(
            name, StorageDbWrapper(db), role,
            upstream_addr=upstream, replication_mode=args.replication_mode,
        )
    print(f"{args.role}: {args.num_shards} shards on :{replicator.port}",
          flush=True)

    # colocated followers AFTER the leader is serving: their pullers
    # connect immediately instead of sitting in connect backoff (all
    # three replicators share IoLoop.default(), which is what makes the
    # in-process loopback transport resolvable between them)
    follower_reps = []
    follower_dbs = []
    if is_cluster:
        for fi in (1, 2):
            rep = Replicator(
                port=args.port + fi,
                flags=ReplicationFlags(
                    write_window=max(1, args.write_window)),
                executor_threads=max(1, args.executor_threads),
            )
            fdbs = {}
            for shard in range(args.num_shards):
                name = f"perf{shard:05d}"
                db = DB(os.path.join(args.db_dir, f"f{fi}", name),
                        DBOptions(wal_ttl_seconds=3600.0))
                fdbs[name] = db
                rep.add_db(
                    name, StorageDbWrapper(db), ReplicaRole.FOLLOWER,
                    upstream_addr=("127.0.0.1", args.port),
                    replication_mode=args.replication_mode,
                )
            follower_reps.append(rep)
            follower_dbs.append(fdbs)
        print(f"cluster: 2 colocated followers on "
              f":{args.port + 1} :{args.port + 2}", flush=True)

    if args.role == "follower":
        try:
            end = time.monotonic() + args.wait_sec
            while time.monotonic() < end:
                time.sleep(5)
                total = sum(db.latest_sequence_number() for db in dbs.values())
                print(f"follower total seq: {total}", flush=True)
        except KeyboardInterrupt:
            pass
        replicator.stop()
        return 0

    if args.replication_mode in (1, 2) and args.warmup_wait_sec > 0:
        # PER-SHARD gate: every shard must have served ≥1 pull. A global
        # request count lets the write phase start while a few shards'
        # pullers are still in 5-10s connect backoff (the follower
        # processes race the leader's sequential add_db); those shards
        # then time out their entire first write window.
        rdb_list = [replicator.get_db(f"perf{s:05d}")
                    for s in range(args.num_shards)]
        deadline = time.monotonic() + args.warmup_wait_sec
        while (time.monotonic() < deadline
               and not all(r.serve_count > 0 for r in rdb_list)):
            time.sleep(0.1)
        live = sum(1 for r in rdb_list if r.serve_count > 0)
        print(
            f"leader warmup: {live}/{args.num_shards} shards have live "
            f"pullers before write phase",
            flush=True,
        )

    # leader: shard-striped writer threads (performance.cpp write loop).
    # With write_window > 1 the writers PIPELINE — and they TOP UP: each
    # pass issues only as many writes per shard as that shard's window
    # has free slots (non-blocking depth check), so a writer never
    # head-of-line blocks on one full window while its other shards'
    # windows drain to empty and their followers park in long-polls.
    # Only when EVERY owned shard is at capacity does the writer wait —
    # on the earliest pending futures, not on a sleep.
    value = b"v" * args.value_size
    total_keys = args.num_keys_per_shard_thread
    pipelined = args.write_window > 1
    acked_counts = [0] * args.num_write_threads

    def writer(tid: int) -> None:
        from collections import deque
        from concurrent.futures import FIRST_COMPLETED, wait as fwait

        my_shards = list(range(tid, args.num_shards, args.num_write_threads))
        names = {s: f"perf{s:05d}" for s in my_shards}
        rdbs = {s: replicator.get_db(names[s]) for s in my_shards}
        acked = 0

        if not pipelined:
            # write_async + immediate result() = the serial blocking
            # path (window 1 allows one in-flight write), but the waiter
            # exposes .acked — the bare write() returns the seq whether
            # the ack landed or timed out, which would count timed-out
            # writes as acked and inflate the serial A/B baseline
            for i in range(total_keys):
                for shard in my_shards:
                    batch = WriteBatch().put(
                        f"t{tid}-k{i:08d}".encode(), value)
                    w = replicator.write_async(names[shard], batch)
                    w.result()
                    if w.acked:
                        acked += 1
            acked_counts[tid] = acked
            return

        next_key = {s: 0 for s in my_shards}
        pending = {s: deque() for s in my_shards}

        def drain_done(shard) -> None:
            nonlocal acked
            dq = pending[shard]
            while dq and dq[0].future.done():
                if dq.popleft().acked:
                    acked += 1

        remaining = set(my_shards)
        while remaining or any(pending[s] for s in my_shards):
            progress = 0
            for shard in list(remaining):
                drain_done(shard)
                free = rdbs[shard].ack_window_free
                i = next_key[shard]
                n = min(free, total_keys - i)
                # don't dribble: a 1-2 write top-up pays a full WAL
                # flush + wakeup + (later) pull response for almost no
                # pipelining gain. Wait for a quarter-window of free
                # slots (or the tail) before topping up.
                if 0 < n < min(args.write_window // 4, total_keys - i):
                    n = 0
                if n:
                    # one write_async_many per top-up: the whole group
                    # commits with one WAL flush / wakeup / stats update
                    batches = [
                        WriteBatch().put(f"t{tid}-k{k:08d}".encode(), value)
                        for k in range(i, i + n)
                    ]
                    pending[shard].extend(
                        replicator.write_async_many(names[shard], batches))
                    next_key[shard] = i + n
                    progress += n
                    if next_key[shard] >= total_keys:
                        remaining.discard(shard)
            if progress:
                continue
            # every unfinished shard is at capacity (or all writes are
            # issued): park on the heads of the pending queues
            heads = [pending[s][0].future for s in my_shards if pending[s]]
            if heads:
                fwait(heads, timeout=0.5, return_when=FIRST_COMPLETED)
            for shard in my_shards:
                drain_done(shard)
        acked_counts[tid] = acked

    start = time.monotonic()
    threads = [
        threading.Thread(target=writer, args=(t,))
        for t in range(args.num_write_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # elapsed INCLUDES the final ack drain: with pipelining the write
    # phase isn't over until every in-flight write resolved, so the
    # writes/s numbers stay acked-write honest
    elapsed = time.monotonic() - start
    if args.trace:
        _print_slowest_write_trace()
    total_writes = total_keys * args.num_shards
    # exact byte count (each shard is written by exactly one thread, keys
    # times); the old mirrored formula used num_shards//num_write_threads
    # for every thread, undercounting when shards % threads != 0
    total_bytes = total_writes * args.value_size
    print(
        f"leader acked {sum(acked_counts)}/{total_writes} writes "
        f"window={args.write_window} mode={args.replication_mode}",
        flush=True,
    )
    print(
        f"leader wrote ~{total_bytes / 1e6:.1f} MB in {elapsed:.3f}s = "
        f"{total_bytes / elapsed / 1e6:.2f} MB/s",
        flush=True,
    )
    print(Stats.get().dump_text(), flush=True)
    if is_cluster:
        # colocated followers: poll convergence in-process instead of
        # lingering blind; the printed lines match what the 3-process
        # bench parses from separate follower stdouts
        want = total_writes
        deadline = time.monotonic() + max(1, args.linger_sec)
        while time.monotonic() < deadline:
            totals = [
                sum(db.latest_sequence_number() for db in fdbs.values())
                for fdbs in follower_dbs
            ]
            for i, tot in enumerate(totals):
                print(f"follower{i} total seq: {tot}", flush=True)
            if all(tot >= want for tot in totals):
                print("cluster converged", flush=True)
                break
            time.sleep(0.2)
        for rep in follower_reps:
            rep.stop()
        for fdbs in follower_dbs:
            for db in fdbs.values():
                db.close()
    elif args.linger_sec:
        print(f"leader lingering {args.linger_sec}s for follower catch-up",
              flush=True)
        time.sleep(args.linger_sec)
    replicator.stop()
    for db in dbs.values():
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
