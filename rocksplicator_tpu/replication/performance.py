"""Replication throughput benchmark harness.

Reference: rocksdb_replicator/performance.cpp:57-207 — a two-process
benchmark (leader + follower binaries) writing N shards × M writer threads
× K keys of fixed-size values, reporting bytes/s and a stats dump.

Run the follower first, then the leader:

    python -m rocksplicator_tpu.replication.performance \
        --role follower --port 9092 --upstream_port 9091 --db_dir /tmp/f
    python -m rocksplicator_tpu.replication.performance \
        --role leader --port 9091 --db_dir /tmp/l \
        --num_shards 200 --num_write_threads 2 \
        --num_keys_per_shard_thread 10240 --value_size 1024
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from ..storage import DB, DBOptions, WriteBatch
from ..utils.stats import Stats
from .db_wrapper import StorageDbWrapper
from .replicated_db import ReplicationFlags
from .replicator import Replicator
from .wire import ReplicaRole


def _print_slowest_write_trace() -> None:
    """Print the slowest sampled write's span tree (the bench's --trace
    deliverable: per-phase attribution of ONE acked write — wal fsync vs
    ack wait — instead of only an aggregate writes/s). Emitted BEFORE the
    throughput line so harnesses that stop relaying output at that line
    still capture it; markers make it machine-extractable."""
    from ..observability.collector import SpanCollector, render_trace

    snap = SpanCollector.get().snapshot()  # one consistent ring view
    writes = [s for s in snap if s["name"] == "repl.write"]
    if not writes:
        print("TRACE-SLOWEST-WRITE-BEGIN none sampled", flush=True)
        print("TRACE-SLOWEST-WRITE-END", flush=True)
        return
    slowest = max(writes, key=lambda s: s["duration_ms"])
    trace = [s for s in snap if s["trace_id"] == slowest["trace_id"]]
    print(
        f"TRACE-SLOWEST-WRITE-BEGIN trace_id={slowest['trace_id']} "
        f"duration_ms={slowest['duration_ms']:.3f} "
        f"sampled_writes={len(writes)}",
        flush=True,
    )
    for line in render_trace(trace):
        print(line, flush=True)
    print("TRACE-SLOWEST-WRITE-END", flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--role", choices=["leader", "follower"], required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--upstream_ip", default="127.0.0.1")
    p.add_argument("--upstream_port", type=int, default=0)
    p.add_argument("--db_dir", required=True)
    # defaults mirror performance.cpp:57-66
    p.add_argument("--num_shards", type=int, default=200)
    p.add_argument("--num_write_threads", type=int, default=2)
    p.add_argument("--num_keys_per_shard_thread", type=int, default=10240)
    p.add_argument("--value_size", type=int, default=1024)
    p.add_argument("--replication_mode", type=int, default=0)
    p.add_argument("--wait_sec", type=int, default=3600,
                   help="follower: how long to serve before exiting")
    p.add_argument("--linger_sec", type=int, default=30,
                   help="leader: keep serving WAL after the write phase so "
                        "followers (possibly in connect backoff) catch up")
    p.add_argument("--trace", action="store_true",
                   help="sample per-write traces (observability/) and print "
                        "the slowest sampled write's span tree after the "
                        "write phase")
    p.add_argument("--trace_rate", type=float, default=1.0 / 64.0,
                   help="head-sampling rate for --trace")
    args = p.parse_args(argv)

    if args.trace:
        from ..observability.collector import SpanCollector

        # capacity sized so a default run's sampled spans survive to the
        # report (they'd otherwise rotate out of the 4096-slot ring)
        SpanCollector.get().configure(
            sample_rate=args.trace_rate, capacity=1 << 15,
            process=f"{args.role}:{args.port}")

    replicator = Replicator(port=args.port)
    dbs = {}
    role = ReplicaRole.LEADER if args.role == "leader" else ReplicaRole.FOLLOWER
    upstream = (
        (args.upstream_ip, args.upstream_port) if args.upstream_port else None
    )
    for shard in range(args.num_shards):
        name = f"perf{shard:05d}"
        db = DB(os.path.join(args.db_dir, name),
                DBOptions(wal_ttl_seconds=3600.0))
        dbs[name] = db
        replicator.add_db(
            name, StorageDbWrapper(db), role,
            upstream_addr=upstream, replication_mode=args.replication_mode,
        )
    print(f"{args.role}: {args.num_shards} shards on :{replicator.port}",
          flush=True)

    if args.role == "follower":
        try:
            end = time.monotonic() + args.wait_sec
            while time.monotonic() < end:
                time.sleep(5)
                total = sum(db.latest_sequence_number() for db in dbs.values())
                print(f"follower total seq: {total}", flush=True)
        except KeyboardInterrupt:
            pass
        replicator.stop()
        return 0

    # leader: shard-striped writer threads (performance.cpp write loop)
    value = b"v" * args.value_size
    total_keys = args.num_keys_per_shard_thread

    def writer(tid: int) -> None:
        for i in range(total_keys):
            for shard in range(tid, args.num_shards, args.num_write_threads):
                name = f"perf{shard:05d}"
                replicator.write(
                    name,
                    WriteBatch().put(f"t{tid}-k{i:08d}".encode(), value),
                )

    start = time.monotonic()
    threads = [
        threading.Thread(target=writer, args=(t,))
        for t in range(args.num_write_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - start
    if args.trace:
        _print_slowest_write_trace()
    # reported formula mirrors performance.cpp:150-155
    total_bytes = (
        args.num_write_threads * total_keys
        * (args.num_shards // args.num_write_threads) * args.value_size
    )
    print(
        f"leader wrote ~{total_bytes / 1e6:.1f} MB in {elapsed:.1f}s = "
        f"{total_bytes / elapsed / 1e6:.2f} MB/s",
        flush=True,
    )
    print(Stats.get().dump_text(), flush=True)
    if args.linger_sec:
        print(f"leader lingering {args.linger_sec}s for follower catch-up",
              flush=True)
        time.sleep(args.linger_sec)
    replicator.stop()
    for db in dbs.values():
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
