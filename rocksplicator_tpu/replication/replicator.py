"""Replicator: owns the replication server, executor, pool, and db map.

Reference: rocksdb_replicator/rocksdb_replicator.h:83-256 — a singleton in
production (``instance()``) owning the replication thrift server (port
9091), a ≥16-thread CPU executor, a client pool, and the db map; tests
construct private instances on distinct ports to build multi-node
topologies in one process (rocksdb_replicator_test.cpp:137-144) — the
constructor here is public for exactly that reason.

``add_db``/``remove_db``/``write`` mirror the reference lifecycle;
removal stops the pull loop and waits for in-flight handlers to drain via
the removed flag (the reference spin-waits on a weak_ptr,
rocksdb_replicator.cpp:135-154 — here explicit ownership makes that a
cancel + flag).
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional, Tuple

from ..rpc.client_pool import RpcClientPool
from ..rpc.ioloop import IoLoop
from ..rpc.server import RpcServer
from ..storage.records import WriteBatch
from ..utils.concurrent_map import FastReadMap
from ..utils.dbconfig import DBConfigManager
from ..utils.segment_utils import db_name_to_segment
from ..utils.stats import Stats, tagged
from .db_wrapper import DbWrapper
from .handler import ReplicatorHandler
from .pull_mux import MuxServerState, PullMuxManager, mux_enabled
from .replicated_db import LeaderResolver, ReplicatedDB, ReplicationFlags
from .wire import ReplicaRole

log = logging.getLogger(__name__)

DEFAULT_REPLICATOR_PORT = 9091
_EXECUTOR_THREADS = 16  # reference: ≥16 CPU threads (rocksdb_replicator.cpp:58-67)


class Replicator:
    _instance: Optional["Replicator"] = None
    _instance_lock = threading.Lock()

    def __init__(
        self,
        port: int = 0,
        ioloop: Optional[IoLoop] = None,
        flags: Optional[ReplicationFlags] = None,
        executor_threads: int = _EXECUTOR_THREADS,
        server_ssl_manager=None,
        client_ssl_manager=None,
    ):
        self._ioloop = ioloop or IoLoop.default()
        self._flags = flags or ReplicationFlags()
        self._dbs: FastReadMap = FastReadMap()
        self._executor = ThreadPoolExecutor(
            max_workers=executor_threads, thread_name_prefix="replicator"
        )
        # TLS for the WAL-shipping plane (reference: SSL in the thrift
        # client pool, thrift_client_pool.h:254-290; refreshable context
        # ssl_context_manager.h) — both sides optional, mutual-TLS when
        # the managers carry a CA.
        self._pool = RpcClientPool(ssl_manager=client_ssl_manager)
        # Mux pull sessions (round 22): the SERVER side always answers
        # replicate_mux (so mux-enabled peers can pull from anyone); the
        # CLIENT side multiplexes only when the killswitch allows.
        self._mux_state = MuxServerState()
        self._pull_mux: Optional[PullMuxManager] = (
            PullMuxManager(self._ioloop.loop, self._executor, self._pool,
                           self._flags)
            if mux_enabled(self._flags) else None)
        self._server = RpcServer(port=port, ioloop=self._ioloop,
                                 ssl_manager=server_ssl_manager)
        self._server.add_handler(
            ReplicatorHandler(self._dbs, mux_state=self._mux_state))
        self._server.start()
        # parked long-polls on THIS replica (per-shard parks + parked mux
        # sessions): the fleet A/B's park gauge, per-port so in-process
        # topologies keep one series per replica
        self._parked_gauge = tagged("replicator.parked_longpolls",
                                    port=str(self._server.port))
        Stats.get().add_gauge(self._parked_gauge, self._parked_longpolls)
        self._maintenance_stop = threading.Event()
        self._maintenance = threading.Thread(
            target=self._maintenance_loop, name="replicator-maint", daemon=True
        )
        self._maintenance.start()

    @classmethod
    def instance(cls, port: int = DEFAULT_REPLICATOR_PORT) -> "Replicator":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls(port=port)
        return cls._instance

    @classmethod
    def reset_for_test(cls) -> None:
        with cls._instance_lock:
            if cls._instance is not None:
                cls._instance.stop()
            cls._instance = None

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def ioloop(self) -> IoLoop:
        return self._ioloop

    # ------------------------------------------------------------------

    def add_db(
        self,
        name: str,
        wrapper: DbWrapper,
        role: ReplicaRole,
        upstream_addr: Optional[Tuple[str, int]] = None,
        replication_mode: Optional[int] = None,
        leader_resolver: Optional[LeaderResolver] = None,
        epoch: int = 0,
    ) -> ReplicatedDB:
        """Register a db for replication. Duplicate names are an error
        (reference returns DB_ALREADY_EXISTS)."""
        if replication_mode is None:
            # Per-dataset config with default 0 (replicated_db.cpp:131-136).
            try:
                segment = db_name_to_segment(name)
            except ValueError:
                segment = name
            replication_mode = DBConfigManager.get().get_replication_mode(segment)
        rdb = ReplicatedDB(
            name=name,
            wrapper=wrapper,
            role=role,
            loop=self._ioloop.loop,
            executor=self._executor,
            pool=self._pool,
            upstream_addr=upstream_addr,
            replication_mode=replication_mode,
            flags=self._flags,
            leader_resolver=leader_resolver,
            epoch=epoch,
            stat_tags={"port": str(self.port)},
            mux=self._pull_mux,
        )
        if not self._dbs.add(name, rdb):
            raise ValueError(f"db already exists: {name}")
        try:
            rdb.start()
        except BaseException:
            # Never leave a zombie registration behind a failed start.
            self._dbs.remove(name)
            rdb.stop()
            raise
        self._register_shard_gauges(name, rdb, wrapper)
        self._maybe_attach_remote_compactor(name, rdb, wrapper)
        return rdb

    def _maybe_attach_remote_compactor(self, name: str, rdb: ReplicatedDB,
                                       wrapper: DbWrapper) -> None:
        """Round 18: when the environment opts in (RSTPU_COMPACT_REMOTE
        + coordinator endpoint + store URI), hook this shard's engine
        into the disaggregated compaction tier — pressure picks above
        the size floor publish to the job ledger instead of merging on
        the serving node. The epoch provider reads the shard's LIVE
        fencing epoch, so a job published before a deposition is
        rejected at install (the round-11 fencing rule extended to
        compaction). The ledger key is name@port: unique per replica,
        since every replica compacts independently. Never fatal — the
        tier is an optimization, serving never depends on it."""
        engine = wrapper.gauge_target()
        if engine is None:
            return
        try:
            from ..compaction_remote.dispatch import attach_from_env

            rdb._remote_compaction_mgr = attach_from_env(
                f"{name}@{self.port}", engine,
                epoch_provider=lambda: rdb.epoch)
        except Exception:
            log.exception("remote-compaction attach failed for %s", name)
            rdb._remote_compaction_mgr = None

    def _register_shard_gauges(self, name: str, rdb: ReplicatedDB,
                               wrapper: DbWrapper) -> None:
        """Pull-model gauges for this shard (round 14): replication lag
        + ack-window occupancy here, the engine's level/amp/debt gauges
        when the wrapper exposes a local engine. Tagged with this
        replicator's port so multi-replicator (in-process cluster) test
        topologies keep one gauge series per replica."""
        from ..storage.engine import register_db_gauges

        stats = Stats.get()
        port = str(self.port)
        names = []
        lag_name = tagged("replicator.applied_seq_lag", db=name, port=port)
        stats.add_gauge(lag_name, rdb.applied_seq_lag)
        names.append(lag_name)
        depth_name = tagged("replicator.ack_window_depth", db=name,
                            port=port)
        stats.add_gauge(depth_name, lambda: float(rdb.ack_window_depth))
        names.append(depth_name)
        engine = wrapper.gauge_target()
        if engine is not None:
            names.extend(register_db_gauges(name, engine, stats=stats,
                                            port=port))
        rdb._gauge_names = names

    def _unregister_shard_gauges(self, rdb: ReplicatedDB) -> None:
        stats = Stats.get()
        for gname in getattr(rdb, "_gauge_names", ()):
            stats.remove_gauge(gname)
        rdb._gauge_names = []

    def remove_db(self, name: str) -> None:
        rdb = self._dbs.get(name)
        if rdb is None:
            raise KeyError(f"no such db: {name}")
        rdb.stop()
        self._unregister_shard_gauges(rdb)
        mgr = getattr(rdb, "_remote_compaction_mgr", None)
        if mgr is not None:
            from ..compaction_remote.dispatch import detach

            detach(rdb.wrapper.gauge_target(), mgr)
            rdb._remote_compaction_mgr = None
        self._dbs.remove(name)

    def get_db(self, name: str) -> Optional[ReplicatedDB]:
        return self._dbs.get(name)

    def write(self, name: str, batch: WriteBatch) -> int:
        rdb = self._dbs.get(name)
        if rdb is None:
            raise KeyError(f"no such db: {name}")
        return rdb.write(batch)

    def write_async(self, name: str, batch: WriteBatch):
        """Pipelined write: WAL-write now, return an AckWaiter whose
        future resolves when the replication-mode ack condition is met
        (or its timeout expires). See ReplicatedDB.write_async."""
        rdb = self._dbs.get(name)
        if rdb is None:
            raise KeyError(f"no such db: {name}")
        return rdb.write_async(batch)

    def write_async_many(self, name: str, batches):
        """Batched pipelined writes: one WAL flush / wakeup / stats
        update for the whole group, one AckWaiter per batch. See
        ReplicatedDB.write_async_many."""
        rdb = self._dbs.get(name)
        if rdb is None:
            raise KeyError(f"no such db: {name}")
        return rdb.write_async_many(batches)

    def introspect(self) -> str:
        lines = [rdb.introspect() for _name, rdb in sorted(self._dbs.items())]
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------------

    def _maintenance_loop(self) -> None:
        """Periodic iterator-cache eviction (reference CachedIterCleaner's
        background EventBase thread, cached_iter_cleaner.cpp:29-78)."""
        while not self._maintenance_stop.wait(5.0):
            for _name, rdb in self._dbs.items():
                rdb._iter_cache.evict_idle()

    def _parked_longpolls(self) -> float:
        """Gauge: serves currently parked on this replica — per-shard
        long-poll parks plus parked mux sessions."""
        total = self._mux_state.parked
        for _name, rdb in self._dbs.items():
            total += rdb._parked_serves
        return float(total)

    def stop(self) -> None:
        self._maintenance_stop.set()
        if self._pull_mux is not None:
            self._pull_mux.stop()
        for _name, rdb in list(self._dbs.items()):
            rdb.stop()
            self._unregister_shard_gauges(rdb)
        self._dbs.clear()
        Stats.get().remove_gauge(self._parked_gauge)
        self._server.stop()
        self._ioloop.run_sync(self._pool.close())
        self._executor.shutdown(wait=False)
        self._maintenance.join(timeout=2.0)
