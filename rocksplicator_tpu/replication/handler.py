"""ReplicatorHandler: the replicate RPC service handler.

Reference: rocksdb_replicator/replicator_handler.cpp:24-41 — db-name lookup
in the FastReadMap, delegate to ReplicatedDB::handleReplicateRequest,
SOURCE_NOT_FOUND otherwise.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..observability.context import current_span
from ..rpc.errors import RpcApplicationError
from ..utils.concurrent_map import FastReadMap
from ..utils.stats import Stats
from .wire import ReplicaRole, ReplicateErrorCode


class ReplicatorHandler:
    def __init__(self, db_map: FastReadMap, mux_state=None):
        self._dbs = db_map
        if mux_state is None:
            from .pull_mux import MuxServerState

            mux_state = MuxServerState()
        self._mux_state = mux_state

    async def handle_replicate(
        self,
        db_name: str = "",
        seq_no: int = 0,
        max_wait_ms: Optional[int] = None,
        max_updates: Optional[int] = None,
        role: str = ReplicaRole.FOLLOWER.value,
        applied_seq: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> dict:
        span = current_span()
        if span is not None and span.sampled:
            # tag the enclosing rpc.server span: /traces readers filter
            # replicate traffic by shard without opening child spans
            span.annotate(db=db_name, from_seq=seq_no,
                          max_updates=max_updates)
        db = self._dbs.get(db_name)
        if db is None or db.removed:
            raise RpcApplicationError(
                ReplicateErrorCode.SOURCE_NOT_FOUND.value, db_name
            )
        # Response carries latest_seq (CDC "start from now" probes, catch-up
        # progress) and source_role (puller's stale-leader detection).
        return await db.handle_replicate_request(
            seq_no=seq_no, max_wait_ms=max_wait_ms,
            max_updates=max_updates, role=role, applied_seq=applied_seq,
            epoch=epoch,
        )

    async def handle_replicate_mux(
        self,
        sections: Optional[dict] = None,
        max_wait_ms: Optional[int] = None,
        budget: Optional[int] = None,
    ) -> dict:
        """Multiplexed pull (round 22): ONE long-poll carrying the cursor
        set for every shard the peer pulls from this node; per-shard
        sections come back in one response, each with the exact
        semantics (fencing, acks, WAL typing, commit point) of a
        per-shard ``replicate`` — see replication/pull_mux.py."""
        span = current_span()
        if span is not None and span.sampled:
            span.annotate(mux_sections=len(sections or ()))
        return await self._mux_state.serve(
            self._dbs, sections or {}, max_wait_ms=max_wait_ms,
            budget=budget)

    async def handle_replicate_ack(
        self,
        db_name: str = "",
        applied_seq: int = 0,
        role: str = ReplicaRole.FOLLOWER.value,
        epoch: Optional[int] = None,
    ) -> dict:
        """Lightweight applied-position push from a pipelined puller whose
        next pull is a parked long-poll: lets mode-2 ack waiters resolve
        at the follower's apply time instead of the next pull."""
        db = self._dbs.get(db_name)
        if db is None or db.removed:
            raise RpcApplicationError(
                ReplicateErrorCode.SOURCE_NOT_FOUND.value, db_name
            )
        db.post_applied(applied_seq, role, epoch=epoch)
        return {"acked_seq": db._acked.value, "epoch": db.epoch}

    async def handle_read(
        self,
        db_name: str = "",
        op: str = "get",
        keys=None,
        start=None,
        count: Optional[int] = None,
        max_lag: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> dict:
        """Bounded-staleness read (round 13): any replica — LEADER or
        FOLLOWER within ``max_lag`` of the leader's committed sequence —
        serves get/multi_get/scan; a follower on a deposed lineage
        rejects exactly as it rejects stale-epoch pulls."""
        span = current_span()
        if span is not None and span.sampled:
            span.annotate(db=db_name, op=op)
        db = self._dbs.get(db_name)
        if db is None or db.removed:
            raise RpcApplicationError(
                ReplicateErrorCode.SOURCE_NOT_FOUND.value, db_name
            )
        return await db.handle_read_request(
            op=op, keys=keys, start=start, count=count, max_lag=max_lag,
            epoch=epoch,
        )

    async def handle_stats(self) -> dict:
        """Process stats export for the spectator's scrape loop (round
        14): every counter/gauge plus the exact all-time histogram
        states (``Stats.export_state``), tagged with this node's shard
        roles so the aggregator can attribute per-shard series without
        a second control-plane lookup. Runs in the executor — the
        export drains thread buffers under locks and evaluates engine
        gauges, none of which belongs on the event loop."""
        roles = {name: rdb.role.value for name, rdb in self._dbs.items()
                 if not rdb.removed}
        loop = asyncio.get_running_loop()
        # cached dump (round 22): at fleet shape the export's gauge
        # sweep is O(shards); the short-TTL cache makes concurrent
        # scrapers (spectator + /metrics pollers) share one pass. The
        # cached dict is shared — copy the top level before annotating.
        state = dict(await loop.run_in_executor(
            None, Stats.get().export_state_cached))
        state["shard_roles"] = roles
        return state

    async def handle_write(
        self,
        db_name: str = "",
        raw_batch=None,
        epoch: Optional[int] = None,
    ) -> dict:
        """Remote leader write (the macro-bench's full-stack put path):
        one encoded WriteBatch in, {seq, acked} out once the replication
        ack condition resolves. Non-leaders raise NOT_LEADER; a deposed
        leader raises STALE_EPOCH."""
        db = self._dbs.get(db_name)
        if db is None or db.removed:
            raise RpcApplicationError(
                ReplicateErrorCode.SOURCE_NOT_FOUND.value, db_name
            )
        if raw_batch is None:
            raise RpcApplicationError("BAD_WRITE", "raw_batch required")
        return await db.handle_write_request(raw_batch, epoch=epoch)
