"""DbWrapper: the 4-method seam between replication and storage.

Reference: rocksdb_replicator/db_wrapper.h:6-15. **This is the boundary the
TPU offload backend plugs into** (BASELINE.json): replication never touches
the engine directly, so a wrapper can route writes/compaction through
offloaded paths — or, for CDC observers, publish updates instead of
persisting them (cdc_admin/cdc_application_db.cpp:15-41).
"""

from __future__ import annotations

import time
from typing import Iterator, List, Optional, Tuple

from ..storage.engine import DB
from ..storage.records import WriteBatch, decode_batch


class DbWrapper:
    """Abstract seam (db_wrapper.h)."""

    def write_to_leader(self, batch: WriteBatch) -> int:
        """Apply a leader-side write. Returns the batch's start seq."""
        raise NotImplementedError

    def get_updates_from_leader(
        self, since_seq: int
    ) -> Iterator[Tuple[int, bytes]]:
        """Iterator (cursor) of (start_seq, raw_batch_bytes) for batches
        with start_seq >= since_seq. The replicator caches live cursors
        between long-poll requests (replicated_db.cpp:577-611)."""
        raise NotImplementedError

    def latest_sequence_number(self) -> int:
        raise NotImplementedError

    def handle_replicate_response(self, raw_data: bytes, timestamp_ms: Optional[int]) -> None:
        """Apply one replicated update locally (follower path)."""
        raise NotImplementedError


class StorageDbWrapper(DbWrapper):
    """Default wrapper over the LSM engine (rocksdb_wrapper.{h,cpp}):
    write → db.write; updates → db.get_updates_since; replicate response →
    decode raw batch, apply locally keeping the embedded timestamp so
    chained downstream followers still see the leader's stamp."""

    def __init__(self, db: DB):
        self.db = db

    def write_to_leader(self, batch: WriteBatch) -> int:
        return self.db.write(batch)

    def get_updates_from_leader(
        self, since_seq: int
    ) -> Iterator[Tuple[int, bytes]]:
        return self.db.get_updates_since(since_seq)

    def latest_sequence_number(self) -> int:
        return self.db.latest_sequence_number()

    def handle_replicate_response(self, raw_data: bytes, timestamp_ms: Optional[int]) -> None:
        # The raw batch still carries the leader's LOG_DATA timestamp, so
        # applying it verbatim preserves the stamp for chained downstream
        # followers (reference re-stamps explicitly; here the bytes already
        # contain it).
        batch = decode_batch(raw_data)
        self.db.write(batch)
