"""DbWrapper: the 4-method seam between replication and storage.

Reference: rocksdb_replicator/db_wrapper.h:6-15. **This is the boundary the
TPU offload backend plugs into** (BASELINE.json): replication never touches
the engine directly, so a wrapper can route writes/compaction through
offloaded paths — or, for CDC observers, publish updates instead of
persisting them (cdc_admin/cdc_application_db.cpp:15-41).
"""

from __future__ import annotations

import time
from typing import Iterator, List, Optional, Tuple

from ..storage.engine import DB
from ..storage.records import WriteBatch, decode_batch


def execute_read_op(reader, op: str, keys=None, start=None,
                    count=None) -> list:
    """The ONE home of get/multi_get/scan dispatch semantics, shared by
    every read surface (`ReplicatedDB._do_read`, `ApplicationDB.read`)
    so the RPC and in-process paths cannot diverge. ``reader`` exposes
    ``get`` / ``multi_get`` / ``scan(start, limit)``."""
    if op == "get":
        key = (keys[0] if keys else None) \
            if isinstance(keys, (list, tuple)) else keys
        if key is None:
            raise ValueError("get requires a key")
        return [reader.get(bytes(key))]
    if op == "multi_get":
        return reader.multi_get([bytes(k) for k in (keys or [])])
    if op == "scan":
        limit = 10 if count is None else max(1, int(count))
        s = bytes(start) if start is not None else None
        return [[k, v] for k, v in reader.scan(s, limit)]
    raise ValueError(f"unknown read op {op!r}")


class DbWrapper:
    """Abstract seam (db_wrapper.h)."""

    def write_to_leader(self, batch: WriteBatch) -> int:
        """Apply a leader-side write. Returns the batch's start seq."""
        raise NotImplementedError

    def write_to_leader_many(self, batches) -> int:
        """Apply a GROUP of leader-side writes in order; returns the
        FIRST batch's start seq (each batch occupies its own contiguous
        seq range after it). Wrappers with a batched engine path
        override this to amortize per-write costs (lock, WAL flush);
        the default preserves the one-by-one contract."""
        first = None
        for b in batches:
            seq = self.write_to_leader(b)
            if first is None:
                first = seq
        if first is None:
            raise ValueError("write_to_leader_many: empty group")
        return first

    def get_updates_from_leader(
        self, since_seq: int
    ) -> Iterator[Tuple[int, bytes]]:
        """Iterator (cursor) of (start_seq, raw_batch_bytes) for batches
        with start_seq >= since_seq. The replicator caches live cursors
        between long-poll requests (replicated_db.cpp:577-611)."""
        raise NotImplementedError

    def latest_sequence_number(self) -> int:
        raise NotImplementedError

    def latest_sequence_number_relaxed(self) -> int:
        """Lock-free/stale-tolerant seq read for introspection paths that
        must never block behind flush/compaction holding the storage
        lock. Wrappers without a cheap relaxed read fall back to the
        locking one."""
        return self.latest_sequence_number()

    def handle_replicate_response(self, raw_data: bytes, timestamp_ms: Optional[int]) -> None:
        """Apply one replicated update locally (follower path)."""
        raise NotImplementedError

    def handle_replicate_updates(self, updates) -> None:
        """Apply a GROUP of replicated updates (one pull response) in
        order. Wrappers with a batched write path override this to
        amortize per-record costs; the default preserves the one-by-one
        contract for existing wrappers (test proxies, CDC observers)."""
        for u in updates:
            self.handle_replicate_response(
                bytes(u["raw_data"]), u.get("timestamp"))

    # -- serving reads (round 13: bounded-staleness follower reads) ------
    # Wrappers that persist locally expose the engine's read surface so
    # any replica — not just the leader — can serve reads; CDC observers
    # and other non-persisting wrappers keep the default and the read
    # handler turns it into a clean RPC error.

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError("wrapper does not serve reads")

    def multi_get(self, keys: List[bytes]) -> List[Optional[bytes]]:
        return [self.get(k) for k in keys]

    def scan(self, start: Optional[bytes], limit: int
             ) -> List[Tuple[bytes, bytes]]:
        raise NotImplementedError("wrapper does not serve scans")

    # -- observability (round 14: engine introspection gauges) -----------

    def gauge_target(self) -> Optional[DB]:
        """The engine whose pull-model gauges should be registered for
        this shard (``engine.register_db_gauges``), or None for wrappers
        with no local engine (CDC observers, test proxies)."""
        return None


class StorageDbWrapper(DbWrapper):
    """Default wrapper over the LSM engine (rocksdb_wrapper.{h,cpp}):
    write → db.write; updates → db.get_updates_since; replicate response →
    decode raw batch, apply locally keeping the embedded timestamp so
    chained downstream followers still see the leader's stamp."""

    def __init__(self, db: DB):
        self.db = db

    def write_to_leader(self, batch: WriteBatch) -> int:
        return self.db.write(batch)

    def write_to_leader_many(self, batches) -> int:
        return self.db.write_many([(b, None) for b in batches])

    def get_updates_from_leader(
        self, since_seq: int
    ) -> Iterator[Tuple[int, bytes]]:
        # resumable tail cursor (resumable=True): the serve path's
        # IterCache keeps it across pulls even when a response drains to
        # the live tail, so steady-state serving never re-scans the
        # active WAL segment
        return self.db.get_updates_cursor(since_seq)

    def latest_sequence_number(self) -> int:
        return self.db.latest_sequence_number()

    def latest_sequence_number_relaxed(self) -> int:
        return self.db.latest_sequence_number_relaxed()

    def handle_replicate_response(self, raw_data: bytes, timestamp_ms: Optional[int]) -> None:
        # The raw batch still carries the leader's LOG_DATA timestamp, so
        # applying it verbatim preserves the stamp for chained downstream
        # followers (reference re-stamps explicitly; here the bytes already
        # contain it). Passing the raw bytes through skips the WAL
        # re-encode — decode + encode per applied update was pure waste on
        # the follower apply hot path.
        batch = decode_batch(raw_data)
        self.db.write(batch, encoded=bytes(raw_data))

    def handle_replicate_updates(self, updates) -> None:
        """Batched apply: one engine write_many per pull response — one
        storage-lock pass and ONE WAL flush for the whole group (the
        per-record flush syscall dominated the apply hot path once
        leader writes pipelined)."""
        items = []
        for u in updates:
            raw = bytes(u["raw_data"])
            items.append((decode_batch(raw), raw))
        self.db.write_many(items)

    def get(self, key: bytes) -> Optional[bytes]:
        return self.db.get(key)

    def multi_get(self, keys: List[bytes]) -> List[Optional[bytes]]:
        return self.db.multi_get(keys)

    def scan(self, start: Optional[bytes], limit: int
             ) -> List[Tuple[bytes, bytes]]:
        out: List[Tuple[bytes, bytes]] = []
        for k, v in self.db.new_iterator(start=start):
            out.append((k, v))
            if len(out) >= limit:
                break
        return out

    def gauge_target(self) -> Optional[DB]:
        return self.db
