"""AsyncNotifier: park long-poll requests without holding threads.

Reference: rocksdb_replicator/non_blocking_condition_variable.h:40-165 —
an executor-backed condition variable where a task runs when its predicate
is true, when notifyAll fires, or on timeout, exactly once. With asyncio
the same contract is a notifier whose ``wait(timeout)`` parks a coroutine
(no thread held — same property that lets thousands of long-polls park)
and a thread-safe ``notify_all`` that wakes every parked waiter.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Set


class AsyncNotifier:
    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._waiters: Set[asyncio.Future] = set()
        self._wake_pending = False

    async def wait(self, timeout_sec: float) -> bool:
        """Park until notify_all or timeout. True iff notified."""
        fut = self.reserve()
        return await self.wait_reserved(fut, timeout_sec)

    def reserve(self) -> asyncio.Future:
        """Register a waiter slot NOW (loop thread only) without parking.
        Lets a caller re-check its predicate AFTER registration — any
        state change after reserve() is guaranteed to notify this slot,
        so the check-then-park race has no missed-wakeup window — which
        in turn makes the writer-side empty-set fast path sound."""
        fut: asyncio.Future = self._loop.create_future()
        self._waiters.add(fut)
        return fut

    async def wait_reserved(self, fut: asyncio.Future,
                            timeout_sec: float) -> bool:
        """Park on a slot from reserve(). True iff notified."""
        try:
            await asyncio.wait_for(fut, timeout_sec)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._waiters.discard(fut)

    def cancel_reserved(self, fut: asyncio.Future) -> None:
        """Release an unused slot (predicate became true before parking)."""
        self._waiters.discard(fut)

    def notify_all(self) -> None:
        """Callable only on the loop thread; use notify_all_threadsafe
        elsewhere."""
        for fut in self._waiters:
            if not fut.done():
                fut.set_result(True)
        self._waiters.clear()

    def notify_all_threadsafe(self) -> None:
        # Empty-set fast path: per-write loop wakeups would otherwise cost
        # a syscall + loop callback per write even with nobody parked (the
        # common pipelined steady state — pullers have backlog and don't
        # park). Safe because waiters register via reserve() BEFORE
        # re-checking the condition: a writer observing the pre-reserve
        # empty set implies the waiter's post-reserve check sees that
        # write. (_waiters mutates only on the loop thread; reading its
        # emptiness from another thread is GIL-atomic.)
        if not self._waiters:
            return
        # Coalescing: N writes landing between two loop iterations
        # schedule ONE wakeup (one self-pipe write), not N. _wake clears
        # the flag BEFORE notifying, so a write racing the notify
        # schedules a fresh wakeup and nothing is missed.
        if self._wake_pending:
            return
        self._wake_pending = True
        self._loop.call_soon_threadsafe(self._wake)

    def _wake(self) -> None:
        self._wake_pending = False
        self.notify_all()
