"""AsyncNotifier: park long-poll requests without holding threads.

Reference: rocksdb_replicator/non_blocking_condition_variable.h:40-165 —
an executor-backed condition variable where a task runs when its predicate
is true, when notifyAll fires, or on timeout, exactly once. With asyncio
the same contract is a notifier whose ``wait(timeout)`` parks a coroutine
(no thread held — same property that lets thousands of long-polls park)
and a thread-safe ``notify_all`` that wakes every parked waiter.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Set


class AsyncNotifier:
    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._waiters: Set[asyncio.Future] = set()

    async def wait(self, timeout_sec: float) -> bool:
        """Park until notify_all or timeout. True iff notified."""
        fut: asyncio.Future = self._loop.create_future()
        self._waiters.add(fut)
        try:
            await asyncio.wait_for(fut, timeout_sec)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._waiters.discard(fut)

    def notify_all(self) -> None:
        """Callable only on the loop thread; use notify_all_threadsafe
        elsewhere."""
        for fut in self._waiters:
            if not fut.done():
                fut.set_result(True)
        self._waiters.clear()

    def notify_all_threadsafe(self) -> None:
        self._loop.call_soon_threadsafe(self.notify_all)
