"""Replication core (reference: rocksdb_replicator/ — SURVEY.md §2.1).

Per-shard leader/follower chained replication:
- leaders stamp timestamps into batches and serve WAL updates to
  long-polling followers;
- followers pull, apply raw batches, and chain to further followers;
- OBSERVER replicas replicate without counting toward ACKs (CDC seam);
- ack modes: 0 async, 1 semi-sync, 2 sync, with fail-fast degradation.
"""

from .wire import ReplicaRole, ReplicateErrorCode, REPLICATOR_METRICS
from .ack_window import AckWaiter, AckWindow, MaxNumberBox
from .db_wrapper import DbWrapper, StorageDbWrapper
from .replicated_db import ReplicatedDB, ReplicationFlags
from .replicator import Replicator

__all__ = [
    "ReplicaRole", "ReplicateErrorCode", "REPLICATOR_METRICS",
    "DbWrapper", "StorageDbWrapper", "MaxNumberBox",
    "AckWaiter", "AckWindow",
    "ReplicatedDB", "ReplicationFlags", "Replicator",
]
