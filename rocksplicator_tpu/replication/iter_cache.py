"""Cached WAL update iterators with idle eviction.

Reference: replicated_db.cpp:577-611 (cached TransactionLogIterators so
long WAL scans don't restart per request) + cached_iter_cleaner.cpp:29-78
(background eviction of iterators idle > 60s).

A cached cursor is keyed by the next seq it will serve; a follower's steady
pull stream hits the cache every time (seq_n+1 == next), so serving N
updates costs one WAL position, not a rescan from seq 0.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..utils.stats import Stats
from .wire import REPLICATOR_METRICS as M


class _Cursor:
    __slots__ = ("it", "next_seq", "last_used")

    def __init__(self, it: Iterator[Tuple[int, bytes]], next_seq: int):
        self.it = it
        self.next_seq = next_seq
        self.last_used = time.monotonic()


class IterCache:
    """next_seq → cursors. Multiple cursors may share one key: two
    followers tailing the same shard both park at the same next_seq, and
    a single-slot map would make them evict each other every pull (each
    miss re-scans the active WAL segment — the exact cost the cache
    exists to avoid)."""

    def __init__(self, idle_timeout_sec: float = 60.0, max_cursors: int = 8):
        self._idle_timeout = idle_timeout_sec
        self._max = max_cursors
        self._lock = threading.Lock()
        self._cursors: Dict[int, List[_Cursor]] = {}

    @staticmethod
    def _close(cur: _Cursor) -> None:
        close = getattr(cur.it, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass

    def take(self, next_seq: int) -> Optional[Iterator[Tuple[int, bytes]]]:
        """Pop a cursor positioned at next_seq, if cached."""
        with self._lock:
            lst = self._cursors.get(next_seq)
            cur = lst.pop() if lst else None
            if lst is not None and not lst:
                self._cursors.pop(next_seq, None)
        if cur is not None:
            Stats.get().incr(M["iter_cache_hits"])
            return cur.it
        Stats.get().incr(M["iter_cache_misses"])
        return None

    def put(self, next_seq: int, it: Iterator[Tuple[int, bytes]]) -> None:
        evicted = None
        with self._lock:
            self._cursors.setdefault(next_seq, []).append(_Cursor(it, next_seq))
            total = sum(len(v) for v in self._cursors.values())
            if total > self._max:
                oldest_key = min(
                    self._cursors,
                    key=lambda k: min(c.last_used for c in self._cursors[k]),
                )
                lst = self._cursors[oldest_key]
                lst.sort(key=lambda c: c.last_used)
                evicted = lst.pop(0)
                if not lst:
                    del self._cursors[oldest_key]
        if evicted is not None:
            self._close(evicted)

    def evict_idle(self, now: Optional[float] = None) -> int:
        """Reference CachedIterCleaner behavior; called by the replicator's
        periodic maintenance task."""
        now = time.monotonic() if now is None else now
        evicted: List[_Cursor] = []
        with self._lock:
            for k in list(self._cursors):
                lst = self._cursors[k]
                keep = []
                for c in lst:
                    (keep if now - c.last_used <= self._idle_timeout
                     else evicted).append(c)
                if keep:
                    self._cursors[k] = keep
                else:
                    del self._cursors[k]
        for c in evicted:
            self._close(c)
        return len(evicted)

    def clear(self) -> None:
        with self._lock:
            dropped = [c for lst in self._cursors.values() for c in lst]
            self._cursors.clear()
        for c in dropped:
            self._close(c)
