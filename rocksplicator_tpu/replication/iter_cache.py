"""Cached WAL update iterators with idle eviction.

Reference: replicated_db.cpp:577-611 (cached TransactionLogIterators so
long WAL scans don't restart per request) + cached_iter_cleaner.cpp:29-78
(background eviction of iterators idle > 60s).

A cached cursor is keyed by the next seq it will serve; a follower's steady
pull stream hits the cache every time (seq_n+1 == next), so serving N
updates costs one WAL position, not a rescan from seq 0.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..utils.stats import Stats
from .wire import REPLICATOR_METRICS as M


class _Cursor:
    __slots__ = ("it", "next_seq", "last_used")

    def __init__(self, it: Iterator[Tuple[int, bytes]], next_seq: int):
        self.it = it
        self.next_seq = next_seq
        self.last_used = time.monotonic()


class IterCache:
    def __init__(self, idle_timeout_sec: float = 60.0, max_cursors: int = 8):
        self._idle_timeout = idle_timeout_sec
        self._max = max_cursors
        self._lock = threading.Lock()
        self._cursors: Dict[int, _Cursor] = {}

    def take(self, next_seq: int) -> Optional[Iterator[Tuple[int, bytes]]]:
        """Pop a cursor positioned at next_seq, if cached."""
        with self._lock:
            cur = self._cursors.pop(next_seq, None)
        if cur is not None:
            Stats.get().incr(M["iter_cache_hits"])
            return cur.it
        Stats.get().incr(M["iter_cache_misses"])
        return None

    def put(self, next_seq: int, it: Iterator[Tuple[int, bytes]]) -> None:
        with self._lock:
            self._cursors[next_seq] = _Cursor(it, next_seq)
            if len(self._cursors) > self._max:
                oldest = min(self._cursors, key=lambda k: self._cursors[k].last_used)
                del self._cursors[oldest]

    def evict_idle(self, now: Optional[float] = None) -> int:
        """Reference CachedIterCleaner behavior; called by the replicator's
        periodic maintenance task."""
        now = time.monotonic() if now is None else now
        with self._lock:
            stale = [
                k for k, c in self._cursors.items()
                if now - c.last_used > self._idle_timeout
            ]
            for k in stale:
                del self._cursors[k]
            return len(stale)

    def clear(self) -> None:
        with self._lock:
            self._cursors.clear()
