"""Multiplexed per-peer pull sessions (round 22 — fleet density).

Reference: the C++ reference amortizes per-shard replication traffic with
shared per-host connections (``ThriftClientPool`` — one connection pool
per upstream host, every shard's calls ride it). This module goes one
step further for the PULL plane, where the per-shard cost is not just
the connection but the whole long-poll stream: a follower node with 100
shards against one peer runs 100 parked long-polls, 100 reconnect
machines, and 100 frames per poll window even when idle.

One **mux session** per upstream peer replaces them: a single long-poll
request carries the cursor set for every shard this node pulls from that
peer, the server drains every shard with backlog into per-shard sections
of ONE response — parking ONCE across all member notifiers when
everything is idle — and the client demuxes each section through the
existing per-shard apply pipeline.

Per-shard semantics survive the mux unchanged, by construction: the
server side serves each section through the SAME
``ReplicatedDB.handle_replicate_request`` (with ``max_wait_ms=0``), so
fencing epochs, mode-1/2 acks, WAL_GAP typing, commit-point attestation
and the adaptive max_updates clamp are per-section; the client side runs
the SAME error taxonomy as ``_pull_loop`` per section, so an epoch bump
fences ONE shard, a WAL_GAP stalls ONE shard, and each shard backs off
on its own jittered RetryPolicy while the rest of the session keeps
streaming.

Killswitch: ``RSTPU_PULL_MUX`` (default off; ``ReplicationFlags.pull_mux``
overrides). Peers that predate ``replicate_mux`` answer NO_SUCH_METHOD —
the session falls back to per-shard pull loops automatically and the
peer is remembered as legacy.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from typing import Dict, List, Optional, Set, Tuple

from ..observability.context import current_span, wire_context
from ..rpc.errors import (RpcApplicationError, RpcConnectionError, RpcError,
                          RpcTransportConfigError)
from ..testing import failpoints as fp
from ..utils.retry_policy import RetryPolicy
from ..utils.stats import Stats
from .wire import REPLICATOR_METRICS as M
from .wire import ReplicaRole, ReplicateErrorCode

log = logging.getLogger(__name__)


def mux_enabled(flags=None) -> bool:
    """Resolve the mux killswitch: an explicit ``flags.pull_mux`` wins;
    otherwise the RSTPU_PULL_MUX env var (default OFF)."""
    if flags is not None and getattr(flags, "pull_mux", None) is not None:
        return bool(flags.pull_mux)
    val = os.environ.get("RSTPU_PULL_MUX", "")
    return val.lower() not in ("", "0", "false", "no")


# ----------------------------------------------------------------------
# server side
# ----------------------------------------------------------------------


class MuxServerState:
    """Per-process server state for ``replicate_mux``: the parked-session
    count (the fleet A/B's parked-longpolls gauge input) and a rotation
    cursor so the session budget starves no section under sustained
    backlog."""

    def __init__(self):
        self.parked = 0
        self._rot = 0

    async def serve(self, db_map, sections: Dict[str, dict],
                    max_wait_ms: Optional[int] = None,
                    budget: Optional[int] = None) -> dict:
        """Serve one mux request: per-section {error} or the exact dict
        ``handle_replicate_request`` returns. Parks AT MOST ONCE for the
        whole session (one reserved slot per member notifier, any wake
        ends the park) — never per section."""
        await fp.async_hit("repl.mux.serve")
        stats = Stats.get()
        stats.incr(M["mux_requests"])
        out: Dict[str, dict] = {}
        live: Dict[str, Tuple[object, dict]] = {}
        for name, sec in (sections or {}).items():
            db = db_map.get(name)
            if db is None or db.removed:
                out[name] = {
                    "error": ReplicateErrorCode.SOURCE_NOT_FOUND.value,
                    "message": name,
                }
                continue
            live[name] = (db, sec or {})
        # Pre-park pass, preserving the legacy per-shard serve ORDER
        # (fence check, then mode-2 ack posting, BEFORE any park): a
        # deposed section must post no acks and must not hold the
        # session's park hostage; a mode-2 leader's pipelined waiters
        # resolve from the puller's applied_seq even when this session
        # is about to park for the full window.
        for name in list(live):
            db, sec = live[name]
            epoch = sec.get("epoch")
            if db._reject_stale_epoch(epoch):
                db._stats.incr(M["stale_epoch_rejects"])
                out[name] = {
                    "error": ReplicateErrorCode.STALE_EPOCH.value,
                    "message": (
                        f"{name}: serving epoch {db.epoch} < puller epoch "
                        f"{epoch}" if epoch is not None else
                        f"{name}: fenced by epoch {db._fenced_by}"),
                }
                live.pop(name)
                continue
            role = sec.get("role", ReplicaRole.FOLLOWER.value)
            if role != ReplicaRole.OBSERVER.value and db.replication_mode == 2:
                applied = sec.get("applied_seq")
                db._acked.post(int(
                    sec.get("seq_no", 0) if applied is None else applied))
        flags = next(iter(live.values()))[0].flags if live else None
        if max_wait_ms is None:
            max_wait_ms = flags.server_long_poll_ms if flags else 0
        if budget is None:
            budget = flags.mux_session_budget if flags else 0

        def _backlog() -> bool:
            for db, sec in live.values():
                latest = db.wrapper.latest_sequence_number_relaxed()
                if latest > int(sec.get("seq_no", 0)):
                    return True
            return False

        if live and max_wait_ms > 0 and not _backlog():
            # ONE park for the whole session: reserve a slot on EVERY
            # member's notifier BEFORE the backlog re-check (the same
            # no-missed-wakeup contract as the per-shard park), then
            # wait for ANY slot; unfired slots are released after.
            slots = [(db, db._notifier.reserve())
                     for db, _sec in live.values()]
            try:
                if not _backlog():
                    root = current_span()
                    if root is not None:
                        root.annotate(tail_exempt="mux_longpoll_serve")
                    stats.incr(M["mux_parks"])
                    self.parked += 1
                    try:
                        await asyncio.wait(
                            [s for _db, s in slots],
                            timeout=max_wait_ms / 1000.0,
                            return_when=asyncio.FIRST_COMPLETED,
                        )
                    finally:
                        self.parked -= 1
            finally:
                for db, slot in slots:
                    db._notifier.cancel_reserved(slot)
        # Serve pass: each live section through the EXACT per-shard
        # serve path with max_wait_ms=0 (no second park) — per-section
        # epoch/ack/WAL/commit-point semantics by construction. The
        # session budget bounds what one response pins in memory; the
        # rotation makes budget starvation impossible under sustained
        # backlog (a zero-grant section still reports latest_seq, so
        # its puller sizes the next round adaptively).
        self._rot += 1
        names = list(live)
        start = self._rot % len(names) if names else 0
        remaining = max(0, int(budget))
        for name in names[start:] + names[:start]:
            db, sec = live[name]
            if db.removed:
                out[name] = {
                    "error": ReplicateErrorCode.SOURCE_REMOVED.value,
                    "message": name,
                }
                continue
            want = int(sec.get("max_updates")
                       or db.flags.max_updates_per_response)
            grant = min(want, remaining)
            if grant <= 0:
                # budget exhausted this round: report position only (the
                # mode-2 ack already posted pre-park); the rotation puts
                # this section first next round
                out[name] = {
                    "updates": [],
                    "latest_seq":
                        db.wrapper.latest_sequence_number_relaxed(),
                    "source_role": db.role.value,
                    "replication_mode": db.replication_mode,
                    "epoch": db.epoch,
                    **db._commit_point_fields(),
                }
                continue
            try:
                res = await db.handle_replicate_request(
                    seq_no=int(sec.get("seq_no", 0)),
                    max_wait_ms=0,
                    max_updates=grant,
                    role=sec.get("role", ReplicaRole.FOLLOWER.value),
                    applied_seq=sec.get("applied_seq"),
                    epoch=sec.get("epoch"),
                )
            except RpcApplicationError as e:
                out[name] = {"error": e.code, "message": str(e)}
                continue
            remaining -= len(res.get("updates") or ())
            out[name] = res
        stats.incr(M["mux_sections"], len(sections or ()))
        return {"sections": out}


# ----------------------------------------------------------------------
# client side
# ----------------------------------------------------------------------


class PullMuxManager:
    """Routes FOLLOWER/OBSERVER shards into one PullMuxSession per
    upstream peer. Lives on the Replicator; ``register``/``deregister``
    are thread-safe (they hop to the IO loop), everything else runs on
    the loop thread."""

    def __init__(self, loop: asyncio.AbstractEventLoop, executor, pool,
                 flags):
        self._loop = loop
        self._executor = executor
        self._pool = pool
        self.flags = flags
        self._sessions: Dict[Tuple[str, int], PullMuxSession] = {}
        self._legacy: Set[Tuple[str, int]] = set()
        self._stopped = False

    def register(self, rdb) -> None:
        self._loop.call_soon_threadsafe(self._route, rdb)

    def deregister(self, rdb) -> None:
        self._loop.call_soon_threadsafe(self._drop, rdb)

    def stop(self) -> None:
        def _stop():
            self._stopped = True
            for sess in list(self._sessions.values()):
                sess.cancel()
            self._sessions.clear()

        self._loop.call_soon_threadsafe(_stop)

    # -- loop thread ---------------------------------------------------

    def _route(self, rdb) -> None:
        if self._stopped or rdb.removed:
            return
        addr = tuple(rdb.upstream_addr or ())
        if len(addr) != 2:
            return
        if addr in self._legacy:
            # peer known to predate replicate_mux: classic per-shard loop
            rdb.start_solo_pull()
            return
        sess = self._sessions.get(addr)
        if sess is None or sess.closed:
            sess = self._sessions[addr] = PullMuxSession(self, addr)
            sess.start()
        sess.add(rdb)

    def _drop(self, rdb) -> None:
        for sess in self._sessions.values():
            sess.discard(rdb)

    def mark_legacy(self, addr) -> None:
        self._legacy.add(tuple(addr))

    def _session_closed(self, sess: "PullMuxSession") -> None:
        if self._sessions.get(sess.addr) is sess:
            self._sessions.pop(sess.addr, None)


class PullMuxSession:
    """One multiplexed pull stream against one upstream peer. The round
    loop mirrors ``ReplicatedDB._pull_loop`` lifted to a member SET:
    whole-call failures are peer-level (one session backoff, per-member
    error accounting), per-SECTION failures run the exact per-shard
    taxonomy and back off only that shard."""

    def __init__(self, mgr: PullMuxManager, addr: Tuple[str, int]):
        self.mgr = mgr
        self.addr = addr
        self.members: Dict[str, object] = {}
        self.closed = False
        self._backoff_until: Dict[str, float] = {}
        self._task: Optional[asyncio.Task] = None
        # membership-change kick: joining shards must not wait out a
        # parked long-poll they are not part of
        self._wake = asyncio.Event()
        f = mgr.flags
        self._retry = RetryPolicy(
            max_attempts=1 << 30,
            base_delay=f.pull_error_delay_min_ms / 1000.0,
            max_delay=f.pull_error_delay_max_ms / 1000.0,
            floor=f.pull_error_delay_min_ms / 1000.0,
        )
        self._retry_attempt = 0
        _seed = os.environ.get("RSTPU_PULL_RETRY_SEED")
        self._rng = random.Random(int(_seed) if _seed else None)
        self._ever_pulled = False

    # -- loop thread ---------------------------------------------------

    def start(self) -> None:
        self._task = self.mgr._loop.create_task(self._run())

    def cancel(self) -> None:
        self.closed = True
        if self._task is not None:
            self._task.cancel()

    def add(self, rdb) -> None:
        self.members[rdb.name] = rdb
        self._backoff_until.pop(rdb.name, None)
        self._wake.set()

    def discard(self, rdb) -> None:
        if self.members.get(rdb.name) is rdb:
            self.members.pop(rdb.name, None)
            self._backoff_until.pop(rdb.name, None)
            self._wake.set()

    def _refresh_members(self) -> List[object]:
        """Drop removed members, re-route members whose upstream moved
        (an upstream reset repoints ONE shard — it changes session, not
        semantics), return the live set."""
        out = []
        for name, rdb in list(self.members.items()):
            if rdb.removed:
                self.members.pop(name)
                self._backoff_until.pop(name, None)
                continue
            if tuple(rdb.upstream_addr or ()) != self.addr:
                self.members.pop(name)
                self._backoff_until.pop(name, None)
                self.mgr._route(rdb)
                continue
            out.append(rdb)
        return out

    async def _run(self) -> None:
        try:
            # coalesce the registration burst (add_db storms register one
            # shard per loop tick) so the first round carries the node's
            # whole cursor set instead of one
            await asyncio.sleep(0.02)
            while True:
                self._wake.clear()
                members = self._refresh_members()
                if not members:
                    return
                now = time.monotonic()
                eligible = [
                    r for r in members
                    if self._backoff_until.get(r.name, 0.0) <= now
                ]
                if not eligible:
                    deadline = min(self._backoff_until[r.name]
                                   for r in members)
                    try:
                        await asyncio.wait_for(
                            self._wake.wait(),
                            max(0.01, deadline - now))
                    except asyncio.TimeoutError:
                        pass
                    continue
                try:
                    await self._pull_round(eligible)
                except asyncio.CancelledError:
                    # same contract as _pull_loop cancellation: never
                    # block teardown on executor work — forget pipelines
                    for r in eligible:
                        r._apply_future = None
                        r._apply_target = None
                        r._applied_through = None
                    raise
                except RpcApplicationError as e:
                    if e.code == "NO_SUCH_METHOD":
                        self._fallback_legacy()
                        return
                    await self._session_error(eligible, e, conn=False)
                except RpcTransportConfigError as e:
                    log.error("mux[%s:%s]: transport misconfig: %s",
                              self.addr[0], self.addr[1], e)
                    await self._session_error(eligible, e, conn=False,
                                              resolver=False)
                except (RpcError, Exception) as e:
                    conn = isinstance(
                        e, (RpcConnectionError, ConnectionError, OSError))
                    log.warning("mux[%s:%s]: pull error: %r",
                                self.addr[0], self.addr[1], e)
                    await self._session_error(eligible, e, conn=conn)
        finally:
            self.closed = True
            self.mgr._session_closed(self)

    async def _pull_round(self, eligible: List[object]) -> None:
        """One mux round: ONE RPC carrying every eligible shard's cursor,
        racing the members' in-flight applies (mode-2 ack pushes fire at
        apply time, exactly as the solo loop's racing apply does), then
        per-section demux."""
        mgr = self.mgr
        f = mgr.flags
        host, port = self.addr
        # the solo loop's pull seam: existing chaos decks inject faults
        # at repl.pull — mux rounds must feel them identically
        await fp.async_hit("repl.pull")
        client = await mgr._pool.get_client(host, port)
        for r in eligible:
            if r._applied_through is None and r._apply_future is None:
                # cold pipeline: one storage-lock read seeds the cursor
                r._applied_through = await mgr._loop.run_in_executor(
                    mgr._executor, r.wrapper.latest_sequence_number)
        sections = {}
        for r in eligible:
            from_seq = (r._apply_target if r._apply_target is not None
                        else r._applied_through)
            sections[r.name] = {
                "seq_no": from_seq,
                "applied_seq": r._applied_through,
                "max_updates": r._cur_max_updates,
                "role": r.role.value,
                "epoch": r.epoch,
            }
        stats = Stats.get()
        stats.incr(M["mux_pulls"])
        stats.incr(M["pull_requests"])
        rpc_task = asyncio.ensure_future(client.call(
            "replicate_mux",
            {
                "sections": sections,
                "max_wait_ms": f.server_long_poll_ms,
                "budget": f.mux_session_budget,
            },
            timeout=(f.server_long_poll_ms + f.pull_rpc_margin_ms) / 1000.0,
            tail_exempt=f.server_long_poll_ms > 0,
        ))
        result = await self._race(client, rpc_task, eligible)
        if result is None:
            return  # round abandoned for a membership change
        self._ever_pulled = True
        self._retry_attempt = 0
        resp = (result or {}).get("sections") or {}
        for r in eligible:
            sec = resp.get(r.name)
            if sec is None or r.removed:
                continue
            if "error" in sec:
                await self._section_error(r, sec)
            else:
                await self._section_ok(r, sec, client)

    async def _race(self, client, rpc_task, eligible):
        """Await the mux RPC while racing (a) every member's in-flight
        apply — completions roll cursors and push mode-2 acks at apply
        time — and (b) the membership-change kick, which abandons the
        round (cancels the RPC; the id-keyed client discards the orphan
        response) so a joining shard never waits out a park it is not
        part of. Returns the RPC result, or None when abandoned."""
        try:
            while not rpc_task.done():
                pend = {}
                for r in eligible:
                    fut = r._apply_future
                    if fut is not None and not fut.done():
                        pend[fut] = r
                done_applies = [r for r in eligible
                                if r._apply_future is not None
                                and r._apply_future.done()]
                for r in done_applies:
                    try:
                        await r._drain_pending_apply(reraise=True)
                    except Exception as e:
                        r._stats.incr(M["pull_errors"])
                        log.warning("%s: pipelined apply failed: %r",
                                    r.name, e)
                        self._shard_backoff(r)
                        continue
                    if r._upstream_mode == 2 and r._applied_through:
                        await r._send_applied_ack(client)
                if done_applies:
                    continue
                waits = {rpc_task, *pend.keys()}
                wake_task = None
                if not self._wake.is_set():
                    wake_task = asyncio.ensure_future(self._wake.wait())
                    waits.add(wake_task)
                elif not pend:
                    # membership changed and nothing left to race
                    rpc_task.cancel()
                    try:
                        await rpc_task
                    except BaseException:
                        pass
                    return None
                try:
                    await asyncio.wait(
                        waits, return_when=asyncio.FIRST_COMPLETED)
                finally:
                    if wake_task is not None:
                        wake_task.cancel()
                if not rpc_task.done() and self._wake.is_set() and not any(
                        f.done() for f in pend):
                    rpc_task.cancel()
                    try:
                        await rpc_task
                    except BaseException:
                        pass
                    return None
            return await rpc_task
        except asyncio.CancelledError:
            rpc_task.cancel()
            raise

    async def _section_ok(self, r, sec: dict, client) -> None:
        """Demux one successful section through the exact solo-pull
        response semantics."""
        source_role = sec.get("source_role")
        resp_epoch = sec.get("epoch")
        if resp_epoch is not None:
            if int(resp_epoch) > r.epoch:
                r.adopt_epoch(int(resp_epoch))
            elif int(resp_epoch) < r.epoch:
                # deposed upstream FOR THIS SHARD: apply nothing, repoint
                # — the rest of the session is untouched
                r._stats.incr(M["stale_epoch_rejects"])
                await self._section_error(r, {
                    "error": ReplicateErrorCode.STALE_EPOCH.value,
                    "message": f"{r.name}: upstream epoch {resp_epoch} "
                               f"< ours {r.epoch}",
                })
                return
        if sec.get("replication_mode") is not None:
            r._upstream_mode = int(sec["replication_mode"])
        r._adopt_commit_point(sec)
        r._note_divergence(sec, source_role)
        updates = sec.get("updates") or []
        r._adapt_max_updates(sec, updates)
        try:
            if not updates:
                await r._drain_pending_apply(reraise=True)
                r._mark_pull_ok()
                self._backoff_until.pop(r.name, None)
                if (r.role is ReplicaRole.FOLLOWER
                        and source_role not in (None,
                                                ReplicaRole.LEADER.value)):
                    r._empty_pulls += 1
                    if r._empty_pulls >= r.flags.empty_pulls_before_reset:
                        r._empty_pulls = 0
                        await r._maybe_reset_upstream(force_sample=False)
                else:
                    r._empty_pulls = 0
                return
            await fp.async_hit("repl.mux.apply")
            # in-order apply: the previous response must land (and its
            # failure surface) before this one reaches the executor
            await r._drain_pending_apply(reraise=True)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            r._stats.incr(M["pull_errors"])
            log.warning("%s: mux apply pipeline error: %r", r.name, e)
            self._shard_backoff(r)
            return
        # A FAILED apply drained inside _race resets the pipeline to
        # storage truth (applied_through=None) — a response built for
        # the abandoned cursor must be dropped here, exactly as the solo
        # loop discards its in-flight response when the racing apply
        # errors. Feeding it on would advance the target past the
        # failure and cascade discontinuity errors round after round.
        cur = (r._apply_target if r._apply_target is not None
               else r._applied_through)
        if cur is None or int(updates[0]["seq_no"]) != cur + 1:
            log.debug("%s: dropping stale mux section (cursor reset)",
                      r.name)
            return
        pull_ctx = wire_context()
        last = updates[-1]
        r._apply_target = int(last["seq_no"]) + int(
            last.get("count") or 1) - 1
        r._apply_future = self.mgr._loop.run_in_executor(
            self.mgr._executor, r._apply_updates, updates, pull_ctx)
        r._mark_pull_ok()
        r._empty_pulls = 0
        self._backoff_until.pop(r.name, None)

    async def _section_error(self, r, sec: dict) -> None:
        """Per-section error: the RpcApplicationError branch of
        ``_pull_loop``, scoped to ONE shard — its backoff, its stall
        flags, its resolver escalation; the session streams on."""
        code = sec.get("error")
        r._stats.incr(M["pull_errors"])
        r._conn_errors = 0
        await r._drain_pending_apply()
        if code in (ReplicateErrorCode.SOURCE_NOT_FOUND.value,
                    ReplicateErrorCode.SOURCE_REMOVED.value):
            await r._maybe_reset_upstream(force_sample=False)
        elif code == ReplicateErrorCode.WAL_GAP.value:
            if not r.pull_stalled_wal_gap:
                r.pull_stalled_wal_gap = True
                r._stats.incr(M["wal_gap_stalls"])
                log.warning(
                    "%s: WAL-tail catch-up STALLED (%s) — snapshot "
                    "rebuild required", r.name, sec.get("message"))
            await r._maybe_reset_upstream(force_sample=True)
        elif code == ReplicateErrorCode.STALE_EPOCH.value:
            await r._maybe_reset_upstream(force_sample=True)
        self._shard_backoff(r)

    def _shard_backoff(self, r) -> None:
        self._backoff_until[r.name] = time.monotonic() + r._next_pull_delay()

    async def _session_error(self, members, e, conn: bool,
                             resolver: bool = True) -> None:
        """Whole-call failure (peer-level): per-member error accounting
        mirroring _pull_loop's connection/generic branches, then ONE
        session backoff — a dead peer costs one reconnect machine, not
        one per shard."""
        for r in members:
            if r.removed:
                continue
            r._stats.incr(M["pull_errors"])
            await r._drain_pending_apply()
            if not resolver:
                r._conn_errors = 0
                continue
            forced = False
            if conn:
                r._conn_errors += 1
                forced = (r._conn_errors
                          >= r.flags.conn_errors_before_forced_reset)
                if forced:
                    r._conn_errors = 0
            else:
                r._conn_errors = 0
            await r._maybe_reset_upstream(force_sample=forced)
        await self._session_delay()

    async def _session_delay(self) -> None:
        """Session-level backoff with the same fast-first-connect tier as
        the per-shard path (one fleet cold start = one fast reconnect
        per PEER, not per shard); interruptible by membership changes."""
        f = self.mgr.flags
        if (not self._ever_pulled
                and self._retry_attempt < f.pull_fast_first_attempts):
            delay = self._rng.uniform(f.pull_fast_min_ms / 1000.0,
                                      f.pull_fast_max_ms / 1000.0)
        else:
            delay = self._retry.delay(self._retry_attempt, self._rng)
        self._retry_attempt += 1
        Stats.get().add_metric("replicator.pull_backoff_ms", delay * 1000.0)
        try:
            await asyncio.wait_for(self._wake.wait(), delay)
        except asyncio.TimeoutError:
            pass

    def _fallback_legacy(self) -> None:
        """The peer answered NO_SUCH_METHOD for replicate_mux: remember
        it as legacy and hand every member its own classic pull loop."""
        Stats.get().incr(M["mux_fallbacks"])
        log.info("mux[%s:%s]: peer predates replicate_mux — falling back "
                 "to per-shard pull loops (%d shards)",
                 self.addr[0], self.addr[1], len(self.members))
        self.mgr.mark_legacy(self.addr)
        for name, r in list(self.members.items()):
            self.members.pop(name)
            if not r.removed:
                r.start_solo_pull()
