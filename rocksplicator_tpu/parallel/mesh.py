"""Device-mesh sharding for batched compaction.

Mesh axes:
- ``shard``: independent shards (DP-analog) — no communication.
- ``block``: blockwise split of one shard's entries (SP-analog) — each
  device merges its block locally, then an ``all_gather`` over the block
  axis assembles the shard's blocks for the final merge, and a ``psum``
  over the shard axis produces global job stats. Collectives ride ICI on
  real hardware; the same program runs on a virtual CPU mesh in tests.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


# Per-device working-set target for one block of a shard's compaction job.
# v5e VMEM is ~128 MiB/core; the kernel's sort working set is a small
# multiple of the block's lane bytes, so budget well below that.
BLOCK_BYTES_TARGET = 32 << 20


def derive_block_axis(num_devices: int,
                      shard_bytes: Optional[int] = None,
                      block_bytes_target: int = BLOCK_BYTES_TARGET) -> int:
    """Block-axis size (SP-analog) from device count and job size.

    Picks the smallest power-of-2 divisor of ``num_devices`` whose blocks
    fit ``block_bytes_target`` (more block-parallelism only when a
    shard's job exceeds one device's budget — otherwise devices are
    better spent on the no-communication shard axis). Shards larger than
    block capacity compose with tpu/chunked.py's hierarchical merge.
    Without a ``shard_bytes`` hint: 2 when the device count is even
    (exercises both collectives), else 1."""
    if num_devices <= 1:
        return 1
    if shard_bytes is None:
        return 2 if num_devices % 2 == 0 else 1
    block = 1
    while (
        block < num_devices
        and num_devices % (block * 2) == 0
        and shard_bytes / block > block_bytes_target
    ):
        block *= 2
    return block


def make_mesh(num_devices: Optional[int] = None,
              axis_names: Tuple[str, str] = ("shard", "block"),
              block: Optional[int] = None,
              shard_bytes: Optional[int] = None):
    """2D mesh over the first ``num_devices`` devices. The block axis is
    ``block`` if given, else derived from the job size (see
    derive_block_axis)."""
    import jax

    devices = jax.devices()
    n = num_devices or len(devices)
    devices = devices[:n]
    if block is None:
        block = derive_block_axis(n, shard_bytes)
    if n % block != 0:
        raise ValueError(f"block axis {block} does not divide {n} devices")
    shard = n // block
    arr = np.array(devices).reshape(shard, block)
    return jax.sharding.Mesh(arr, axis_names)


def sharded_compaction_step(mesh, model=None):
    """Returns a jitted step over (S, B, N, ...) arrays: S sharded on the
    ``shard`` axis, B on the ``block`` axis.

    Per (shard, block) tile: local merge-resolve. Then all_gather along
    ``block`` to assemble the shard's blocks, a second merge-resolve over
    the concatenation (entries per block stay sorted, so this is the
    SP merge step), bloom build, and a psum'd global stats reduction.
    Output: final merged arrays per shard (replicated over ``block``),
    bloom words, per-shard counts, and the global count.

    **Required invariant:** a shard's blocks must partition its entries by
    sequence range — every seq in block b strictly newer than every seq in
    block b-1 (the natural layout: blocks are WAL ranges / LSM runs).
    Block-local resolution folds operands into the block's newest base;
    that composes across blocks ONLY under this ordering (a newer block's
    partial fold must not swallow operands that an older block's newer-seq
    base should shadow). ``make_sharded_inputs`` generates compliant data.
    """
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map

        replication_check = {"check_vma": False}
    except ImportError:  # pre-0.5 jax: experimental namespace + old kwarg
        from jax.experimental.shard_map import shard_map

        replication_check = {"check_rep": False}
    from jax.sharding import PartitionSpec as P

    from ..models.compaction_model import CompactionModel
    from ..ops.bloom_tpu import bloom_build_tpu
    from ..ops.compaction_kernel import merge_resolve_kernel

    model = model or CompactionModel()
    merge_kind = model.merge_kind
    sort_backend = model.sort_backend

    def local_step(kwbe, klen, shi, slo, vt, vw, vl, valid):
        # local shapes: (s, 1, N, ...) — one block column per device
        s, b, n = klen.shape
        squeeze = lambda a: a.reshape((s * b, n) + a.shape[3:])

        def run(args, drop):
            return merge_resolve_kernel(
                *args, merge_kind=merge_kind, drop_tombstones=drop,
                sort_backend=sort_backend,
            )

        # 1) block-local merge (keep tombstones: blocks are partial views)
        local = dict(jax.vmap(lambda *a: run(a, False))(
            squeeze(kwbe), squeeze(klen), squeeze(shi),
            squeeze(slo), squeeze(vt), squeeze(vw), squeeze(vl),
            squeeze(valid),
        ))
        local_fallback = jnp.any(local.pop("needs_cpu_fallback"))
        # LE lanes are byteswap-derived wherever needed — don't pay the
        # all_gather for them
        local.pop("key_words_le")
        # 2) assemble the shard's blocks: all_gather over the block axis
        gathered = {
            k: jax.lax.all_gather(v, "block", axis=1)
            for k, v in local.items()
        }
        nb = gathered["key_len"].shape[1]
        flat = {
            k: v.reshape((s, nb * n) + v.shape[3:])
            for k, v in gathered.items()
            if k != "count"
        }
        # rows beyond each block's count are zero-filled by the scatter —
        # mark them invalid for the final merge
        per_block_counts = gathered["count"]  # (s, nb)
        row_block = jnp.arange(nb * n) // n
        row_in_block = jnp.arange(nb * n) % n
        valid2 = row_in_block[None, :] < per_block_counts[:, row_block]
        # 3) final merge per shard + bloom + stats
        final = dict(jax.vmap(
            lambda *a: merge_resolve_kernel(
                *a, merge_kind=merge_kind,
                drop_tombstones=model.drop_tombstones,
                sort_backend=sort_backend,
            )
        )(
            flat["key_words_be"], flat["key_len"],
            flat["seq_hi"], flat["seq_lo"], flat["vtype"],
            flat["val_words"], flat["val_len"], valid2,
        ))
        fallback = local_fallback | jnp.any(final.pop("needs_cpu_fallback"))
        out_valid = (
            jnp.arange(nb * n)[None, :] < final["count"][:, None]
        )
        bloom = jax.vmap(
            lambda kw, kl, v: bloom_build_tpu(
                kw, kl, v, num_words=model.num_bloom_words
            )
        )(final["key_words_le"], final["key_len"], out_valid)
        if model.emit_planar:
            # production sink format on-device, per shard (the same
            # encode model.forward emits single-chip): plane words +
            # word-domain checksums for every planar block
            from ..ops.block_encode import (encode_planar_words_tpu,
                                            planar_checksums_tpu)

            planar = jax.vmap(
                lambda kwb, shi, slo, vt, vw: encode_planar_words_tpu(
                    kwb, shi, slo, vt, vw,
                    klen=model.row_klen, vlen=model.row_vlen,
                    seq32=model.seq32,
                    block_entries=model.planar_block_entries,
                )
            )(final["key_words_be"], final["seq_hi"], final["seq_lo"],
              final["vtype"], final["val_words"])
            final["planar_words"] = planar
            final["planar_chk"] = jax.vmap(planar_checksums_tpu)(planar)
        global_count = jax.lax.psum(final["count"].sum(), "shard")
        # any device needing CPU fallback poisons the whole job. Reduce over
        # BOTH axes: local_fallback differs per block column, and out_spec
        # P(None, None) materializes one column's value.
        global_fallback = jax.lax.pmax(
            fallback.astype(jnp.int32), ("shard", "block")
        )
        # re-insert the block axis (replicated) for out_specs
        expand = lambda a: a[:, None]
        return (
            {k: expand(v) for k, v in final.items() if k != "count"},
            expand(bloom),
            expand(final["count"]),
            global_count[None, None],
            global_fallback[None, None],
        )

    in_spec = P("shard", "block")
    final_keys = [
        "key_words_be", "key_words_le", "key_len", "seq_hi",
        "seq_lo", "vtype", "val_words", "val_len",
    ]
    if model.emit_planar:
        final_keys += ["planar_words", "planar_chk"]
    step = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(in_spec,) * 8,
        out_specs=(
            {k: P("shard", None) for k in final_keys},
            P("shard", None),
            P("shard", None),
            P(None, None),
            P(None, None),
        ),
        **replication_check,
    )
    return jax.jit(step)


def make_sharded_inputs(mesh, shards_per_device: int = 1,
                        entries_per_block: int = 256, model=None,
                        seed: int = 0) -> Dict[str, np.ndarray]:
    """Synthetic (S, B, N, ...) inputs laid out for the mesh."""
    from ..models.compaction_model import synth_counter_batch

    shard_n = mesh.shape["shard"] * shards_per_device
    block_n = mesh.shape["block"]
    n = entries_per_block
    arrays = None
    for s in range(shard_n):
        for b in range(block_n):
            batch = synth_counter_batch(
                n, seed=seed + s * 131 + b,
                start_seq=1 + b * n,
            )
            if arrays is None:
                arrays = {
                    k: np.zeros((shard_n, block_n) + v.shape, v.dtype)
                    for k, v in batch.items()
                }
            for k, v in batch.items():
                arrays[k][s, b] = v
    return arrays


def shard_inputs_on_mesh(mesh, arrays: Dict[str, np.ndarray]):
    """device_put with PartitionSpec("shard", "block") on the leading dims."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("shard", "block"))
    return {k: jax.device_put(v, sharding) for k, v in arrays.items()}
