"""Mesh-sharded execution of the compaction pipeline.

The scaling dimensions (SURVEY §2.6 mapping): the "shard" mesh axis is the
DP-analog (independent shards compact in parallel) and the "block" axis is
the SP-analog (one shard's entry stream split blockwise across devices,
merged with collectives).
"""

from .mesh import make_mesh, sharded_compaction_step

__all__ = ["make_mesh", "sharded_compaction_step"]
