"""CompactionModel — the framework's flagship jittable computation.

This framework's "model" is not a neural net: the forward step is the
fused merge-resolve + bloom-build pipeline over a fixed-capacity batch of
KV entries (one shard's compaction job). It is pure, static-shaped, and
jit/vmap/shard_map-composable — the unit the driver compile-checks and the
bench times.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

from ..ops.bloom_tpu import bloom_build_tpu
from ..ops.compaction_kernel import MergeKind, merge_resolve_kernel
from ..ops.kv_format import KEY_WORDS
from ..storage.bloom import num_words_for

_PUT, _DELETE, _MERGE = 1, 2, 3


@dataclass
class CompactionModel:
    """Configuration of the flagship pipeline."""

    capacity: int = 1 << 16        # entries per shard batch
    val_words: int = 2             # 8-byte counter values
    bits_per_key: int = 10
    merge_kind: MergeKind = MergeKind.UINT64_ADD
    drop_tombstones: bool = True
    # caller-verified fast-path promises (see ops/compaction_kernel):
    # synthetic/counter workloads have one key width and 32-bit seqs;
    # key_words bounds the u32 lanes that actually carry key bytes
    uniform_klen: bool = False
    seq32: bool = False
    key_words: int = KEY_WORDS
    # (row_klen, row_vlen) enables ON-DEVICE block encoding: forward also
    # emits the SST entry-row byte matrix (ops/block_encode.py), making
    # the flagship pipeline merge→bloom→bytes with no host byte-work
    emit_rows: bool = False
    row_klen: int = 16
    row_vlen: int = 8
    # PLANAR alternative (the production sink format): emit block plane
    # words + word-domain checksums instead of interleaved rows — on this
    # hardware the row matrix is the most expensive layout op in the
    # pipeline while planar is concatenation (PERF.md)
    emit_planar: bool = False
    planar_block_entries: int = 1024
    # "lax" = XLA's generic sort; "pallas" = the VMEM-resident bitonic
    # kernel (ops/pallas_sort.py) that holds every operand lane on-chip
    # across all compare-exchange stages — the attack on the sort's HBM
    # traffic (PERF.md round-2 lever); "pallas_fused" = the whole
    # merge-resolve (sort + scans + compaction) in one VMEM residency
    # (ops/pallas_resolve.py). Opt-in until chip-measured.
    sort_backend: str = "lax"

    @property
    def num_bloom_words(self) -> int:
        return num_words_for(self.capacity, self.bits_per_key)

    def forward(
        self,
        key_words_be, key_len,
        seq_hi, seq_lo, vtype, val_words, val_len, valid,
    ) -> Dict:
        """One shard's compaction: merged entries + bloom + count.
        (LE key lanes are byteswap-derived on device — not an input.)"""
        import jax
        import jax.numpy as jnp

        out = merge_resolve_kernel(
            key_words_be, key_len, seq_hi, seq_lo,
            vtype, val_words, val_len, valid,
            merge_kind=self.merge_kind,
            drop_tombstones=self.drop_tombstones,
            uniform_klen=self.uniform_klen, seq32=self.seq32,
            key_words=self.key_words,
            sort_backend=self.sort_backend,
        )
        out_valid = jax.lax.iota(jnp.int32, key_len.shape[0]) < out["count"]
        out["bloom"] = bloom_build_tpu(
            out["key_words_le"], out["key_len"], out_valid,
            num_words=self.num_bloom_words,
        )
        if self.emit_rows:
            from ..ops.block_encode import encode_rows_tpu

            out["rows"] = encode_rows_tpu(
                out["key_words_be"], out["seq_hi"], out["seq_lo"],
                out["vtype"], out["val_words"],
                klen=self.row_klen, vlen=self.row_vlen,
            )
        if self.emit_planar:
            from ..ops.block_encode import (encode_planar_words_tpu,
                                            planar_checksums_tpu)

            words = encode_planar_words_tpu(
                out["key_words_be"], out["seq_hi"], out["seq_lo"],
                out["vtype"], out["val_words"],
                klen=self.row_klen, vlen=self.row_vlen, seq32=self.seq32,
                block_entries=self.planar_block_entries,
            )
            out["planar_words"] = words
            out["planar_chk"] = planar_checksums_tpu(words)
        return out

    def example_args(self, seed: int = 0) -> Tuple:
        """Numpy example inputs matching forward()'s signature."""
        b = synth_counter_batch(self.capacity, seed=seed,
                                val_words=self.val_words)
        return (
            b["key_words_be"], b["key_len"],
            b["seq_hi"], b["seq_lo"], b["vtype"], b["val_words"],
            b["val_len"], b["valid"],
        )


def synth_counter_batch_jax(
    n: int,
    key_space: int | None = None,
    seed: int = 0,
    merge_frac: float = 0.6,
    delete_frac: float = 0.05,
    val_words: int = 2,
    key_bytes: int = 16,
    start_seq: int = 1,
):
    """Device-side synth_counter_batch: same shapes/distribution, built
    with the JAX PRNG so benchmark inputs can be GENERATED ON THE DEVICE
    instead of shipped over host↔device (the tunnel moves ~30 MB/s; a
    32-shard batch is 222 MB of lanes). Exact bits differ from the numpy
    generator (threefry vs PCG64) — callers compare throughput across
    distribution-matched, not bit-identical, data."""
    import jax
    import jax.numpy as jnp

    key_space = key_space or max(1, n // 8)
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    key_ids = jax.random.randint(
        k1, (n,), 0, key_space, dtype=jnp.uint32)
    # numpy layout: first 8 key bytes are the big-endian u64 id, so BE
    # word0 is the (zero) high half and word1 the id; remaining lanes 0
    zeros = jnp.zeros((n,), jnp.uint32)
    kw_be = jnp.stack(
        [zeros, key_ids, zeros, zeros, zeros, zeros], axis=1)
    from ..ops.compaction_kernel import bswap32

    kw_le = bswap32(kw_be)
    r = jax.random.uniform(k2, (n,))
    vtype = jnp.where(
        r < merge_frac, jnp.uint32(_MERGE),
        jnp.where(r < merge_frac + delete_frac, jnp.uint32(_DELETE),
                  jnp.uint32(_PUT)),
    )
    vals = jax.random.randint(k3, (n,), 0, 1000, dtype=jnp.uint32)
    vals = jnp.where(vtype == _DELETE, jnp.uint32(0), vals)
    vw = jnp.zeros((n, val_words), jnp.uint32).at[:, 0].set(vals)
    seqs = start_seq + jnp.arange(n, dtype=jnp.uint32)
    return {
        "key_words_be": kw_be,
        "key_words_le": kw_le,
        "key_len": jnp.full((n,), jnp.uint32(key_bytes)),
        "seq_hi": jnp.zeros((n,), jnp.uint32),
        "seq_lo": seqs,
        "vtype": vtype,
        "val_words": vw,
        "val_len": jnp.where(vtype == _DELETE, jnp.uint32(0),
                             jnp.uint32(8)),
        "valid": jnp.ones((n,), bool),
    }


def synth_counter_batch(
    n: int,
    key_space: int | None = None,
    seed: int = 0,
    merge_frac: float = 0.6,
    delete_frac: float = 0.05,
    val_words: int = 2,
    key_bytes: int = 16,
    start_seq: int = 1,
) -> Dict[str, np.ndarray]:
    """Vectorized synthetic counter-workload batch (the bench generator).

    Keys: ``key_bytes``-long, first 8 bytes = big-endian key id drawn from
    ``key_space`` distinct ids (power-law-ish duplicates exercise the merge
    fold), remaining bytes zero. Ops: MERGE bumps, PUTs, a few DELETEs.
    """
    rng = np.random.default_rng(seed)
    key_space = key_space or max(1, n // 8)
    key_ids = rng.integers(0, key_space, size=n, dtype=np.uint64)
    key_buf = np.zeros((n, 24), dtype=np.uint8)
    key_buf[:, :8] = key_ids.astype(">u8").view(np.uint8).reshape(n, 8)
    r = rng.random(n)
    vtype = np.where(
        r < merge_frac, _MERGE, np.where(r < merge_frac + delete_frac, _DELETE, _PUT)
    ).astype(np.uint32)
    vals = rng.integers(0, 1000, size=n, dtype=np.uint64)
    vals = np.where(vtype == _DELETE, 0, vals)
    val_buf = np.zeros((n, val_words * 4), dtype=np.uint8)
    val_buf[:, :8] = vals.astype("<u8").view(np.uint8).reshape(n, 8)
    seqs = np.arange(start_seq, start_seq + n, dtype=np.uint64)
    return {
        "key_words_be": key_buf.view(">u4").astype(np.uint32).reshape(n, 6),
        "key_words_le": key_buf.view("<u4").reshape(n, 6).copy(),
        "key_len": np.full(n, key_bytes, dtype=np.uint32),
        "seq_hi": (seqs >> np.uint64(32)).astype(np.uint32),
        "seq_lo": (seqs & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        "vtype": vtype,
        "val_words": val_buf.view("<u4").reshape(n, val_words).copy(),
        "val_len": np.where(vtype == _DELETE, 0, 8).astype(np.uint32),
        "valid": np.ones(n, dtype=bool),
    }
