"""Flagship 'model': the compaction pipeline as a jittable forward step."""

from .compaction_model import CompactionModel, synth_counter_batch

__all__ = ["CompactionModel", "synth_counter_batch"]
