"""Register-blocked bloom filter with a TPU-vectorizable hash.

Reference: RocksDB bloom filters at 10 bits/key (performance.cpp bloom
config). Design constraint here is the BASELINE.json north star: bloom
bitmap construction runs as a TPU kernel over fixed-width lanes, so the
hash is defined over a **fixed 24-byte zero-padded key prefix plus the key
length**, FNV-1a folded in u32 words — computable with identical results in
numpy/JAX u32 lanes and in this pure-Python reference implementation.
(Long keys sharing a 24-byte prefix merely share bloom bits — more false
positives, never false negatives.)

Layout: ``num_words`` 32-bit words; each key sets K bits within ONE word
(register-blocked / Impala-style), chosen by a second hash — one word of
memory traffic per probe on CPU, one lane op on TPU.
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Tuple

import numpy as np

PREFIX_BYTES = 24
_PREFIX_WORDS = PREFIX_BYTES // 4
K_BITS = 6
_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619
_H2_MUL = 0x9E3779B1
_MASK32 = 0xFFFFFFFF


def key_words(key: bytes) -> List[int]:
    """The 7 u32 lanes hashed for ``key`` (6 prefix words + length)."""
    prefix = key[:PREFIX_BYTES].ljust(PREFIX_BYTES, b"\x00")
    words = list(struct.unpack(f"<{_PREFIX_WORDS}I", prefix))
    words.append(len(key) & _MASK32)
    return words


def _avalanche(h: int) -> int:
    """murmur3 fmix32 — u32 shifts/multiplies only (TPU-lane friendly)."""
    h &= _MASK32
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK32
    h ^= h >> 16
    return h


def _avalanche_np(h: np.ndarray) -> np.ndarray:
    """Vectorized _avalanche over a u32 lane (wrapping multiplies)."""
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    return h


def hash_many(keys: List[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    """Filter-independent halves of the batch bloom probe: (h1, mask)
    u32 lanes for ``keys`` (vectorized 24-byte-prefix + length FNV fold,
    avalanche, K_BITS mask). Bit-exact with :func:`hash_pair` +
    :func:`word_mask` modulo the per-filter ``h1 % num_words`` index,
    which :meth:`BloomFilter.may_contain_hashed` applies."""
    n = len(keys)
    if n == 0:
        z = np.zeros(0, dtype=np.uint32)
        return z, z
    mat = np.frombuffer(
        b"".join(k[:PREFIX_BYTES].ljust(PREFIX_BYTES, b"\x00")
                 for k in keys),
        dtype=np.uint8).reshape(n, PREFIX_BYTES)
    lens = np.fromiter((len(k) for k in keys), dtype=np.uint32, count=n)
    words_le = mat.view("<u4").astype(np.uint32)
    with np.errstate(over="ignore"):
        h = np.full(n, _FNV_OFFSET, dtype=np.uint32)
        for w in range(_PREFIX_WORDS):
            h = (h ^ words_le[:, w]) * np.uint32(_FNV_PRIME)
        h = (h ^ lens) * np.uint32(_FNV_PRIME)
        h1 = _avalanche_np(h)
        h2 = _avalanche_np(h * np.uint32(_H2_MUL) + np.uint32(1))
        mask = np.zeros(n, dtype=np.uint32)
        for j in range(K_BITS):
            mask |= np.uint32(1) << ((h2 >> np.uint32(5 * j))
                                     & np.uint32(31))
    return h1, mask


def hash_pair(key: bytes) -> tuple:
    h = _FNV_OFFSET
    for w in key_words(key):
        h = ((h ^ w) * _FNV_PRIME) & _MASK32
    h1 = _avalanche(h)
    h2 = _avalanche((h * _H2_MUL + 1) & _MASK32)
    return h1, h2


def word_mask(key: bytes, num_words: int) -> tuple:
    """(word_index, 32-bit mask) for ``key`` — the exact quantities the TPU
    kernel computes per lane. Each of the K bits comes from an independent
    5-bit slice of h2 (30 of 32 bits used)."""
    h1, h2 = hash_pair(key)
    mask = 0
    for j in range(K_BITS):
        mask |= 1 << ((h2 >> (5 * j)) & 31)
    return h1 % num_words, mask


def num_words_for(num_keys: int, bits_per_key: int = 10) -> int:
    return max(1, (num_keys * bits_per_key + 31) // 32)


def _native():
    """Native hash/build path (format-identical; parity-tested)."""
    from .native.binding import NATIVE

    return NATIVE


class BloomFilter:
    def __init__(self, num_words: int, words: np.ndarray | None = None):
        self.num_words = num_words
        self.words = (
            words if words is not None else np.zeros(num_words, dtype=np.uint32)
        )

    @classmethod
    def build(cls, keys: Iterable[bytes], bits_per_key: int = 10) -> "BloomFilter":
        keys = list(keys)
        bf = cls(num_words_for(len(keys), bits_per_key))
        native = _native()
        if native is not None and keys:
            native.bloom_add_many(bf.words, keys)
            return bf
        for key in keys:
            bf.add(key)
        return bf

    @classmethod
    def build_from_arrays(cls, key_bytes_matrix, key_lens,
                          bits_per_key: int = 10) -> "BloomFilter":
        """Bulk build from a (n, max_klen) u8 key matrix + per-row
        lengths — no per-key Python objects (the per-key loop dominates
        the whole CPU compaction path at scale). Native path hands the
        concatenated buffer + offsets straight to bloom_add_many."""
        n = int(len(key_lens))
        bf = cls(num_words_for(n, bits_per_key))
        if n == 0:
            return bf
        key_bytes_matrix = np.ascontiguousarray(
            key_bytes_matrix, dtype=np.uint8)
        # clip to the matrix width: the mask below truncates the BUFFER
        # at the width, so un-clipped offsets would shift every later
        # key's hash range (and run past the buffer) — and the Python
        # fallback's slice truncates the same way
        lens = np.minimum(np.asarray(key_lens, dtype=np.uint64),
                          np.uint64(key_bytes_matrix.shape[1]))
        native = _native()
        if native is not None:
            mask = (np.arange(key_bytes_matrix.shape[1], dtype=np.uint64)
                    [None, :] < lens[:, None])
            buf = key_bytes_matrix[mask]  # row-major: keys stay in order
            offsets = np.zeros(n + 1, dtype=np.uint64)
            np.cumsum(lens, out=offsets[1:])
            native.bloom_add_concat(bf.words, buf, offsets, n)
            return bf
        for i in range(n):
            bf.add(key_bytes_matrix[i, : int(lens[i])].tobytes())
        return bf

    def add(self, key: bytes) -> None:
        idx, mask = word_mask(key, self.num_words)
        self.words[idx] |= np.uint32(mask)

    def may_contain(self, key: bytes) -> bool:
        # Pure Python on purpose: the per-probe ctypes marshalling costs
        # more than the hash itself (measured); the native path wins only
        # for bulk build.
        idx, mask = word_mask(key, self.num_words)
        return (int(self.words[idx]) & mask) == mask

    def may_contain_many(self, keys: List[bytes]) -> np.ndarray:
        """(n,) bool — the batch probe (multi_get checks a whole key set
        against each SST in one vectorized pass). Bit-exact with
        may_contain: same 24-byte-prefix + full-length hash."""
        h1, mask = hash_many(keys)
        return self.may_contain_hashed(h1, mask)

    def may_contain_hashed(self, h1: np.ndarray,
                           mask: np.ndarray) -> np.ndarray:
        """Probe with hashes precomputed by :func:`hash_many` — h1/mask
        depend only on the keys, so a multi-SST read (multi_get) hashes
        the key set ONCE and pays a modulo + gather per filter."""
        with np.errstate(over="ignore"):
            idx = h1 % np.uint32(self.num_words)
        return (self.words[idx] & mask) == mask

    # -- serialization ----------------------------------------------------

    def to_bytes(self) -> bytes:
        return struct.pack("<I", self.num_words) + self.words.astype("<u4").tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BloomFilter":
        (num_words,) = struct.unpack_from("<I", data, 0)
        words = np.frombuffer(data, dtype="<u4", count=num_words, offset=4).copy()
        return cls(num_words, words)
