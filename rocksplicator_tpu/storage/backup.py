"""Backup/restore: checkpoint-based object-store backups.

Reference: the admin plane's four checkpoint/backup mechanisms (SURVEY §5):
(1) HDFS BackupEngine and (2) S3-env BackupEngine collapse here into one
object-store path (the store URI decides the backend); (3) checkpoint-based
backup — ``Checkpoint::CreateCheckpoint`` + parallel raw-file transfer with
a ``dbmeta`` file (admin_handler.cpp:996-1129, 1208-1327) — is the
mechanism implemented; (4) the continuous incremental thread lives in
``admin.backup_manager``.

Layout under ``<prefix>/``: the checkpoint's files verbatim plus ``dbmeta``
(JSON: DBMetaData + file list). Incremental upload skips files already in
the store (SST files are immutable and uniquely named per upload set).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Dict, List, Optional

from ..observability.span import start_span
from ..utils.objectstore import ObjectStore
from .engine import DB, DBOptions
from .errors import StorageError

DBMETA_KEY = "dbmeta"


def backup_db(
    db: DB,
    store: ObjectStore,
    prefix: str,
    meta: Optional[Dict] = None,
    parallelism: int = 8,
    incremental: bool = True,
) -> Dict:
    """Checkpoint ``db`` and upload it under ``prefix``. Returns the dbmeta
    written. ``incremental`` skips files the store already holds.

    Split callers (the admin handler) checkpoint and upload separately so
    only the checkpoint — fast, hardlink-based — runs under the per-db
    admin lock: ``db.checkpoint(dir)`` then :func:`upload_checkpoint`.
    The checkpoint's hardlinks pin the SST inodes, so the upload stays
    consistent even if the db is closed or destroyed meanwhile."""
    # stage next to the db (same filesystem): the default temp dir is
    # often another device, where checkpoint's os.link degrades to a
    # full copy under the DB lock
    tmp = tempfile.mkdtemp(
        prefix=".backup-",  # swept at AdminHandler startup if orphaned
        dir=os.path.dirname(os.path.abspath(db.path)))
    ckpt_dir = os.path.join(tmp, "ckpt")
    try:
        ckpt_seq = db.checkpoint(ckpt_dir)
        return upload_checkpoint(
            db.path, store, prefix, ckpt_dir, ckpt_seq,
            meta=meta, parallelism=parallelism, incremental=incremental)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def upload_checkpoint(
    db_path: str,
    store: ObjectStore,
    prefix: str,
    ckpt_dir: str,
    ckpt_seq: int,
    meta: Optional[Dict] = None,
    parallelism: int = 8,
    incremental: bool = True,
) -> Dict:
    """Upload an already-created checkpoint directory under ``prefix``
    and write its dbmeta. Needs no db lock of any kind: the checkpoint
    directory is immutable once created."""
    files = sorted(
        f for f in os.listdir(ckpt_dir) if os.path.isfile(os.path.join(ckpt_dir, f))
    )
    existing = set()
    if incremental:
        with start_span("backup.list_existing"):
            plen = len(prefix.rstrip("/")) + 1
            existing = {
                k[plen:]
                for k in store.list_objects(prefix.rstrip("/") + "/")
            }
    to_upload = [
        os.path.join(ckpt_dir, f) for f in files
        if f not in existing or f == "MANIFEST"
    ]
    with start_span("backup.upload", files=len(to_upload),
                    parallelism=parallelism) as sp:
        sp.annotate(bytes=sum(os.path.getsize(p) for p in to_upload))
        store.put_objects(to_upload, prefix, parallelism=parallelism)
    # The MANIFEST is the one mutable file: a later incremental pass
    # into the same prefix overwrites it, which would break every
    # OLDER checkpoint in the chain (its dbmeta would download a
    # manifest referencing SSTs it never listed). Keep a versioned
    # copy per pass; the SSTs themselves are immutable and retained.
    manifest_key = f"MANIFEST-{ckpt_seq:020d}"
    with start_span("backup.manifest_copy"):
        store.copy_object(prefix.rstrip("/") + "/MANIFEST",
                          prefix.rstrip("/") + "/" + manifest_key)
    dbmeta = {
        "db_name": os.path.basename(db_path),
        "files": files,
        "manifest_key": manifest_key,
        "timestamp_ms": int(time.time() * 1000),
        # seq captured at checkpoint time, not after the upload: writes
        # landing during the upload are not in this backup.
        "seq": ckpt_seq,
    }
    if meta:
        dbmeta.update(meta)
    payload = json.dumps(dbmeta).encode("utf-8")
    with start_span("backup.dbmeta_put"):
        store.put_object_bytes(
            prefix.rstrip("/") + "/" + DBMETA_KEY, payload)
        # Versioned dbmeta: every past checkpoint stays restorable,
        # which is what lets point-in-time restore pick the newest
        # checkpoint <= to_seq (rocksdb BackupEngine's numbered-backup
        # chain analog).
        store.put_object_bytes(
            f"{prefix.rstrip('/')}/{DBMETA_KEY}-{ckpt_seq:020d}", payload)
    return dbmeta


def restore_db(
    store: ObjectStore,
    prefix: str,
    db_path: str,
    options: Optional[DBOptions] = None,
    parallelism: int = 8,
    dbmeta_key: str = DBMETA_KEY,
) -> Dict:
    """Download a backup into ``db_path`` (which must not exist) and
    validate against its dbmeta. Returns the dbmeta. The caller opens the
    DB afterwards (reference restoreDBHelper then re-adds the db).
    ``dbmeta_key`` selects a specific checkpoint from the versioned chain
    (``dbmeta-<seq>``); the default is the latest."""
    if os.path.exists(db_path):
        raise StorageError(f"restore target exists: {db_path}")
    with start_span("restore.dbmeta_get"):
        raw = store.get_object_bytes(prefix.rstrip("/") + "/" + dbmeta_key)
    dbmeta = json.loads(raw.decode("utf-8"))
    tmp = db_path + ".restoring"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    try:
        with start_span("restore.download", files=len(dbmeta["files"])):
            for f in dbmeta["files"]:
                key = f
                if f == "MANIFEST" and dbmeta.get("manifest_key"):
                    # download THIS checkpoint's manifest version (the bare
                    # MANIFEST object tracks the newest pass in the prefix)
                    key = dbmeta["manifest_key"]
                store.get_object(prefix.rstrip("/") + "/" + key,
                                 os.path.join(tmp, f))
        os.replace(tmp, db_path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dbmeta
