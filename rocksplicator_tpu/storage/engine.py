"""DB: the LSM engine tying WAL, memtable, SSTs, and compaction together.

API parity targets (what the upper layers use of rocksdb::DB — SURVEY.md):
- ``write(batch)`` / ``get`` / ``multi_get`` / ``new_iterator``
  (application_db.cpp delegates these)
- ``latest_sequence_number`` / ``get_updates_since`` (db_wrapper.h seam)
- ``checkpoint`` (admin_handler.cpp:996-1129 checkpoint backup)
- ``ingest_external_file`` with ``allow_global_seqno`` / ``ingest_behind``
  (admin_handler.cpp:1819-1827)
- ``compact_range`` (async_tm_compactDB) with a pluggable backend — the
  TPU offload seam
- ``get_property`` incl. ``num-levels`` / ``highest-empty-level``
  (application_db.cpp:183-225 DBLmaxEmpty ingest-behind safety check)
- ``destroy_db`` (clearDB path: removeDB → DestroyDB → reopen)
- ``set_options`` (async_tm_setDBOptions)

Directory layout: ``<path>/MANIFEST`` (JSON, atomic rewrite),
``<path>/wal/wal-*.log``, ``<path>/sst-*.tsst``.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from ..observability.span import start_span
from ..testing import failpoints as fp
from ..utils.misc import write_file_atomic
from ..utils.stats import Stats
from . import wal as wal_mod
from .compaction import CompactionBackend, CpuCompactionBackend, resolve_stream
from .errors import Corruption, InvalidArgument, StorageError
from .memtable import MemTable
from .merge import MERGE_OPERATORS, MergeOperator
from .records import OpType, WriteBatch, decode_batch
from .sst import COMPRESSION_NONE, COMPRESSION_ZLIB, SSTReader, SSTWriter

import bisect
import heapq
import itertools
import logging

log = logging.getLogger(__name__)

_MANIFEST = "MANIFEST"


@dataclass
class DBOptions:
    create_if_missing: bool = True
    error_if_exists: bool = False
    merge_operator: Optional[MergeOperator] = None
    num_levels: int = 7
    allow_ingest_behind: bool = False
    memtable_bytes: int = 8 * 1024 * 1024
    block_bytes: int = 32 * 1024
    compression: int = COMPRESSION_ZLIB
    bits_per_key: int = 10
    wal_segment_bytes: int = 16 * 1024 * 1024
    wal_ttl_seconds: float = 3600.0
    sync_writes: bool = False
    level0_compaction_trigger: int = 4
    target_file_bytes: int = 64 * 1024 * 1024
    compaction_backend: Optional[CompactionBackend] = None
    disable_auto_compaction: bool = False
    # Background flush/compaction: writes swap a full memtable into the
    # immutable queue and return immediately (stalling only when the queue
    # is full) — the BASELINE write-stall target depends on this.
    # Off by default so single-threaded callers stay deterministic.
    background_compaction: bool = False
    # Total memtables (1 active + up to N-1 immutable awaiting flush) —
    # RocksDB's max_write_buffer_number. A burst that fills one memtable
    # while another flushes no longer stalls the writer; only a sustained
    # rate above flush throughput fills the queue and stalls.
    max_write_buffers: int = 4
    # After this many CONSECUTIVE background-flush failures, writes raise
    # instead of queueing data the flusher can't persist. The round-2
    # failure mode was the opposite: retry-forever while the DB silently
    # accepted writes it would never flush (VERDICT r2 #1). RocksDB's
    # analog: bg_error_ puts the DB in read-only mode.
    max_flush_failures: int = 3
    # Delayed-write controller (rocksdb WriteController analog): once
    # flush/compaction debt builds — imm queue one short of full, or L0
    # at the slowdown trigger — each admission pays a delay proportional
    # to its bytes (batch_bytes / delayed_write_rate, the rocksdb
    # delayed_write_rate knob) instead of eventually hitting a hard
    # multi-flush-length stop. Hard stops (queue completely full + active
    # memtable full) still happen but become rare, which is what keeps
    # write-stall p99 in the single-digit milliseconds under a storm.
    # 0 disables the soft tier. Triggers mirror rocksdb's
    # level0_slowdown/stop_writes_trigger (defaults 20/36 there; lower
    # here because L0 files are smaller).
    delayed_write_rate: int = 16 * 1024 * 1024  # bytes/s, rocksdb default
    level0_slowdown_writes_trigger: int = 12
    level0_stop_writes_trigger: int = 24
    # Per-level byte targets for the compaction-debt gauges (rocksdb's
    # max_bytes_for_level_base/_multiplier): level L>=1 target is
    # base * multiplier^(L-1); bytes above target are "debt" — the
    # foreground-pressure signal a workload-adaptive compaction
    # scheduler prioritizes by (RESYSTANCE, arxiv 2603.05162). L0 debt
    # is files beyond the compaction trigger, expressed in bytes.
    max_bytes_for_level_base: int = 256 * 1024 * 1024
    max_bytes_for_level_multiplier: int = 10
    # WAL archival (storage.archive.WalArchiver.sink, or any
    # callable(path)): sealed WAL segments are shipped here before TTL
    # deletion, enabling point-in-time restore (restore_db(..., to_seq))
    # — the BackupEngine-incremental-chain analog. None = segments are
    # simply deleted at TTL, as before.
    wal_archive_sink: Optional[object] = None
    # Workload-adaptive compaction scheduling (compaction_scheduler.py):
    # the background compaction thread picks work by PRESSURE (L0 file
    # count vs triggers, per-level debt vs targets, windowed read-amp,
    # delayed-write stall boost) and re-ranks on every flush/install
    # instead of waiting on the fixed L0 trigger. RSTPU_COMPACTION_SCHED=0
    # reverts every DB in the process to the legacy trigger loop (the
    # scheduler A/B's off arm).
    compaction_scheduler: bool = field(
        default_factory=lambda: os.environ.get(
            "RSTPU_COMPACTION_SCHED", "1") not in ("0", "false"))
    # Key-range subcompactions (rocksdb max_subcompactions): one large
    # compaction splits into disjoint key-range slices executed in
    # parallel across cores (one padded device batch on the TPU
    # backend). 0 = auto (min(4, cores)), 1 = off.
    max_subcompactions: int = field(
        default_factory=lambda: int(os.environ.get(
            "RSTPU_MAX_SUBCOMPACTIONS", "0")))
    # Compaction output IO budget (bytes/s) shared with the delayed-
    # write controller: compaction file writes consume tokens and yield
    # to in-flight foreground WAL fsyncs; admission stalls OPEN the
    # budget (debt drain is what un-delays writes), as does a
    # read-heavy mix. 0 = unmetered (yield-to-foreground only).
    compaction_budget_bytes_per_sec: int = field(
        default_factory=lambda: int(os.environ.get(
            "RSTPU_COMPACT_BUDGET_BYTES", "0")))
    # Hard ceiling on compaction lane bytes materialized in RAM
    # (storage/stream_merge.py): full compactions whose projected
    # working set exceeds it run as a streaming chunked k-way merge
    # with fixed windows per input run instead of decoding every run at
    # once — unlocking levels >> RAM. 0 = the process-wide default
    # (RSTPU_COMPACT_MEM_BUDGET, 256 MiB). The per-compaction
    # high-water feeds the compaction.peak_bytes_materialized gauge.
    compaction_memory_budget_bytes: int = 0
    # Retained key range [retain_lo, retain_hi) as hex strings (the
    # SplitRecord split_key encoding): compactions DROP user keys
    # outside the range — the range-split child's garbage trim. A child
    # born by renaming a full parent copy serves only its half; its
    # first scheduled compaction rewrites inputs without the other
    # half's bytes instead of carrying them to the bottom level
    # forever. Keys in the reserved internal namespace (leading NUL —
    # CDC watermarks/applies counters, storage/…/checkpoint.py) are
    # ALWAYS retained regardless of the range. None/"" = no bound.
    retain_lo: Optional[str] = None
    retain_hi: Optional[str] = None

    # Mutable at runtime via DB.set_options (reference setDBOptions RPC).
    MUTABLE = {
        "memtable_bytes", "wal_ttl_seconds", "level0_compaction_trigger",
        "target_file_bytes", "disable_auto_compaction", "sync_writes",
        "delayed_write_rate", "level0_slowdown_writes_trigger",
        "level0_stop_writes_trigger", "max_subcompactions",
        "compaction_budget_bytes_per_sec",
        "compaction_memory_budget_bytes", "retain_lo", "retain_hi",
    }

    def retain_bounds(self) -> Optional[Tuple[Optional[bytes],
                                              Optional[bytes]]]:
        """Decoded (lo, hi) byte bounds, or None when no trim is
        configured. Malformed hex disables the trim (never drop data on
        a bad knob) rather than raising mid-compaction."""
        if not self.retain_lo and not self.retain_hi:
            return None
        try:
            lo = bytes.fromhex(self.retain_lo) if self.retain_lo else None
            hi = bytes.fromhex(self.retain_hi) if self.retain_hi else None
        except ValueError:
            return None
        return (lo, hi)


class _MergedMemView:
    """Read view over several immutable memtables as one sorted entry
    stream — the source handed to the SST sinks when a flush drains a
    multi-memtable backlog in one file. Each memtable's entries() is
    (key asc, seq desc); the heap-merge preserves that order globally
    (distinct memtables never share a seq)."""

    def __init__(self, imms: List[MemTable]):
        self._imms = imms
        self.max_seq = max(m.max_seq for m in imms)

    def entries(self) -> Iterator[Tuple[bytes, int, int, bytes]]:
        return heapq.merge(
            *(m.entries() for m in self._imms),
            key=lambda e: (e[0], -e[1]),
        )

    def drain_lanes(self):
        """Concatenated unsorted lanes across every memtable (see
        MemTable.drain_lanes) — the caller's single lexsort restores the
        global (key asc, seq desc) order. None when any memtable can't
        express its entries as lanes; cross-memtable width mismatches
        are caught by the caller's planar_widths check."""
        import numpy as np

        parts = [m.drain_lanes() for m in self._imms]
        if any(p is None for p in parts):
            return None
        # Cross-memtable width checks BEFORE any pad/concat — scalar
        # reads off each part's lanes, so a mismatched burst bails in
        # O(parts) instead of after a giant transient concatenation
        # (the same round-2 lesson MemTable.drain_lanes applies within
        # one memtable).
        if len({km.shape[1] for _l, km in parts}) != 1:
            return None  # mixed key widths across memtables
        part_vlens = set()
        for lanes, _km in parts:
            live = lanes["val_len"][lanes["vtype"] != 2]
            if len(live):  # all-DELETE parts constrain nothing
                part_vlens.add(int(live[0]))
        if len(part_vlens) > 1:
            return None  # mixed value widths across memtables
        vw = max(p[0]["val_words"].shape[1] for p in parts)
        for lanes, _km in parts:
            w = lanes["val_words"].shape[1]
            if w < vw:
                lanes["val_words"] = np.pad(
                    lanes["val_words"], [(0, 0), (0, vw - w)])
        lanes = {
            f: np.concatenate([l[f] for l, _km in parts])
            for f in parts[0][0]
        }
        return lanes, np.concatenate([km for _l, km in parts])


class DB:
    """One LSM database (one shard in the sharded deployment)."""

    def __init__(self, path: str, options: Optional[DBOptions] = None):
        self.path = os.path.abspath(path)
        self.options = options or DBOptions()
        self._lock = threading.RLock()
        self._mem = MemTable()
        self._imms: List[MemTable] = []  # immutable queue, oldest first
        self._last_seq = 0
        self._persisted_seq = 0  # highest seq durable in SSTs
        self._next_file_id = 1
        # levels[0] may overlap; levels[1:] sorted non-overlapping by range
        self._levels: List[List[str]] = []
        self._readers: Dict[str, SSTReader] = {}
        # per-level key-fence arrays (sorted min_keys, parallel max_keys +
        # names) for bisect file lookup on levels >= 1; built lazily and
        # dropped whenever a compaction/ingest rewrites a level's file set
        self._fences: Dict[int, Tuple[List[bytes], List[bytes], List[str]]] = {}
        self._wal: Optional[wal_mod.WalWriter] = None
        self._closed = False
        if self.options.compaction_backend is not None:
            self._backend = self.options.compaction_backend
        else:
            # default: heapq streaming for tuple merges PLUS the direct
            # array sink (native C resolve + bulk bloom + planar writer)
            # for runs that read as lanes — RocksDB-class compaction on
            # hosts without an accelerator
            from .native_compaction import NativeCompactionBackend

            self._backend = NativeCompactionBackend()
        # background machinery: cond signals imm-slot changes; compaction
        # mutex serializes compactions (bg + manual) so only one remover of
        # files runs at a time (flushes only ever add files)
        self._cond = threading.Condition(self._lock)
        self._compaction_mutex = threading.Lock()
        # Manifest writes are versioned so the two fsyncs in
        # write_file_atomic can run OUTSIDE self._lock (they were the
        # dominant write-stall tail: every flush/compaction install held
        # the DB lock across file+dir fsync). Snapshots are taken under
        # self._lock (monotonic version); the writer mutex drops any
        # snapshot older than what is already durable.
        self._manifest_mutex = threading.Lock()  # rstpu-check: io-mutex versioned manifest writer — exists precisely to take the fsyncs OFF self._lock
        self._manifest_version = 0
        self._manifest_written_version = 0
        self._bg_stop = False
        self._bg_flush_error: Optional[BaseException] = None
        self._bg_flush_failures = 0
        # Measured flush throughput (bytes/s, EWMA over recent flushes).
        # The delayed-write controller paces admissions to THIS, not the
        # static delayed_write_rate knob, when the host flushes slower
        # than the knob assumes (rocksdb's WriteController does the same:
        # the delay rate tracks flush bandwidth). 0 = no flush measured.
        self._flush_rate_ewma = 0.0
        self._bg_compaction_error: Optional[BaseException] = None
        self._bg_compaction_failures = 0
        self._bg_thread: Optional[threading.Thread] = None
        self._compaction_thread: Optional[threading.Thread] = None
        # Introspection counters (all mutated under self._lock): the
        # cumulative inputs of the pull-model gauges. read-amp = files
        # consulted per get (fence/bloom path); write-amp = bytes
        # written by compaction / bytes flushed (rocksdb's definition,
        # measured at the flush/compaction install sinks).
        self._gets_total = 0
        self._files_consulted_total = 0
        self._bytes_flushed_total = 0
        self._bytes_compacted_total = 0
        # split of bytes_compacted_total by WHERE the merge ran: bytes a
        # remote worker produced (round 18 disaggregated tier) vs bytes
        # this serving node's own compactions wrote. local = total -
        # remote; the macro-bench acceptance drives local → ~0 tier-on.
        self._remote_offloaded_bytes_total = 0
        # round 18: when set (set_remote_compactor), non-manual picks
        # offer themselves to the disaggregated worker tier before the
        # local compaction dispatch
        self._remote_compactor = None
        # high-water of live compaction lane bytes during the most
        # recent direct/streaming merge (stream_merge.MemTracker) —
        # the compaction.peak_bytes_materialized gauge the memory
        # budget's acceptance test asserts against
        self._compaction_peak_bytes = 0
        # last foreground write (monotonic): the scheduler defers batch
        # level-debt work while the foreground is live and drains it in
        # valleys (compaction_scheduler.IDLE_DRAIN_SEC). 0 = never
        # written this process ⇒ idle, so a reopened db with standing
        # debt drains immediately.
        self._last_write_mono = 0.0
        # short-lived cache so one /stats or /metrics dump evaluating a
        # dozen per-db gauges pays ONE lock pass, not one per gauge
        self._metrics_cache: Tuple[float, Optional[Dict]] = (0.0, None)
        # Workload-adaptive compaction scheduling (round 16): priority
        # picks from the pressure gauges + the foreground-yielding IO
        # budget. The budget exists whenever the scheduler does — even
        # at rate 0 its yield-to-foreground tier is active.
        self._sched = None
        self._io_budget = None
        if self.options.background_compaction and \
                self.options.compaction_scheduler:
            from .compaction_scheduler import CompactionScheduler, IoBudget

            self._sched = CompactionScheduler(self)
            self._io_budget = IoBudget(
                self.options.compaction_budget_bytes_per_sec)
        self._open()
        if self.options.background_compaction:
            # Separate flush and compaction threads (as RocksDB separates
            # its pools): a running compaction must never block the imm
            # slot, or writers inherit the compaction's latency.
            self._bg_thread = threading.Thread(
                target=self._flush_loop,
                name=f"lsm-flush-{os.path.basename(self.path)}", daemon=True,
            )
            self._bg_thread.start()
            self._compaction_thread = threading.Thread(
                target=self._compaction_loop,
                name=f"lsm-compact-{os.path.basename(self.path)}", daemon=True,
            )
            self._compaction_thread.start()

    # ------------------------------------------------------------------
    # open / recovery
    # ------------------------------------------------------------------

    def _open(self) -> None:
        manifest_path = os.path.join(self.path, _MANIFEST)
        exists = os.path.isfile(manifest_path)
        if exists and self.options.error_if_exists:
            raise InvalidArgument(f"db exists: {self.path}")
        if not exists and not self.options.create_if_missing:
            raise InvalidArgument(f"db missing: {self.path}")
        os.makedirs(self.path, exist_ok=True)
        os.makedirs(self._wal_dir, exist_ok=True)
        if exists:
            with open(manifest_path, "r") as f:
                manifest = json.load(f)
            self._persisted_seq = manifest["persisted_seq"]
            self._next_file_id = manifest["next_file_id"]
            self._levels = [list(files) for files in manifest["levels"]]
            self._incarnation = manifest.get("incarnation", "00000000")
        else:
            self._levels = [[] for _ in range(self.options.num_levels)]
            # Unique per DB creation: file names can never collide across a
            # destroy+recreate, so name-based incremental backup skipping is
            # safe (a recreated db's sst-...-00000001 is a different name).
            self._incarnation = uuid.uuid4().hex[:8]
            self._persist_manifest()
        while len(self._levels) < self.options.num_levels:
            self._levels.append([])
        for level_files in self._levels:
            for name in level_files:
                self._readers[name] = SSTReader(os.path.join(self.path, name))
        # Recover: last_seq from SSTs, then WAL replay beyond persisted_seq.
        self._last_seq = self._persisted_seq
        for start_seq, body in wal_mod.iter_updates(
            self._wal_dir, 0, truncate_torn=True
        ):
            batch = decode_batch(body)
            end_seq = start_seq + batch.count() - 1
            if end_seq <= self._persisted_seq:
                continue
            self._apply_to_memtable(batch, start_seq)
            self._last_seq = max(self._last_seq, end_seq)
        self._wal = wal_mod.WalWriter(
            self._wal_dir, self.options.wal_segment_bytes
        )
        if self._io_budget is not None:
            # foreground WAL fsyncs register in-flight so compaction
            # output writes yield to them (compaction_scheduler.IoBudget)
            self._wal.io_budget = self._io_budget

    @property
    def _wal_dir(self) -> str:
        return os.path.join(self.path, "wal")

    def _manifest_dict(self) -> Dict:
        return {
            "persisted_seq": self._persisted_seq,
            "next_file_id": self._next_file_id,
            "levels": self._levels,
            "incarnation": self._incarnation,
        }

    def _persist_manifest(self, target_dir: Optional[str] = None) -> None:
        """Synchronous manifest write (durable on return). For another
        directory (checkpoint/backup) it is a plain unversioned copy; for
        the live DB it participates in the versioned ordering so it can
        never be overwritten by a stale concurrent snapshot."""
        if target_dir is not None:
            fp.hit("manifest.persist")
            write_file_atomic(
                os.path.join(target_dir, _MANIFEST),
                json.dumps(self._manifest_dict()).encode("utf-8"),
            )
            return
        self._write_manifest_payload(*self._manifest_snapshot_locked())

    def _manifest_snapshot_locked(self) -> Tuple[int, bytes]:
        """Capture manifest content + version under self._lock; pair with
        _write_manifest_payload AFTER releasing the lock."""
        self._manifest_version += 1
        return (self._manifest_version,
                json.dumps(self._manifest_dict()).encode("utf-8"))

    def _write_manifest_payload(self, version: int, payload: bytes) -> None:
        """Durably write a manifest snapshot unless a newer one already
        landed. Holds only _manifest_mutex — never self._lock — so the
        fsyncs don't stall writers."""
        with self._manifest_mutex:
            if version <= self._manifest_written_version:
                return
            fp.hit("manifest.persist")
            write_file_atomic(
                os.path.join(self.path, _MANIFEST), payload)
            self._manifest_written_version = version

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------

    def write(self, batch: WriteBatch, sync: bool = False,
              encoded: Optional[bytes] = None) -> int:
        """Apply a batch atomically; returns the batch's start seq.

        ``encoded`` lets a caller that already HOLDS the batch's encoded
        bytes (a follower applying a replicated update ships the raw
        leader bytes) skip the re-encode — the bytes must be exactly
        ``batch.encode()``.

        Sync durability is GROUP-COMMITTED: the fsync runs OUTSIDE the
        DB lock (readers and other writers never block on the disk) and
        one leader's fsync covers every concurrently-waiting sync
        writer (WalWriter.sync_to). As in rocksdb's pipelined-write
        mode, a concurrent reader may observe a sync write in the
        memtable shortly before its fsync returns; write() itself does
        not return until the batch is durable."""
        count = batch.count()
        with self._lock:
            self._check_open()
            self._check_flush_health_locked()
            self._admission_stall_locked(batch.byte_size())
            self._check_open()
            self._check_flush_health_locked()
            start_seq = self._last_seq + 1
            self._last_write_mono = time.monotonic()
            if encoded is None:
                encoded = batch.encode()
            assert self._wal is not None
            token = self._wal.append(start_seq, encoded)
            self._apply_to_memtable(batch, start_seq)
            self._last_seq += count
            if self._mem.approximate_bytes() >= self.options.memtable_bytes:
                if self._bg_thread is not None:
                    self._swap_to_imm_locked()
                else:
                    self._flush_locked()
            wal = self._wal
        if sync or self.options.sync_writes:
            wal.sync_to(token)
        return start_seq

    def write_many(
        self,
        items: List[Tuple[WriteBatch, Optional[bytes]]],
        sync: bool = False,
    ) -> int:
        """Apply a GROUP of batches in order with one lock pass and one
        WAL flush — the follower apply path commits a whole replication
        pull response per call instead of paying the per-record flush
        syscall and lock round-trip 50+ times per response. Each batch
        still gets its own sequence range (identical numbering to N
        ``write`` calls — replication continuity depends on it); the
        group is NOT atomic against a crash mid-flush, which matches N
        separate non-sync writes. Returns the FIRST batch's start seq.

        ``items`` pairs each batch with its encoded bytes when the
        caller already holds them (replicated updates ship the leader's
        raw bytes), else None to encode here."""
        if not items:
            raise ValueError("write_many: empty group")
        total_bytes = sum(
            len(enc) if enc is not None else b.byte_size()
            for b, enc in items
        )
        with self._lock:
            self._check_open()
            self._check_flush_health_locked()
            self._admission_stall_locked(total_bytes)
            self._check_open()
            self._check_flush_health_locked()
            assert self._wal is not None
            first_seq = self._last_seq + 1
            self._last_write_mono = time.monotonic()
            records = []
            seq = first_seq
            for batch, encoded in items:
                if encoded is None:
                    encoded = batch.encode()
                records.append((seq, encoded))
                seq += batch.count()
            token = self._wal.append_many(records)
            seq = first_seq
            for batch, _ in items:
                self._apply_to_memtable(batch, seq)
                seq += batch.count()
                self._last_seq = seq - 1
            if self._mem.approximate_bytes() >= self.options.memtable_bytes:
                if self._bg_thread is not None:
                    self._swap_to_imm_locked()
                else:
                    self._flush_locked()
            wal = self._wal
        if sync or self.options.sync_writes:
            wal.sync_to(token)
        return first_seq

    def _admission_stall_locked(self, batch_bytes: int) -> None:
        """Write-stall at ADMISSION (rocksdb WriteController analog):
        stalling here — before seq assignment and the WAL append — means
        a flush-gate trip raises for a write that has NOT committed (safe
        to retry), and admission is fair: late arrivals cannot refill a
        fresh memtable under a writer already waiting in the swap loop,
        which starved it through multiple flush cycles.

        Two tiers, as in rocksdb:
        - SOFT (delayed write): imm queue one short of full, or L0 at the
          slowdown trigger → this admission pays one short bounded delay.
          The flusher/compactor runs during the delay (the wait releases
          the lock), so debt drains before the hard condition is reached.
        - HARD (stop): no imm slot AND the active memtable is full, or L0
          at the stop trigger → wait for a flush/compaction to complete.
        Both tiers record storage.write_stall_ms."""
        if self._bg_thread is None:
            return  # inline-flush mode: writes flush synchronously
        opts = self.options

        def l0_managed():
            # re-evaluated each pass: disable_auto_compaction is MUTABLE,
            # and a writer parked on the stop trigger must not keep
            # waiting for a compactor the operator just switched off
            return (self._compaction_thread is not None
                    and not opts.disable_auto_compaction)

        cap = max(1, opts.max_write_buffers - 1)
        stall_start = None
        if opts.delayed_write_rate > 0 and (
            (cap > 1 and len(self._imms) >= cap - 1)
            or (l0_managed() and len(self._levels[0])
                >= opts.level0_slowdown_writes_trigger)
        ):
            # Pace to the MEASURED flush rate when it is below the
            # configured delayed_write_rate (rocksdb WriteController
            # semantics: delay rate follows flush bandwidth). On a
            # contended host flushes run slower, so static pacing admits
            # faster than the flusher drains and writers pile into the
            # hard tier — which is where double-digit p99 comes from.
            # One delay stays capped (8ms) so the soft tier itself can't
            # produce double-digit stalls.
            rate = float(opts.delayed_write_rate)
            if self._flush_rate_ewma > 0.0:
                rate = min(rate, max(self._flush_rate_ewma, 256.0 * 1024))
            delay = min(0.008, max(batch_bytes, 64) / rate)
            stall_start = time.monotonic()
            self._cond.wait(delay)
        while (
            (
                len(self._imms) >= cap
                and self._mem.approximate_bytes() >= opts.memtable_bytes
            )
            or (l0_managed() and len(self._levels[0])
                >= opts.level0_stop_writes_trigger)
        ) and not self._closed and not self._bg_stop:
            self._check_flush_health_locked()  # pre-admission: may raise
            self._check_compaction_health_locked()  # ditto for the L0 gate
            if stall_start is None:
                stall_start = time.monotonic()
            self._cond.wait(0.05)
        self._record_stall(stall_start)

    def _swap_to_imm_locked(self, force: bool = False) -> None:
        """Hand the full memtable to the background flusher. Stalls only
        while the immutable QUEUE is full AND this writer's swap is still
        needed — once a peer writer swapped, the fresh memtable is below
        threshold and waiters exit immediately. Never exceeds the queue
        bound (bails instead on stop/close)."""
        cap = max(1, self.options.max_write_buffers - 1)
        stall_start = None
        while (
            len(self._imms) >= cap
            and not self._closed
            and not self._bg_stop
            and (force or self._mem.approximate_bytes()
                 >= self.options.memtable_bytes)
        ):
            # A failing flusher never drains the queue. This writer's
            # batch is already WAL-appended and applied, so raising here
            # would report failure for a committed write (a retry would
            # double-apply MERGE). Bail without swapping instead — the
            # NEXT write is rejected pre-admission by the health check at
            # the top of write(), matching rocksdb's bg_error
            # reject-before-admit semantics.
            if self._flush_gate_tripped_locked():
                self._record_stall(stall_start)
                return
            if stall_start is None:
                stall_start = time.monotonic()
            self._cond.wait(0.05)
        self._record_stall(stall_start)
        if (
            len(self._imms) >= cap  # stop/close exit: leave the queue alone
            or self._closed
            or self._bg_stop
            or len(self._mem) == 0
            or not (force or self._mem.approximate_bytes()
                    >= self.options.memtable_bytes)
        ):
            return
        self._imms.append(self._mem)
        self._mem = MemTable()
        self._cond.notify_all()

    def _record_stall(self, stall_start: Optional[float]) -> None:
        if stall_start is not None:
            stall_ms = (time.monotonic() - stall_start) * 1000.0
            Stats.get().add_metric("storage.write_stall_ms", stall_ms)
            if self._io_budget is not None:
                # the delayed-write controller's stall signal feeds the
                # scheduler's priority boost AND opens the IO budget:
                # debt drain accelerates precisely when writes are
                # being delayed
                self._io_budget.note_stall(stall_ms)

    def _flush_gate_tripped_locked(self) -> bool:
        """One source of truth for 'the background flusher is dead enough
        to refuse admission' — shared by the pre-admission raise and the
        stall-loop bail so the thresholds can't drift."""
        return (
            self._bg_flush_error is not None
            and self._bg_flush_failures >= self.options.max_flush_failures
        )

    def _check_compaction_health_locked(self) -> None:
        """Raise once the background compactor has failed enough
        consecutive times: a writer parked on the L0 stop trigger would
        otherwise wait forever for a drain that cannot happen (same
        loud-failure requirement as the flush gate)."""
        if (
            self._bg_compaction_error is not None
            and self._bg_compaction_failures
            >= self.options.max_flush_failures
        ):
            raise StorageError(
                f"background compaction failed "
                f"{self._bg_compaction_failures}x consecutively; refusing "
                f"writes at L0 stop trigger: {self._bg_compaction_error!r}"
            )

    def _check_flush_health_locked(self) -> None:
        """Raise once the background flusher has failed enough consecutive
        times that accepting more writes would just grow an unpersistable
        backlog (loud-failure requirement — VERDICT r2 #1)."""
        if self._flush_gate_tripped_locked():
            raise StorageError(
                f"background flush failed {self._bg_flush_failures}x "
                f"consecutively; refusing writes: {self._bg_flush_error!r}"
            )

    def _drain_imm_locked(self) -> None:
        """Wait until no immutable memtable is pending. Raises if the DB
        closed underneath us or the background flusher is failing (matching
        inline mode, where the flush error reached the caller)."""
        while self._imms and not self._closed:
            if self._bg_flush_error is not None:
                raise StorageError(
                    f"background flush failing: {self._bg_flush_error!r}"
                )
            self._cond.wait(0.05)
        self._check_open()

    def _apply_to_memtable(self, batch: WriteBatch, start_seq: int) -> None:
        seq = start_seq
        for op, key, value in batch.ops():
            if op is OpType.LOG_DATA:
                continue
            self._mem.apply(key, seq, op, value)
            seq += 1

    def put(self, key: bytes, value: bytes) -> int:
        return self.write(WriteBatch().put(key, value))

    def delete(self, key: bytes) -> int:
        return self.write(WriteBatch().delete(key))

    def merge(self, key: bytes, operand: bytes) -> int:
        return self.write(WriteBatch().merge(key, operand))

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        key = bytes(key)
        with self._lock:
            self._check_open()
            # read-amp accounting: every SST actually consulted (bloom/
            # fence survivors) counts; the gauge reports the cumulative
            # files-consulted-per-get ratio
            self._gets_total += 1
            consulted = 0
            try:
                merge_op = self.options.merge_operator
                operands: List[bytes] = []
                # newest first: active memtable, then immutables newest->oldest
                for mem in (self._mem, *reversed(self._imms)):
                    resolved, value, pending = mem.get(key, merge_op)
                    if resolved and not operands:
                        return value
                    if resolved:
                        base = value
                        return merge_op.merge(key, base, operands[::-1]) if merge_op else base
                    operands.extend(pending[::-1])  # newest-first accumulation
                # L0 newest-first, then deeper levels. Fold through every entry
                # of each file's per-key stack (MERGE operands stack within one
                # SST after a flush).
                for name in reversed(self._levels[0]):
                    consulted += 1
                    for result in self._readers[name].get_entries(key):
                        done, value = self._fold(key, result, operands, merge_op)
                        if done:
                            return value
                for level in range(1, len(self._levels)):
                    reader = self._find_file_for_key(level, key)
                    if reader is None:
                        continue
                    consulted += 1
                    for result in reader.get_entries(key):
                        done, value = self._fold(key, result, operands, merge_op)
                        if done:
                            return value
                if operands and merge_op:
                    return merge_op.merge(key, None, operands[::-1])
                return None
            finally:
                self._files_consulted_total += consulted

    def _fold(
        self,
        key: bytes,
        result: Tuple[int, int, bytes],
        operands: List[bytes],
        merge_op: Optional[MergeOperator],
    ) -> Tuple[bool, Optional[bytes]]:
        _seq, vtype, value = result
        if vtype == OpType.PUT:
            if operands and merge_op:
                return True, merge_op.merge(key, value, operands[::-1])
            return True, value
        if vtype == OpType.DELETE:
            if operands and merge_op:
                return True, merge_op.merge(key, None, operands[::-1])
            return True, None
        operands.append(value)  # MERGE operand, keep descending
        return False, None

    def _level_fences_locked(
        self, level: int
    ) -> Tuple[List[bytes], List[bytes], List[str]]:
        """(sorted min_keys, parallel max_keys, names) for a level —
        built once per file-set generation (install/GC/ingest clear the
        cache), replacing the per-get linear min_key()/max_key() scan."""
        fences = self._fences.get(level)
        if fences is None:
            recs = []
            for name in self._levels[level]:
                reader = self._readers[name]
                mn, mx = reader.min_key(), reader.max_key()
                if mn is not None and mx is not None:
                    recs.append((mn, mx, name))
            recs.sort()
            fences = ([r[0] for r in recs], [r[1] for r in recs],
                      [r[2] for r in recs])
            self._fences[level] = fences
        return fences

    def _find_file_for_key(self, level: int, key: bytes) -> Optional[SSTReader]:
        """Bisect the level's fence arrays (levels >= 1 are sorted and
        non-overlapping): the candidate file is the one with the greatest
        min_key <= key, live iff key <= its max_key."""
        mins, maxs, names = self._level_fences_locked(level)
        i = bisect.bisect_right(mins, key) - 1
        if i >= 0 and key <= maxs[i]:
            return self._readers[names[i]]
        return None

    def multi_get(self, keys: List[bytes]) -> List[Optional[bytes]]:
        """Point lookups for many keys with ONE lock pass over the
        memtable/file-set snapshot (``[self.get(k) for k in keys]``
        re-took the DB lock per key), blooms checked in batch, and keys
        grouped per SST so each touched block decodes (or cache-hits)
        once. Result order matches ``keys``; semantics are entry-exact
        with per-key ``get`` (the parity test pins it)."""
        from .bloom import hash_many

        keys_b = [bytes(k) for k in keys]
        with self._lock:
            self._check_open()
            self._gets_total += len(keys_b)
            merge_op = self.options.merge_operator
            results: Dict[bytes, Optional[bytes]] = {}
            operands: Dict[bytes, List[bytes]] = {}
            pending: List[bytes] = []
            for k in keys_b:
                if k not in operands:
                    operands[k] = []
                    pending.append(k)
            # bloom hashes are filter-independent: compute ONCE for the
            # unique key set, probe per SST with a modulo + gather
            h1_all, mask_all = hash_many(pending)
            hashes = ({k: i for i, k in enumerate(pending)},
                      h1_all, mask_all)
            # newest first: active memtable, then immutables newest->oldest
            for mem in (self._mem, *reversed(self._imms)):
                if not pending:
                    break
                still: List[bytes] = []
                for k in pending:
                    resolved, value, pend = mem.get(k, merge_op)
                    ops = operands[k]
                    if resolved:
                        results[k] = (
                            merge_op.merge(k, value, ops[::-1])
                            if ops and merge_op else value
                        )
                    else:
                        ops.extend(pend[::-1])  # newest-first accumulation
                        still.append(k)
                pending = still
            # L0 newest-first: every file may contain any key
            for name in reversed(self._levels[0]):
                if not pending:
                    break
                pending = self._fold_reader_many(
                    self._readers[name], pending, operands, results,
                    merge_op, hashes)
            # deeper levels: group pending keys per fenced file
            for level in range(1, len(self._levels)):
                if not pending:
                    break
                groups: Dict[str, List[bytes]] = {}
                skipped: List[bytes] = []
                mins, maxs, names = self._level_fences_locked(level)
                for k in pending:
                    i = bisect.bisect_right(mins, k) - 1
                    if i >= 0 and k <= maxs[i]:
                        groups.setdefault(names[i], []).append(k)
                    else:
                        skipped.append(k)
                still = skipped
                for name, group in groups.items():
                    still.extend(self._fold_reader_many(
                        self._readers[name], group, operands, results,
                        merge_op, hashes))
                pending = still
            for k in pending:
                ops = operands[k]
                results[k] = (
                    merge_op.merge(k, None, ops[::-1])
                    if ops and merge_op else None
                )
            return [results[k] for k in keys_b]

    def _fold_reader_many(
        self,
        reader: SSTReader,
        pending: List[bytes],
        operands: Dict[bytes, List[bytes]],
        results: Dict[bytes, Optional[bytes]],
        merge_op: Optional[MergeOperator],
        hashes=None,
    ) -> List[bytes]:
        """Fold one SST's entry stacks into the per-key resolution state;
        returns the keys still unresolved after this file."""
        self._files_consulted_total += len(pending)  # read-amp accounting
        found = reader.get_entries_many(pending, hashes=hashes)
        still: List[bytes] = []
        for k in pending:
            entries = found.get(k)
            done = False
            if entries:
                for result in entries:
                    done, value = self._fold(k, result, operands[k],
                                             merge_op)
                    if done:
                        results[k] = value
                        break
            if not done:
                still.append(k)
        return still

    def new_iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]:
        """Live (key, value) pairs in key order over a point-in-time view.

        The view is materialized under the DB lock so concurrent flush/
        compaction file GC cannot invalidate it (the native engine will use
        refcounted file snapshots instead)."""
        out: List[Tuple[bytes, bytes]] = []
        with self._lock:
            self._check_open()
            runs: List[Iterator] = []
            mems = [self._mem, *self._imms]
            for mem in mems:
                runs.append(iter(list(mem.entries())))
            for name in self._levels[0]:
                runs.append(self._readers[name].iterate())
            for level_files in self._levels[1:]:
                for name in level_files:
                    runs.append(self._readers[name].iterate())
            merge_op = self.options.merge_operator
            merged = heapq.merge(*runs, key=lambda e: (e[0], -e[1]))
            resolved = resolve_stream(merged, merge_op, False)
            # resolve_stream emits one entry per key except for unresolved
            # MERGE chains (no partial-merge operator), which must be folded
            # here as a group — newest first in the stream.
            for key, group in itertools.groupby(resolved, key=lambda e: e[0]):
                entries = list(group)
                if start is not None and key < start:
                    continue
                if end is not None and key >= end:
                    break
                vtype = entries[0][2]
                if vtype == OpType.DELETE:
                    continue
                if vtype == OpType.MERGE:
                    operands = [e[3] for e in reversed(entries)]  # oldest first
                    value = (
                        merge_op.merge(key, None, operands)
                        if merge_op else entries[0][3]
                    )
                else:
                    value = entries[0][3]
                out.append((key, value))
        return iter(out)

    # ------------------------------------------------------------------
    # sequence numbers / replication shipping (db_wrapper.h seam)
    # ------------------------------------------------------------------

    def latest_sequence_number(self) -> int:
        with self._lock:
            return self._last_seq

    def latest_sequence_number_relaxed(self) -> int:
        """Lock-free (possibly slightly stale) seq read for status/
        introspection paths: flush/compaction can hold self._lock for
        seconds, and a status scrape must never hang behind it. The GIL
        makes the bare int read atomic; it simply may miss a write that
        is committing concurrently."""
        return self._last_seq

    def get_updates_since(self, seq: int) -> Iterator[Tuple[int, bytes]]:
        """(start_seq, raw_batch_bytes) for every batch whose start_seq >=
        ``seq``. Followers pass latest_local+1 (replicated_db.cpp:486-505)."""
        return wal_mod.iter_updates(self._wal_dir, seq)

    def oldest_wal_seq(self) -> Optional[int]:
        """First seq the WAL can still serve (None = empty WAL). A
        peer below this cannot WAL-catch-up from us — it must rebuild
        from a snapshot (needRebuildDB's WAL-availability check)."""
        return wal_mod.oldest_seq(self._wal_dir)

    def get_updates_cursor(self, seq: int) -> "wal_mod.WalTailCursor":
        """Resumable tail cursor over the same records as
        ``get_updates_since`` — survives reaching the live tail, so the
        replication serve path can cache it across pulls instead of
        re-scanning the active segment per response."""
        return wal_mod.WalTailCursor(
            self._wal_dir, seq,
            segment_bytes=self.options.wal_segment_bytes)

    # ------------------------------------------------------------------
    # flush / compaction
    # ------------------------------------------------------------------

    def flush(self) -> None:
        """Synchronous flush: on return, everything written before the call
        is durable in SSTs (in background mode this drains the imm slot)."""
        with self._lock:
            self._check_open()
            if self._bg_thread is None:
                self._flush_locked()
            else:
                if len(self._mem):
                    self._swap_to_imm_locked(force=True)
                self._drain_imm_locked()
            persisted = self._persisted_seq
        if self.options.wal_archive_sink is not None:
            # archive + purge OFF the DB lock (the sink is network IO)
            wal_mod.purge_obsolete(
                self._wal_dir, persisted, self.options.wal_ttl_seconds,
                archive_sink=self.options.wal_archive_sink,
            )

    # ------------------------------------------------------------------
    # background thread
    # ------------------------------------------------------------------

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while not self._bg_stop and not self._imms:
                    # every wake source notifies (_swap_to_imm_locked,
                    # close); the long timeout is only a missed-notify
                    # safety net — at 1000+ shards per host, per-DB
                    # 0.2s polling burned a measurable core fraction
                    self._cond.wait(10.0)
                if self._bg_stop and not self._imms:
                    return
                # Take EVERY pending immutable memtable: one SST per
                # burst instead of one per memtable (rocksdb's
                # flush-multiple-memtables behavior) — fewer flushes,
                # fewer/larger L0 files, less compaction pressure, and
                # the queue drains in one pass so stalled writers wake
                # after ONE flush latency however deep the backlog.
                imms = list(self._imms)
            if imms:
                try:
                    self._flush_imms(imms)
                    # drop the last reference so the flushed memtables
                    # free before the next idle wait, not on the next
                    # burst
                    imms = None
                    with self._lock:
                        self._bg_flush_error = None
                        self._bg_flush_failures = 0
                except Exception as e:
                    with self._lock:
                        self._bg_flush_error = e
                        self._bg_flush_failures += 1
                        # wake stalled writers/drainers so they observe the
                        # failure instead of waiting on a drain that won't
                        # happen
                        self._cond.notify_all()
                    log.exception("%s: background flush failed (%d); "
                                  "retrying", self.path,
                                  self._bg_flush_failures)
                    time.sleep(1.0)

    def _pick_compaction_locked(self):
        """The compaction thread's work selector. With the adaptive
        scheduler: rank candidates by pressure (compaction_scheduler.py)
        — re-ranked on every wake, and every flush install/compaction
        install/ingest/set_options notifies the condition, so ranking
        is event-driven rather than a timer scan. Without it: the
        legacy fixed L0-trigger predicate."""
        if self._sched is not None:
            return self._sched.pick_locked()
        from .compaction_scheduler import Pick

        if (not self.options.disable_auto_compaction
                and len(self._levels[0])
                >= self.options.level0_compaction_trigger):
            return Pick("l0", 0, 1.0, "legacy trigger")
        return None

    def schedule_compaction(self):
        """Queue a manual FULL compaction on the scheduler's priority
        queue and return a Future resolved when it completes — the
        post-ingest path (admin BatchCompactor) submits through this so
        its compactions obey the same priority order as background
        picks. Returns None when no adaptive compaction thread is
        running (caller falls back to a direct compact_range)."""
        with self._lock:
            self._check_open()
            if (self._sched is None or self._compaction_thread is None
                    or self._bg_stop):
                return None
            from concurrent.futures import Future

            fut: Future = Future()
            self._sched.submit_manual_locked(fut)
            self._cond.notify_all()
            return fut

    def _compaction_loop(self) -> None:
        from ..utils.stats import tagged

        while True:
            with self._lock:
                pick = None
                while not self._bg_stop:
                    pick = self._pick_compaction_locked()
                    if pick is not None:
                        break
                    # wake sources all notify: flush install, compaction
                    # install, ingest, manual submission, close, and
                    # set_options (the ranking reads MUTABLE options)
                    self._cond.wait(10.0)
                if self._bg_stop:
                    if self._sched is not None:
                        self._sched.fail_pending_locked(
                            StorageError("db closing"))
                    return
                if self._sched is not None:
                    self._sched.note_picked_locked()
            manual_futs = []
            try:
                if self._sched is not None:
                    # before dequeuing manual futures: a fault injected
                    # at the pick seam is retried by this loop (registry
                    # contract), so it must not permanently fail waiters
                    # whose compaction was never attempted
                    fp.hit("compact.pick")
                    Stats.get().incr(
                        tagged("compaction.sched_picks", kind=pick.kind))
                if pick.kind == "manual":
                    with self._lock:
                        manual_futs = self._sched.take_manual_locked()
                    # one full compaction satisfies every queued waiter
                    # (the same coalescing as BatchCompactor's dedupe)
                    self.compact_range()
                    for f in manual_futs:
                        if not f.done():
                            f.set_result(None)
                else:
                    # round 18: offer non-manual picks to the
                    # disaggregated worker tier first. "installed" — the
                    # pick is satisfied remotely; "fenced" — this leader
                    # was deposed mid-job, so neither the remote result
                    # nor a local merge may run (surfaced as a bg error,
                    # same backoff as any failed compaction); "declined"
                    # — the unchanged local path below is the fallback.
                    handled = "declined"
                    if self._remote_compactor is not None:
                        handled = self._remote_compactor.maybe_offload(pick)
                    if handled == "fenced":
                        raise StorageError(
                            "remote compaction fenced: leader epoch "
                            "stale — refusing local fallback")
                    if handled != "installed":
                        if pick.kind == "level":
                            self._compact_level_bg(pick.level)
                        else:
                            self._compact_level0_bg()
                with self._lock:
                    self._bg_compaction_error = None
                    self._bg_compaction_failures = 0
            except Exception as e:
                for f in manual_futs:
                    if not f.done():
                        f.set_exception(e)
                with self._lock:
                    self._bg_compaction_error = e
                    self._bg_compaction_failures += 1
                    # wake writers parked on the L0 stop trigger so they
                    # observe the failure instead of waiting on a drain
                    # that won't happen
                    self._cond.notify_all()
                log.exception("%s: background compaction failed (%d)",
                              self.path, self._bg_compaction_failures)
                time.sleep(1.0)

    def _write_mem_sst(self, path: str, mem: MemTable) -> None:
        """Write a memtable's entries as one SST. Fixed-width workloads
        take the ARRAY drain path (lanes collected as byte joins, one
        lexsort over key words with seq-desc tiebreak, planar sink with
        bulk bloom — no per-entry Python and array-decodable for the
        first-level compaction); anything else falls back cleanly to the
        per-entry SSTWriter sink."""
        if self._try_array_flush(path, mem):
            return
        writer = SSTWriter(
            path,
            self.options.block_bytes,
            self.options.compression,
            self.options.bits_per_key,
        )
        try:
            for key, seq, vtype, value in mem.entries():
                writer.add(key, seq, vtype, value)
            writer.finish()
        except BaseException:
            writer.abandon()
            raise

    def _try_array_flush(self, path: str, mem) -> bool:
        """True when the vectorized drain→lexsort→planar pipeline handled
        the flush. ``mem`` is a MemTable or _MergedMemView; both expose
        drain_lanes() (width checks bail inline, before any large buffer
        — the round-2 lesson: one oversized value among a million small
        ones must not cost a giant transient allocation)."""
        import numpy as np

        from ..tpu.format import planar_stride, planar_widths, \
            write_sst_from_arrays
        from .bloom import BloomFilter

        with start_span("flush.drain"):
            drained = mem.drain_lanes()
        if drained is None:
            return False
        lanes, key_mat = drained
        n = key_mat.shape[0]
        widths = planar_widths(lanes, n)
        if widths is None:
            return False  # cross-memtable width mismatch
        klen, vlen = widths
        with start_span("flush.sort", entries=n):
            # np.lexsort: last column has highest priority → key words
            # ascending (uniform klen ⇒ BE word order == byte order),
            # inverted seq as the descending tiebreak
            seq = (
                lanes["seq_hi"].astype(np.uint64) << np.uint64(32)
            ) | lanes["seq_lo"].astype(np.uint64)
            kw = lanes["key_words_be"]
            kwc = (klen + 3) // 4
            order = np.lexsort(
                (~seq,) + tuple(kw[:, w] for w in range(kwc - 1, -1, -1)))
            if not np.array_equal(order, np.arange(n)):
                lanes = {f: a[order] for f, a in lanes.items()}
        with start_span("flush.encode", entries=n):
            # bulk bloom (order-independent — built from the pre-sort key
            # matrix) instead of a per-key Python loop
            bloom = BloomFilter.build_from_arrays(
                key_mat, np.full(n, klen, dtype=np.uint64),
                self.options.bits_per_key,
            )
            stride = planar_stride(klen, vlen)
            props = write_sst_from_arrays(
                lanes, n, path,
                bloom_words=bloom.words,
                block_entries=max(64, self.options.block_bytes // stride),
                compression=self.options.compression,
                bits_per_key=self.options.bits_per_key,
                planar=True,
            )
        return props is not None

    def _flush_imms(self, imms: List[MemTable]) -> None:
        """Write the pending immutable memtables (oldest first) as ONE
        L0 SST — ALL file IO outside the lock (writes keep flowing): the
        SST write, the reader open (footer+index read), and the manifest
        fsyncs. Only the in-memory installation runs under the lock.
        Crash between install and the manifest write is covered by the
        WAL (purged strictly after the manifest is durable)."""
        with self._lock:
            name = self._new_file_name()
        path = os.path.join(self.path, name)
        source = imms[0] if len(imms) == 1 else _MergedMemView(imms)
        flushed_bytes = sum(m.approximate_bytes() for m in imms)
        # Always-sampled flush trace: the sst-write vs install vs purge
        # split is what write-stall attribution needs (BASELINE p99 <10 ms
        # under compaction storm). ONE span with phase annotations, not
        # child spans: under a storm the flusher is the writers' critical
        # path, and per-flush overhead amplifies through the GIL on small
        # hosts — phase timings are raw perf_counter deltas instead.
        with start_span("storage.flush", always=True, memtables=len(imms),
                        bytes=flushed_bytes) as fsp:
            t0 = time.monotonic()
            self._write_mem_sst(path, source)
            flush_sec = max(time.monotonic() - t0, 1e-6)
            reader = SSTReader(path)
            max_seq = source.max_seq
            t1 = time.monotonic()
            with self._lock:
                rate = flushed_bytes / flush_sec
                self._flush_rate_ewma = (
                    rate if self._flush_rate_ewma == 0.0
                    else 0.5 * self._flush_rate_ewma + 0.5 * rate
                )
                self._readers[name] = reader
                self._levels[0].append(name)
                self._bytes_flushed_total += reader.file_size
                self._persisted_seq = max(self._persisted_seq, max_seq)
                snapshot = self._manifest_snapshot_locked()
                for m in imms:
                    if self._imms and self._imms[0] is m:
                        self._imms.pop(0)
                self._cond.notify_all()
            self._write_manifest_payload(*snapshot)
            t2 = time.monotonic()
            wal_mod.purge_obsolete(
                self._wal_dir, self._persisted_seq,
                self.options.wal_ttl_seconds,
                archive_sink=self.options.wal_archive_sink,
            )
            if fsp.sampled:
                t3 = time.monotonic()
                fsp.annotate(
                    seq=max_seq,
                    sst_write_ms=round(flush_sec * 1e3, 3),
                    install_ms=round((t2 - t1) * 1e3, 3),
                    wal_purge_ms=round((t3 - t2) * 1e3, 3),
                )

    def _note_compacted_locked(self, out_names: List[str],
                               remote: bool = False) -> None:
        """Write-amp accounting at a compaction install sink: bytes
        WRITTEN by the compaction (its outputs). Caller holds self._lock
        and has already registered readers for ``out_names``. ``remote``
        marks bytes a disaggregated worker produced, which count toward
        write-amp (the generation exists either way) but not toward the
        serving node's local compaction output gauge."""
        out_bytes = sum(
            self._readers[n].file_size for n in out_names
            if n in self._readers)
        self._bytes_compacted_total += out_bytes
        if remote:
            self._remote_offloaded_bytes_total += out_bytes

    def _compact_level0_bg(self) -> None:
        """L0→L1 compaction with the merge OUTSIDE the DB lock. Safe
        because compactions (the only file removers) are serialized by
        _compaction_mutex and flushes only add files."""
        # Always-sampled compaction trace: plan → merge (kernel or heap) →
        # install → gc, the RESYSTANCE-style per-phase view of where a
        # compaction's seconds go. Child spans are fine here: compactions
        # are long relative to span cost (unlike the flush hot path).
        with self._compaction_mutex, \
                start_span("storage.compaction", always=True) as csp:
            with start_span("compaction.plan"):
                with self._lock:
                    if self._closed:
                        return
                    inputs_l0 = list(self._levels[0])
                    inputs_l1 = list(self._levels[1])
                    inputs = inputs_l0 + inputs_l1
                    if not inputs:
                        return
                    drop = (
                        all(not files for files in self._levels[2:])
                        and not self.options.allow_ingest_behind
                    )
                    runs = [self._readers[n] for n in inputs]
            csp.annotate(inputs=len(inputs), backend=self._backend.name)
            with start_span("compaction.merge"):
                out_names = self._write_merged(runs, drop_tombstones=drop)
            csp.annotate(outputs=len(out_names))
            with start_span("compaction.install"):
                # crash-at-install atomicity: a fault here (before any
                # in-memory mutation or manifest write) leaves the DB
                # exactly pre-compaction — outputs are swept, inputs
                # stay live (tested by the subcompaction crash matrix)
                try:
                    fp.hit("compact.install")
                except BaseException:
                    self._discard_outputs(out_names)
                    raise
                with self._lock:
                    if self._closed:
                        return
                    # newer L0 files may have arrived during the merge —
                    # keep them
                    self._levels[0] = [
                        n for n in self._levels[0] if n not in inputs_l0
                    ]
                    self._levels[1] = out_names
                    self._note_compacted_locked(out_names)
                    self._fences.clear()
                    snapshot = self._manifest_snapshot_locked()
                    dead = [(n, self._readers.pop(n, None)) for n in inputs]
                    # L0 just shrank: wake writers parked on the stop
                    # trigger
                    self._cond.notify_all()
                # Durable manifest first, THEN delete the files it stopped
                # referencing — all outside self._lock (the fsyncs + a few
                # hundred unlinks under the lock were a write-stall tail).
                self._write_manifest_payload(*snapshot)
            with start_span("compaction.gc", files=len(dead)):
                self._remove_dead_files(dead)

    def _compact_level_bg(self, level: int) -> None:
        """Debt-driven level→level+1 compaction (scheduler "level"
        pick): merge all of ``level`` with the OVERLAPPING files of
        ``level+1``, install into ``level+1``. Same off-lock merge and
        manifest-before-GC ordering as the L0 path; safe because
        compactions are serialized by _compaction_mutex and nothing
        else adds files to levels >= 1."""
        with self._compaction_mutex, \
                start_span("storage.compaction", always=True) as csp:
            with start_span("compaction.plan"):
                with self._lock:
                    if self._closed:
                        return
                    top = len(self._levels) - 1
                    if self.options.allow_ingest_behind:
                        # the true bottom level is reserved for
                        # ingested-behind files (compact_range makes the
                        # same reservation) — never install into it
                        top -= 1
                    if not (1 <= level < top):
                        return
                    inputs_src = list(self._levels[level])
                    if not inputs_src:
                        return
                    # overlap against the source files' overall range
                    lo = hi = None
                    for n in inputs_src:
                        r = self._readers[n]
                        mn, mx = r.min_key(), r.max_key()
                        if mn is None:
                            continue
                        lo = mn if lo is None else min(lo, mn)
                        hi = mx if hi is None else max(hi, mx)
                    inputs_dst = []
                    for n in self._levels[level + 1]:
                        r = self._readers[n]
                        mn, mx = r.min_key(), r.max_key()
                        if mn is None or lo is None or (
                                mx >= lo and mn <= hi):
                            inputs_dst.append(n)
                    inputs = inputs_src + inputs_dst
                    # tombstones survive unless level+1 is the deepest
                    # data-bearing level (same rule as the L0 path)
                    drop = (
                        all(not self._levels[i]
                            for i in range(level + 2, len(self._levels)))
                        and not self.options.allow_ingest_behind
                    )
                    runs = [self._readers[n] for n in inputs]
            csp.annotate(inputs=len(inputs), backend=self._backend.name,
                         level=level)
            with start_span("compaction.merge"):
                out_names = self._write_merged(runs, drop_tombstones=drop)
            csp.annotate(outputs=len(out_names))
            with start_span("compaction.install"):
                try:
                    fp.hit("compact.install")
                except BaseException:
                    self._discard_outputs(out_names)
                    raise
                with self._lock:
                    if self._closed:
                        return
                    src_set = set(inputs_src)
                    dst_set = set(inputs_dst)
                    self._levels[level] = [
                        n for n in self._levels[level] if n not in src_set]
                    self._levels[level + 1] = [
                        n for n in self._levels[level + 1]
                        if n not in dst_set
                    ] + out_names
                    self._note_compacted_locked(out_names)
                    self._fences.clear()
                    snapshot = self._manifest_snapshot_locked()
                    dead = [(n, self._readers.pop(n, None)) for n in inputs]
                    self._cond.notify_all()
                self._write_manifest_payload(*snapshot)
            with start_span("compaction.gc", files=len(dead)):
                self._remove_dead_files(dead)

    def _flush_locked(self, defer_manifest: bool = False) -> None:
        """``defer_manifest=True`` (ingest_external_file's internal flush
        only) skips the manifest persist + WAL purge + compaction-trigger
        tail: the caller persists a manifest that covers this flush
        moments later, halving the flush's fsync bill. Crash-safe — until
        that manifest lands the flushed SST is an orphan file and the WAL
        still holds every entry, so recovery replays as if the flush
        never happened."""
        if self._imms:
            # callers must drain first (would flush out of queue order and
            # inflate persisted_seq past unflushed sequence numbers)
            raise StorageError("flush with immutable memtables pending")
        if len(self._mem) == 0:
            return
        mem = self._mem
        self._imms.append(mem)
        self._mem = MemTable()
        try:
            name = self._new_file_name()
            self._write_mem_sst(os.path.join(self.path, name), mem)
            self._readers[name] = SSTReader(os.path.join(self.path, name))
            self._levels[0].append(name)
            self._bytes_flushed_total += self._readers[name].file_size
            self._persisted_seq = max(self._persisted_seq, mem.max_seq)
            if not defer_manifest:
                self._persist_manifest()
        except BaseException:
            # Keep read-your-writes: fold the unflushed entries back under
            # any writes that raced in. (Both sinks abandon their partial
            # file on failure.)
            self._mem.absorb_older(mem)
            raise
        finally:
            if mem in self._imms:
                self._imms.remove(mem)
        if defer_manifest:
            return
        if self.options.wal_archive_sink is None:
            # cheap unlink-only purge. With an archive sink the purge
            # does network IO and _flush_locked runs UNDER the DB lock —
            # the off-lock purgers (_flush_imms in bg mode, flush() after
            # it releases the lock) handle archival instead.
            wal_mod.purge_obsolete(
                self._wal_dir, self._persisted_seq,
                self.options.wal_ttl_seconds,
            )
        if (
            self._bg_thread is None  # bg mode compacts on its own thread
            and not self.options.disable_auto_compaction
            and len(self._levels[0]) >= self.options.level0_compaction_trigger
        ):
            self._compact_level0_locked()

    def _new_file_name(self) -> str:
        # self-locking (RLock): callers run both inside and outside the
        # DB lock (background merges allocate names off-lock)
        with self._lock:
            name = f"sst-{self._incarnation}-{self._next_file_id:08d}.tsst"
            self._next_file_id += 1
            return name

    def compact_range(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> None:
        """Full compaction: merge everything into the bottom level (the
        reference's CompactRange(full) after ingest, admin_handler.cpp:1845).
        ``start``/``end`` accepted for API parity; the merge is whole-range.
        The merge itself runs OUTSIDE the DB lock (writes keep flowing);
        _compaction_mutex serializes against background compaction."""
        self.flush()
        with self._compaction_mutex, \
                start_span("storage.compact_range", always=True) as csp:
            with start_span("compaction.plan"):
                with self._lock:
                    self._check_open()
                    # allow_ingest_behind reserves the true bottom level for
                    # ingested-behind data (RocksDB does the same), so full
                    # compaction targets num_levels-2 there.
                    bottom = self.options.num_levels - 1
                    if self.options.allow_ingest_behind:
                        bottom -= 1
                    inputs: List[str] = [
                        n for files in self._levels for n in files
                    ]
                    if not inputs:
                        return
                    runs = [self._readers[n] for n in inputs]
            csp.annotate(inputs=len(inputs), backend=self._backend.name)
            # Tombstones must survive when data can later be ingested BEHIND
            # this level — dropping them would resurrect deleted keys.
            with start_span("compaction.merge"):
                out_names = self._write_merged(
                    runs,
                    drop_tombstones=not self.options.allow_ingest_behind,
                )
            csp.annotate(outputs=len(out_names))
            with start_span("compaction.install"):
                try:
                    fp.hit("compact.install")
                except BaseException:
                    self._discard_outputs(out_names)
                    raise
                with self._lock:
                    self._check_open()
                    input_set = set(inputs)
                    # new L0 flushes may have landed during the merge: keep
                    # them
                    for files in self._levels:
                        files[:] = [n for n in files if n not in input_set]
                    self._levels[bottom] = out_names + self._levels[bottom]
                    self._note_compacted_locked(out_names)
                    self._fences.clear()
                    # Manifest first, THEN delete inputs — a crash in
                    # between leaves orphan files (harmless), never a
                    # manifest pointing at deleted ones (unopenable DB).
                    self._persist_manifest()
                    self._gc_files(inputs)
                    # L0 drained: re-rank the scheduler / wake stalled
                    # writers parked on the stop trigger
                    self._cond.notify_all()

    def _compact_level0_locked(self) -> None:
        """L0 → L1 compaction (tombstones kept; not bottom level).
        Runs UNDER the DB lock (inline mode), so subcompactions are
        forced off: a slice worker allocating an output name would
        block on the lock this thread holds — and with writers parked
        on the same lock there is no latency to win anyway."""
        inputs = list(self._levels[0]) + list(self._levels[1])
        if not inputs:
            return
        runs = [self._readers[n] for n in inputs]
        drop = (
            all(not files for files in self._levels[2:])
            and not self.options.allow_ingest_behind
        )
        out_names = self._write_merged(runs, drop_tombstones=drop,
                                       subcompactions=1)
        self._levels[0] = []
        self._levels[1] = out_names
        self._note_compacted_locked(out_names)
        self._fences.clear()
        self._persist_manifest()  # before GC — see compact_range
        self._gc_files(inputs)

    def _effective_subcompactions(self) -> int:
        """max_subcompactions with 0 = auto (min(4, cores))."""
        n = self.options.max_subcompactions
        if n <= 0:
            n = min(4, os.cpu_count() or 1)
        return max(1, n)

    @staticmethod
    def _retain_filter(stream, lo: Optional[bytes], hi: Optional[bytes]):
        """Drop entries whose user key falls outside [lo, hi) — the
        split-child garbage trim. The reserved internal namespace
        (leading NUL: CDC watermarks + applies counters) is ALWAYS
        retained: that state belongs to the db, not to the key range it
        serves, and must survive the trim."""
        for entry in stream:
            key = entry[0]
            if not key.startswith(b"\x00"):
                if lo is not None and key < lo:
                    continue
                if hi is not None and key >= hi:
                    continue
            yield entry

    def _write_merged(self, runs: List, drop_tombstones: bool,
                      subcompactions: Optional[int] = None) -> List[str]:
        retain = self.options.retain_bounds()
        # Backends with a direct file sink (the TPU pipeline: kernel output
        # arrays → vectorized block assembly + kernel-built bloom) skip the
        # per-entry tuple path entirely, splitting at target_file_bytes.
        # A retain trim forces the tuple path: the direct sinks consume
        # whole runs and have no per-entry seam to drop out-of-range keys
        # at (only split children pay this, and only until their trim-
        # triggering compactions have rewritten the inherited files).
        direct = getattr(self._backend, "merge_runs_to_files", None)
        if retain is not None:
            direct = None
        if direct is not None:
            # readers are re-iterable; materialize only raw iterables so a
            # failed direct attempt can still fall back to the tuple path
            runs = [
                r if hasattr(r, "iterate") else list(r) for r in runs
            ]
            allocated: List[str] = []

            def path_factory() -> str:
                name = self._new_file_name()
                allocated.append(name)
                return os.path.join(self.path, name)

            # subcompaction + IO-budget + memory-budget plumbing only
            # for backends that declare support (keeps third-party
            # backend signatures unchanged)
            kwargs = {}
            tracker = None
            if getattr(self._backend, "supports_subcompactions", False):
                kwargs["max_subcompactions"] = (
                    subcompactions if subcompactions is not None
                    else self._effective_subcompactions())
                kwargs["io_budget"] = self._io_budget
            if getattr(self._backend, "supports_memory_budget", False):
                from .stream_merge import CompactionMemoryBudget

                tracker = CompactionMemoryBudget.get().tracker()
                kwargs["mem_tracker"] = tracker
                kwargs["memory_budget_bytes"] = (
                    self.options.compaction_memory_budget_bytes)
            try:
                outputs = direct(
                    runs, self.options.merge_operator, drop_tombstones,
                    path_factory, self.options.block_bytes,
                    self.options.compression, self.options.bits_per_key,
                    self.options.target_file_bytes, **kwargs,
                )
            except Exception:
                log.exception("direct merge sink failed; using tuple path")
                outputs = None
            finally:
                if tracker is not None:
                    tracker.close()
                    if tracker.peak:
                        # the peak_bytes_materialized gauge: high-water
                        # of live lane bytes during this compaction
                        self._compaction_peak_bytes = tracker.peak
            if outputs is not None:
                names: List[str] = []
                for path, _props in outputs:
                    name = os.path.basename(path)
                    self._readers[name] = SSTReader(path)
                    names.append(name)
                return names
        streams = [r.iterate() if hasattr(r, "iterate") else r for r in runs]
        stream = self._backend.merge_runs(
            streams, self.options.merge_operator, drop_tombstones
        )
        if retain is not None:
            stream = self._retain_filter(stream, *retain)
            Stats.get().incr("compaction.retain_trims")
        return self._write_entry_stream(stream, io_budget=self._io_budget)

    def _write_entry_stream(self, stream, io_budget=None) -> List[str]:
        """Write an already-merged (key asc, seq desc) entry stream into
        output SSTs, splitting at target_file_bytes. Shared by the tuple
        merge path and the cross-db batched-compaction install.
        ``io_budget`` (compaction callers only) throttles after each
        finished output file so background IO yields to foreground
        fsyncs."""
        out_names: List[str] = []
        writer: Optional[SSTWriter] = None
        written = 0
        for key, seq, vtype, value in stream:
            if writer is None:
                name = self._new_file_name()
                out_names.append(name)
                writer = SSTWriter(
                    os.path.join(self.path, name),
                    self.options.block_bytes,
                    self.options.compression,
                    self.options.bits_per_key,
                )
                written = 0
            writer.add(key, seq, vtype, value)
            written += len(key) + len(value)
            if written >= self.options.target_file_bytes:
                writer.finish()
                writer = None
                if io_budget is not None:
                    io_budget.throttle(written)
        if writer is not None:
            writer.finish()
            if io_budget is not None:
                io_budget.throttle(written)
        for name in out_names:
            self._readers[name] = SSTReader(os.path.join(self.path, name))
        return out_names

    # ------------------------------------------------------------------
    # batched full compaction (plan / install seam)
    # ------------------------------------------------------------------
    #
    # compact_range does plan → merge → install in one call, holding the
    # compaction mutex throughout. The cross-shard batched post-load
    # compaction (tpu/compaction_service.compact_dbs_batched) needs the
    # MERGE stage lifted out so many DBs' merges run in one padded device
    # call; these three methods expose exactly the plan/install halves
    # with the same locking discipline. A plan holds this DB's compaction
    # mutex until exactly one of install_full_compaction /
    # abort_full_compaction consumes it.

    def plan_full_compaction(self) -> Optional[dict]:
        """Flush, then snapshot a full-compaction plan (inputs + readers +
        target level). Returns None — and retains nothing — when there is
        nothing to compact. On a non-None return the caller OWNS the
        compaction mutex via the plan."""
        self.flush()
        self._compaction_mutex.acquire()
        try:
            with self._lock:
                self._check_open()
                bottom = self.options.num_levels - 1
                if self.options.allow_ingest_behind:
                    bottom -= 1
                inputs: List[str] = [
                    n for files in self._levels for n in files
                ]
                if not inputs:
                    self._compaction_mutex.release()
                    return None
                runs = [self._readers[n] for n in inputs]
            return {
                "inputs": inputs,
                "runs": runs,
                "bottom": bottom,
                "drop_tombstones": not self.options.allow_ingest_behind,
            }
        except BaseException:
            self._compaction_mutex.release()
            raise

    def snapshot_full_compaction(self) -> Optional[dict]:
        """Mutex-FREE sibling of :meth:`plan_full_compaction` for the
        disaggregated tier (round 19): flush, then snapshot the live
        input set WITHOUT taking the compaction mutex, so local L0
        picks and manual compact_range keep running while a worker
        merges off-node. The snapshot is only a CANDIDATE — before
        installing, the caller must win the mutex and revalidate via
        :meth:`begin_full_install`; a concurrent local compaction may
        have consumed (and GC'd) any of these inputs, in which case the
        remote result is discarded and the local outcome stands."""
        self.flush()
        with self._lock:
            self._check_open()
            bottom = self.options.num_levels - 1
            if self.options.allow_ingest_behind:
                bottom -= 1
            inputs: List[str] = [
                n for files in self._levels for n in files
            ]
            if not inputs:
                return None
            runs = [self._readers[n] for n in inputs]
        return {
            "inputs": inputs,
            "runs": runs,
            "bottom": bottom,
            "drop_tombstones": not self.options.allow_ingest_behind,
            "snapshot": True,
        }

    def begin_full_install(self, plan: dict) -> bool:
        """Win the compaction mutex for a SNAPSHOT plan's install and
        revalidate every input is still live (no local compaction
        consumed one while the remote merge ran). True: the caller now
        owns the mutex exactly as after :meth:`plan_full_compaction` —
        exactly one of install_full_compaction / abort_full_compaction
        must consume it. False: the snapshot is stale and NOTHING is
        held — the caller discards the remote outputs."""
        self._compaction_mutex.acquire()
        try:
            with self._lock:
                self._check_open()
                live = {n for files in self._levels for n in files}
                if not set(plan["inputs"]) <= live:
                    self._compaction_mutex.release()
                    return False
            return True
        except BaseException:
            self._compaction_mutex.release()
            raise

    def allocate_sst(self) -> Tuple[str, str]:
        """Reserve an SST file name for an external compaction sink;
        returns (name, absolute path). The file only becomes live when a
        later install names it (orphaned allocations are harmless)."""
        name = self._new_file_name()
        return name, os.path.join(self.path, name)

    def install_full_compaction(self, plan: dict, entries=None,
                                files: Optional[List[str]] = None,
                                arrays: Optional[Tuple[dict, int]] = None,
                                remote: bool = False,
                                ) -> None:
        """Swap in a plan's externally-merged outputs (manifest first,
        then input GC — the compact_range crash-safety order). Outputs
        come as merged ``entries`` tuples written here, as ``files``:
        names from :meth:`allocate_sst` whose SSTs the caller already
        wrote durably (the array-native batched sink), or as ``arrays``:
        a resolved ``(lanes, count)`` pair written here through the
        vectorized PLANAR sink with bulk blooms — no per-entry Python.
        An ``arrays`` install the planar layout can't express raises
        InvalidArgument (callers with mixed-width results unpack to
        ``entries`` instead). Consumes the plan's mutex."""
        try:
            fp.hit("compact.install")
            if files is not None:
                out_names = list(files)
                for name in out_names:
                    self._readers[name] = SSTReader(
                        os.path.join(self.path, name))
            elif arrays is not None:
                out_names = self._write_resolved_arrays(*arrays)
                if out_names is None:
                    raise InvalidArgument(
                        "install_full_compaction: arrays not planar-"
                        "expressible (non-uniform widths) — unpack to "
                        "entries for the tuple sink")
            else:
                out_names = self._write_entry_stream(
                    iter(entries), io_budget=self._io_budget)
            with self._lock:
                self._check_open()
                input_set = set(plan["inputs"])
                # L0 flushes that landed during the external merge stay
                for level_files in self._levels:
                    level_files[:] = [
                        n for n in level_files if n not in input_set]
                bottom = plan["bottom"]
                self._levels[bottom] = out_names + self._levels[bottom]
                self._note_compacted_locked(out_names, remote=remote)
                self._fences.clear()
                self._persist_manifest()
                self._gc_files(plan["inputs"])
        finally:
            self._compaction_mutex.release()

    def _write_resolved_arrays(self, lanes: dict,
                               count: int) -> Optional[List[str]]:
        """Write already-resolved lane arrays as PLANAR SSTs (split at
        target_file_bytes, bulk blooms) and register readers — the
        array-native install sink shared with the compaction backends.
        None when the planar layout can't express the rows."""
        from .native_compaction import write_resolved_lanes

        if count == 0:
            return []
        outputs = write_resolved_lanes(
            lanes, count, self.allocate_sst_path,
            self.options.block_bytes, self.options.compression,
            self.options.bits_per_key, self.options.target_file_bytes,
            io_budget=self._io_budget,
        )
        if outputs is None:
            return None
        names: List[str] = []
        for path, _props in outputs:
            name = os.path.basename(path)
            self._readers[name] = SSTReader(path)
            names.append(name)
        return names

    def allocate_sst_path(self) -> str:
        """path_factory form of :meth:`allocate_sst` (the array sinks
        take a zero-arg callable returning an absolute path)."""
        return self.allocate_sst()[1]

    def abort_full_compaction(self, plan: dict) -> None:
        """Release a plan without installing (external merge declined or
        failed); the DB is untouched and compact_range remains safe."""
        self._compaction_mutex.release()

    def set_remote_compactor(self, manager) -> None:
        """Attach (or detach with None) a disaggregated-compaction
        manager (compaction_remote.RemoteCompactionManager). Non-manual
        background picks then publish to the worker tier before falling
        back to the local merge — see _compaction_loop."""
        with self._lock:
            self._remote_compactor = manager

    def _remove_dead_files(
        self, dead: List[Tuple[str, Optional[SSTReader]]]
    ) -> None:
        """Close + unlink files already dropped from self._readers. Needs
        no lock — callers pop the readers under self._lock first."""
        for name, reader in dead:
            if reader is not None:
                reader.close()
            try:
                os.remove(os.path.join(self.path, name))
            except OSError:
                pass

    def _gc_files(self, names: List[str]) -> None:
        self._remove_dead_files(
            [(name, self._readers.pop(name, None)) for name in names])

    def _discard_outputs(self, out_names: List[str]) -> None:
        """Sweep never-installed compaction outputs after an install-
        phase fault: close + drop their readers and unlink the files
        (nothing references them — the manifest was never written)."""
        with self._lock:
            dead = [(n, self._readers.pop(n, None)) for n in out_names]
        self._remove_dead_files(dead)

    # ------------------------------------------------------------------
    # properties (application_db.cpp:183-225)
    # ------------------------------------------------------------------

    def get_property(self, name: str) -> Optional[str]:
        # accept rocksdb's property namespace ("rocksdb.num-files-at-
        # level0") so reference callers port unchanged
        if name.startswith("rocksdb."):
            name = name[len("rocksdb."):]
        with self._lock:
            if name == "num-levels":
                return str(self.options.num_levels)
            if name == "highest-empty-level":
                # Highest (deepest) level index that is empty along with all
                # levels above... reference semantics: the highest level L
                # such that levels L..Lmax hold no files ⇒ safe ingest-behind.
                highest = -1
                for i in range(self.options.num_levels - 1, -1, -1):
                    if not self._levels[i]:
                        highest = i
                    else:
                        break
                return str(highest)
            if name.startswith("num-files-at-level"):
                level = int(name[len("num-files-at-level"):])
                if 0 <= level < len(self._levels):
                    return str(len(self._levels[level]))
                return "0"
            if name == "estimate-num-keys":
                total = len(self._mem) + sum(
                    r.props.get("num_keys", 0) for r in self._readers.values()
                )
                return str(total)
            if name == "total-sst-bytes":
                total = 0
                for files in self._levels:
                    for n in files:
                        try:
                            total += os.path.getsize(os.path.join(self.path, n))
                        except OSError:
                            pass
                return str(total)
            return None

    def approximate_disk_size(self) -> int:
        return int(self.get_property("total-sst-bytes") or 0)

    # ------------------------------------------------------------------
    # introspection gauges (round 14: the observability plane's inputs)
    # ------------------------------------------------------------------

    def metrics_snapshot(self, max_age: float = 0.5) -> Dict:
        """One consistent cut of the engine's pull-model gauge inputs,
        computed in ONE pass under the DB lock (file sizes are cached on
        the readers — no filesystem IO under the lock) and cached for
        ``max_age`` seconds so a /metrics dump evaluating a dozen per-db
        gauges pays one lock pass, not one per gauge. These are the
        foreground-pressure signals the workload-adaptive compaction
        scheduler and the per-shard rebalancer consume (ROADMAP)."""
        now = time.monotonic()
        cached_at, cached = self._metrics_cache
        if cached is not None and now - cached_at < max_age:
            return cached
        opts = self.options
        with self._lock:
            if self._closed:
                return cached or {}
            level_files = [len(files) for files in self._levels]
            level_bytes = [
                sum(self._readers[n].file_size for n in files
                    if n in self._readers)
                for files in self._levels
            ]
            # compaction debt: bytes above each level's target. L0's
            # target is the compaction trigger expressed in bytes (files
            # beyond the trigger, at the level's mean file size); deeper
            # levels use the rocksdb-style base * multiplier^(L-1).
            debt = [0] * len(self._levels)
            if level_files[0] > opts.level0_compaction_trigger:
                mean = level_bytes[0] / max(1, level_files[0])
                debt[0] = int(
                    (level_files[0] - opts.level0_compaction_trigger) * mean)
            target = opts.max_bytes_for_level_base
            for lvl in range(1, len(self._levels)):
                debt[lvl] = max(0, level_bytes[lvl] - target)
                target *= opts.max_bytes_for_level_multiplier
            mem_bytes = self._mem.approximate_bytes() + sum(
                m.approximate_bytes() for m in self._imms)
            unflushed_seqs = max(0, self._last_seq - self._persisted_seq)
            gets = self._gets_total
            consulted = self._files_consulted_total
            flushed = self._bytes_flushed_total
            compacted = self._bytes_compacted_total
            remote_offloaded = self._remote_offloaded_bytes_total
            compaction_peak = self._compaction_peak_bytes
        # WAL backlog sized OUTSIDE the lock (directory listing is IO);
        # the segment set is append/purge-only so a racing purge at
        # worst under-counts one segment
        wal_bytes = 0
        try:
            with os.scandir(self._wal_dir) as it:
                for entry in it:
                    try:
                        wal_bytes += entry.stat().st_size
                    except OSError:
                        continue
        except OSError:
            pass
        snap = {
            "level_files": level_files,
            "level_bytes": level_bytes,
            "compaction_debt_bytes": debt,
            "memtable_bytes": mem_bytes,
            "wal_backlog_bytes": wal_bytes,
            "unflushed_seqs": unflushed_seqs,
            "read_amp": (consulted / gets) if gets else 0.0,
            "write_amp": (compacted / flushed) if flushed else 0.0,
            "gets_total": gets,
            "files_consulted_total": consulted,
            "bytes_flushed_total": flushed,
            "bytes_compacted_total": compacted,
            "bytes_compacted_local_total": compacted - remote_offloaded,
            "remote_offloaded_bytes_total": remote_offloaded,
            "compaction_peak_bytes_materialized": compaction_peak,
        }
        self._metrics_cache = (now, snap)
        return snap

    def set_options(self, updates: Dict[str, object]) -> None:
        """Runtime-mutable options (reference setDBOptions,
        admin_handler.cpp:2134-2158)."""
        from ..utils.flags import _coerce

        with self._lock:
            # validate EVERY key before applying ANY: a partial apply
            # followed by InvalidArgument would mutate predicates the
            # parked background loops never get notified about
            for k in updates:
                if k not in DBOptions.MUTABLE:
                    raise InvalidArgument(f"option not mutable: {k}")
            for k, v in updates.items():
                current = getattr(self.options, k)
                if current is None or v is None:
                    # Optional[str] knobs (retain_lo/retain_hi): no
                    # current type to coerce to; "" clears the bound
                    setattr(self.options, k,
                            None if v in (None, "") else str(v))
                else:
                    # _coerce handles "false"→False etc. (same class of
                    # bug as flags string coercion).
                    setattr(self.options, k, _coerce(v, type(current)))
            if ("compaction_budget_bytes_per_sec" in updates
                    and self._io_budget is not None):
                self._io_budget.set_rate(
                    self.options.compaction_budget_bytes_per_sec)
            # wake the background loops: their wait predicates read
            # mutable options (e.g. disable_auto_compaction toggled off
            # must start the parked compactor now, not on the next write)
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # checkpoint / ingest / destroy
    # ------------------------------------------------------------------

    def checkpoint(self, checkpoint_dir: str) -> int:
        """Consistent on-disk snapshot via hardlinks (rocksdb::Checkpoint).
        Flushes first so the checkpoint is WAL-free, like the reference's
        checkpoint-backup path (admin_handler.cpp:996-1129). Returns the
        sequence number the snapshot actually contains, captured under the
        DB lock — writes landing after this call are not in the snapshot."""
        with start_span("storage.checkpoint") as sp, self._lock:
            self._check_open()
            # drain any in-flight background flush, then flush synchronously
            with start_span("checkpoint.flush"):
                self._drain_imm_locked()
                self._flush_locked()
            if os.path.exists(checkpoint_dir):
                raise InvalidArgument(f"checkpoint dir exists: {checkpoint_dir}")
            os.makedirs(checkpoint_dir)
            nfiles = 0
            with start_span("checkpoint.link"):
                for files in self._levels:
                    for name in files:
                        src = os.path.join(self.path, name)
                        dst = os.path.join(checkpoint_dir, name)
                        try:
                            os.link(src, dst)
                        except OSError:
                            # rstpu-check: allow(blocking-under-lock) cross-device fallback only; the checkpoint's file set + manifest must be one consistent cut under the lock
                            shutil.copyfile(src, dst)
                        nfiles += 1
                self._persist_manifest(target_dir=checkpoint_dir)
            sp.annotate(files=nfiles, seq=self._last_seq)
            return self._last_seq

    def ingest_external_file(
        self,
        sst_paths: List[str],
        move_files: bool = False,
        allow_global_seqno: bool = True,
        ingest_behind: bool = False,
        validated: bool = False,
    ) -> None:
        """IngestExternalFile parity (admin_handler.cpp:1819-1827).

        Normal ingest: file gets global_seqno = last_seq+1 and lands in L0.
        ingest_behind: file lands in the bottom level with global_seqno 0
        (older than everything); requires ``allow_ingest_behind`` and an
        empty bottom level (the DBLmaxEmpty check).

        ``validated=True``: the caller already format/checksum-probed every
        file (the admin handler's pre-lock validate stage) — skip the
        per-file SSTReader probe here so it doesn't run under the DB lock.
        """
        with self._lock:
            self._check_open()
            if ingest_behind:
                if not self.options.allow_ingest_behind:
                    raise InvalidArgument("db not opened with allow_ingest_behind")
                if self._levels[-1]:
                    raise InvalidArgument("bottom level not empty")
            new_names: List[str] = []
            # Both ingest modes rewrite the adopted file's footer in place
            # (global seqno). A multiply-linked source (the object store's
            # zero-copy download path hands out hardlinks to the bucket
            # object) must therefore be adopted by COPY, or the rewrite
            # would mutate the shared inode — i.e. corrupt the bucket.
            will_rewrite = ingest_behind or allow_global_seqno
            try:
                fp.hit("engine.ingest")
                for src in sst_paths:
                    if not validated:
                        probe = SSTReader(src)  # validates format
                        probe.close()
                    name = self._new_file_name()
                    dst = os.path.join(self.path, name)
                    if move_files:
                        if will_rewrite and os.stat(src).st_nlink > 1:
                            # copy-or-fail: a rename fallback would keep
                            # the shared inode and re-open the bucket-
                            # corruption hole this branch exists to close
                            # rstpu-check: allow(blocking-under-lock) rare nlink>1 fallback; admin pre-breaks links outside every lock (handler.validate), so this copy under the db lock is the last-resort safety net
                            shutil.copyfile(src, dst)
                            os.remove(src)
                        else:
                            try:
                                os.link(src, dst)
                                os.remove(src)
                            except OSError:
                                shutil.move(src, dst)
                    else:
                        # rstpu-check: allow(blocking-under-lock) ingest file materialization must be atomic vs readers/seq allocation; per-shard only — the round-7 narrowing keeps other dbs unaffected
                        shutil.copyfile(src, dst)
                    new_names.append(name)
            except (OSError, Corruption) as e:
                self._gc_files(new_names)
                raise StorageError(f"ingest failed: {e}") from e
            if ingest_behind:
                # rstpu-check: allow(blocking-under-lock) footer rewrite+fsync must complete before the file set becomes visible; crash matrix (test_failpoints) pins the pre/post-ingest atomicity this ordering provides
                self._set_global_seqnos(new_names, 0)
                # Bottom level must stay sorted & non-overlapping.
                readers = [self._readers_open(n) for n in new_names]
                readers.sort(key=lambda r: r.min_key() or b"")
                ordered = [os.path.basename(r._path) for r in readers]
                for a, b in zip(readers, readers[1:]):
                    if a.max_key() and b.min_key() and a.max_key() >= b.min_key():
                        self._gc_files(new_names)
                        raise InvalidArgument("ingest_behind files overlap")
                self._levels[-1] = ordered
                self._fences.clear()
            else:
                # The ingested file is newer than everything current, so the
                # memtable — and any in-flight background flush, which would
                # otherwise land in L0 ABOVE the ingested file — must be
                # flushed below it first (RocksDB flushes on overlapping
                # ingest for the same reason). The manifest persist is
                # deferred to THIS method's final persist (one durable
                # manifest write covers flush + ingest), with the WAL purge
                # re-run below once that manifest is down.
                self._drain_imm_locked()
                if len(self._mem):
                    self._flush_locked(defer_manifest=True)
                if allow_global_seqno:
                    self._last_seq += 1
                    # rstpu-check: allow(blocking-under-lock) the global seqno is allocated from _last_seq under the lock and must be durable in the footer before install — releasing mid-rewrite would let a racing write reuse the seq
                    self._set_global_seqnos(new_names, self._last_seq)
                    self._persisted_seq = max(self._persisted_seq, self._last_seq)
                else:
                    for name in new_names:
                        # no footer rewrite on this branch — fsync the
                        # copied pages before the manifest names the file
                        # (ingested data has no WAL to replay)
                        with open(os.path.join(self.path, name), "rb") as f:
                            # rstpu-check: allow(blocking-under-lock) ingested pages must be durable before the manifest names the file (no WAL covers them); ingest is rare and per-shard
                            os.fsync(f.fileno())
                        self._readers_open(name)
                self._levels[0].extend(new_names)
                # the parked compactor's predicate reads len(levels[0])
                self._cond.notify_all()
            self._persist_manifest()
            if not ingest_behind and self.options.wal_archive_sink is None:
                # the deferred flush's purge: only now that the manifest
                # naming the flushed SST is durable is dropping the WAL
                # entries it covers safe
                wal_mod.purge_obsolete(
                    self._wal_dir, self._persisted_seq,
                    self.options.wal_ttl_seconds,
                )

    def _readers_open(self, name: str) -> SSTReader:
        if name not in self._readers:
            self._readers[name] = SSTReader(os.path.join(self.path, name))
        return self._readers[name]

    def _set_global_seqnos(self, names: List[str], seqno: int) -> None:
        """Rewrite the footer global_seqno in place (RocksDB does exactly
        this — a pwrite into the ingested file's seqno slot)."""
        from .sst import _FOOTER, FLAG_HAS_GLOBAL_SEQNO, MAGIC

        for name in names:
            fp.hit("sst.ingest_footer")
            path = os.path.join(self.path, name)
            with open(path, "r+b") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(size - _FOOTER.size)
                fields = list(_FOOTER.unpack(f.read(_FOOTER.size)))
                fields[3] = seqno
                fields[6] |= FLAG_HAS_GLOBAL_SEQNO
                f.seek(size - _FOOTER.size)
                f.write(_FOOTER.pack(*fields))
                # ingested data was never in the WAL: the copy AND this
                # footer rewrite must be durable before the manifest
                # references the file (same invariant as SSTWriter.finish)
                f.flush()
                os.fsync(f.fileno())
            old = self._readers.pop(name, None)
            if old is not None:
                old.close()
            self._readers[name] = SSTReader(path)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def close(self) -> None:
        # Stop the background thread first (it drains a pending imm before
        # exiting), then tear down under the lock.
        with self._lock:
            if self._closed:
                return
            self._bg_stop = True
            self._cond.notify_all()
        if self._bg_thread is not None:
            self._bg_thread.join(timeout=30.0)
            self._bg_thread = None
        if self._compaction_thread is not None:
            self._compaction_thread.join(timeout=60.0)
            self._compaction_thread = None
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
            if self._wal is not None:
                self._wal.close()
            for reader in self._readers.values():
                reader.close()
            self._readers.clear()

    def _check_open(self) -> None:
        if self._closed:
            raise StorageError("db is closed")

    def __enter__(self) -> "DB":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def destroy_db(path: str) -> None:
    """DestroyDB parity (clearDB path, admin_handler.cpp:1774-1817)."""
    if os.path.isdir(path):
        shutil.rmtree(path)


# ---------------------------------------------------------------------------
# pull-model gauge registration (reference stats.h pull gauges)
# ---------------------------------------------------------------------------

# per-level families (tagged db=<name> level=<L>)
DB_LEVEL_GAUGES = (
    "storage.level_files",
    "storage.level_bytes",
    "storage.compaction_debt_bytes",
)
# scalar families (tagged db=<name>)
DB_SCALAR_GAUGES = {
    "storage.memtable_bytes": "memtable_bytes",
    "storage.wal_backlog_bytes": "wal_backlog_bytes",
    "storage.unflushed_seqs": "unflushed_seqs",
    "storage.read_amp": "read_amp",
    "storage.write_amp": "write_amp",
    # high-water of live lane bytes during the most recent compaction
    # merge — the streaming bounded-memory pipeline's load-bearing
    # ceiling proof (stream_merge.CompactionMemoryBudget)
    "compaction.peak_bytes_materialized":
        "compaction_peak_bytes_materialized",
    # disaggregated tier (round 18): the serving-shaped pair — output
    # bytes this node's own compactions wrote vs bytes workers produced.
    # Tier-on acceptance drives local_output_bytes → ~0.
    "compaction.local_output_bytes": "bytes_compacted_local_total",
    "compaction.remote_offloaded_bytes": "remote_offloaded_bytes_total",
}
_LEVEL_GAUGE_KEYS = {
    "storage.level_files": "level_files",
    "storage.level_bytes": "level_bytes",
    "storage.compaction_debt_bytes": "compaction_debt_bytes",
}


def register_db_gauges(name: str, db: DB,
                       stats: Optional[Stats] = None,
                       **extra_tags: str) -> List[str]:
    """Register this shard's engine gauges on the process Stats registry
    (pull-model: each callback reads the db's cached metrics_snapshot).
    ``extra_tags`` (e.g. port=...) disambiguate multi-replicator test
    processes where several engines carry the same shard name. Returns
    the registered gauge names for :func:`unregister_db_gauges`."""
    from ..utils.stats import tagged

    stats = stats or Stats.get()
    names: List[str] = []

    def add(gname: str, cb) -> None:
        stats.add_gauge(gname, cb)
        names.append(gname)

    for family in DB_LEVEL_GAUGES:
        key = _LEVEL_GAUGE_KEYS[family]
        for lvl in range(db.options.num_levels):
            def cb(key=key, lvl=lvl) -> float:
                vals = db.metrics_snapshot().get(key) or []
                return float(vals[lvl]) if lvl < len(vals) else 0.0
            add(tagged(family, db=name, level=str(lvl), **extra_tags), cb)
    for family, key in DB_SCALAR_GAUGES.items():
        def cb(key=key) -> float:
            return float(db.metrics_snapshot().get(key) or 0.0)
        add(tagged(family, db=name, **extra_tags), cb)
    # process-global: registered idempotently alongside any db (the
    # decoded-block cache is process-wide)
    stats.add_gauge("storage.block_cache.hit_rate", _block_cache_hit_rate)
    return names


def unregister_db_gauges(names: List[str],
                         stats: Optional[Stats] = None) -> None:
    stats = stats or Stats.get()
    for gname in names:
        stats.remove_gauge(gname)


def _block_cache_hit_rate() -> float:
    s = Stats.get()
    hits = s.get_counter("storage.block_cache.hit")
    misses = s.get_counter("storage.block_cache.miss")
    total = hits + misses
    return hits / total if total else 0.0
