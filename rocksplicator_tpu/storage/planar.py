"""PLANAR (struct-of-arrays) TSST block codec — host side.

The round-2 device profiling (PERF.md) showed minor-dim byte interleaving
is the most expensive thing a TPU can do with kernel output, while the
kernel's struct-of-array u32 lanes ARE already the data. The planar block
format therefore writes each data block as u32 *planes* in lane order —
on-device "encoding" degenerates to packing one u8 lane (vtype) and
concatenating, files shrink (no per-entry klen/vlen/seq_hi overhead:
41 B/entry → 33 B at 16/8 widths, less with seq32), and block checksums
become pure u32 word math on both sides.

Block layout (all little-endian), after the 16-byte header:

    u32 n_entries | u8 klen | u8 vlen | u8 flags | u8 0 | u64 0
    key planes   ceil(klen/4) × n u32   (big-endian WORD VALUES — the
                                         kernel's key_words_be lanes)
    seq_lo plane n u32
    seq_hi plane n u32                  (omitted when flags & SEQ32)
    vtype plane  ceil(n/4) u32          (4 entries packed per word, LE)
    val planes   ceil(vlen/4) × n u32   (the kernel's val_words lanes)

Entries within a block are key-ascending (same contract as entry-stream
blocks); klen/vlen are uniform per FILE (the vectorized-sink promise).
The codec nibble in the block index distinguishes planar blocks, so one
file could mix encodings; readers dispatch per block. v1 entry-stream
files stay readable unchanged (golden-format compatibility); planar
files are new-format output of the TPU sink.

Reference seam being reproduced: the SST files rocksdb ingests/compacts
(SURVEY §3.3 addS3SstFilesToDB); the planar layout is the TPU-first
re-design of their data blocks.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

# n, klen, vlen_lo, flags, vlen_hi, reserved. vlen is u16 split across
# bytes 5 (lo) and 7 (hi): byte 7 was a reserved zero in the original
# layout, so every previously-written file reads back with vlen_hi == 0 —
# the widening is backward-compatible. klen stays u8 (bounded at 24, the
# TPU key-lane width).
PLANAR_HEADER = struct.Struct("<IBBBBQ")
PLANAR_FLAG_SEQ32 = 1
PLANAR_MAX_KLEN = 24
PLANAR_MAX_VLEN = 0xFFFF


def pack_planar_header(n: int, klen: int, vlen: int, flags: int) -> bytes:
    """The ONLY planar-header packer (every sink goes through here so the
    vlen bound is enforced in one place — the round-2 crash was a sink
    packing vlen straight into a 'B' field)."""
    if not (0 < klen <= PLANAR_MAX_KLEN):
        raise ValueError(f"planar klen out of range: {klen}")
    if not (0 <= vlen <= PLANAR_MAX_VLEN):
        raise ValueError(f"planar vlen out of range: {vlen}")
    return PLANAR_HEADER.pack(n, klen, vlen & 0xFF, flags, vlen >> 8, 0)


def unpack_planar_header(raw: bytes) -> Tuple[int, int, int, int]:
    """(n, klen, vlen, flags) with bounds validation → Corruption."""
    from .errors import Corruption

    if len(raw) < PLANAR_HEADER.size:
        raise Corruption(f"planar block: {len(raw)} bytes < header")
    n, klen, vlen_lo, flags, vlen_hi, _ = PLANAR_HEADER.unpack_from(raw, 0)
    vlen = vlen_lo | (vlen_hi << 8)
    if not (0 < klen <= PLANAR_MAX_KLEN):
        raise Corruption(f"planar block: klen {klen} out of range")
    return n, klen, vlen, flags


def plane_words(n: int, klen: int, vlen: int, seq32: bool) -> int:
    """u32 words of plane data for a planar block of n entries."""
    kw = (klen + 3) // 4
    vw = (vlen + 3) // 4
    return n * (kw + 1 + (0 if seq32 else 1) + vw) + (n + 3) // 4


def pack_vtype_plane(vtype: np.ndarray) -> np.ndarray:
    """(n,) u32 vtype values -> (ceil(n/4),) u32, 4 per word LE."""
    n = len(vtype)
    pad = (-n) % 4
    v = np.pad(vtype.astype(np.uint8), (0, pad))
    return v.view("<u4").copy()


def unpack_vtype_plane(words: np.ndarray, n: int) -> np.ndarray:
    return words.view(np.uint8)[:n].astype(np.uint32)


def encode_planar_block(
    arrays: Dict[str, np.ndarray], start: int, end: int,
    klen: int, vlen: int, seq32: bool,
) -> bytes:
    """Kernel-output lanes [start, end) -> planar block bytes (numpy —
    the host fallback; the device path produces the identical plane words
    via ops/block_encode.encode_planar_words_tpu)."""
    n = end - start
    kw = (klen + 3) // 4
    vw = (vlen + 3) // 4
    parts: List[np.ndarray] = [
        np.ascontiguousarray(
            arrays["key_words_be"][start:end, :kw].T).reshape(-1),
        arrays["seq_lo"][start:end].astype(np.uint32),
    ]
    if not seq32:
        parts.append(arrays["seq_hi"][start:end].astype(np.uint32))
    parts.append(pack_vtype_plane(arrays["vtype"][start:end]))
    if vw:
        parts.append(np.ascontiguousarray(
            arrays["val_words"][start:end, :vw].T).reshape(-1))
    words = np.concatenate(parts).astype("<u4")
    header = pack_planar_header(
        n, klen, vlen, PLANAR_FLAG_SEQ32 if seq32 else 0)
    return header + words.tobytes()


def decode_planar_block(raw: bytes) -> Dict[str, np.ndarray]:
    """Planar block bytes -> lane arrays (pure views/reshapes)."""
    n, klen, vlen, flags = unpack_planar_header(raw)
    seq32 = bool(flags & PLANAR_FLAG_SEQ32)
    kw = (klen + 3) // 4
    vw = (vlen + 3) // 4
    want = PLANAR_HEADER.size + 4 * plane_words(n, klen, vlen, seq32)
    if len(raw) != want:
        from .errors import Corruption

        raise Corruption(
            f"planar block: {len(raw)} bytes, layout wants {want}")
    words = np.frombuffer(raw, dtype="<u4", offset=PLANAR_HEADER.size)
    pos = 0
    kw_lanes = words[pos:pos + kw * n].reshape(kw, n)
    pos += kw * n
    seq_lo = words[pos:pos + n]
    pos += n
    if seq32:
        seq_hi = np.zeros(n, dtype=np.uint32)
    else:
        seq_hi = words[pos:pos + n]
        pos += n
    nv = (n + 3) // 4
    vtype = unpack_vtype_plane(words[pos:pos + nv], n)
    pos += nv
    val_lanes = words[pos:pos + vw * n].reshape(vw, n)

    key_buf = np.zeros((n, 24), dtype=np.uint8)
    kb = np.ascontiguousarray(
        kw_lanes.T.astype(">u4")).view(np.uint8).reshape(n, kw * 4)
    key_buf[:, :klen] = kb[:, :klen]
    vval = max(2, vw)
    val_words = np.zeros((n, vval), dtype=np.uint32)
    if vw:
        val_words[:, :vw] = val_lanes.T
    return {
        "key_words_be": key_buf.view(">u4").astype(np.uint32).reshape(n, 6),
        "key_words_le": key_buf.view("<u4").reshape(n, 6).copy(),
        "key_len": np.full(n, klen, dtype=np.uint32),
        "seq_hi": seq_hi.astype(np.uint32),
        "seq_lo": seq_lo.astype(np.uint32),
        "vtype": vtype,
        "val_words": val_words,
        "val_len": np.where(vtype == 2, 0, vlen).astype(np.uint32),
    }


def iter_planar_block(raw: bytes) -> Iterator[Tuple[bytes, int, int, bytes]]:
    """Planar block -> (key, seq, vtype, value) tuples (the generic
    reader path; array consumers use decode_planar_block directly)."""
    lanes = decode_planar_block(raw)
    n = len(lanes["key_len"])
    klen = int(lanes["key_len"][0]) if n else 0
    kb = (
        np.ascontiguousarray(lanes["key_words_be"].astype(">u4"))
        .view(np.uint8).reshape(n, 24)
    )
    vb = (
        np.ascontiguousarray(lanes["val_words"].astype("<u4"))
        .view(np.uint8).reshape(n, -1)
    )
    seqs = (
        lanes["seq_hi"].astype(np.uint64) << np.uint64(32)
    ) | lanes["seq_lo"].astype(np.uint64)
    vtypes = lanes["vtype"]
    vlens = lanes["val_len"]
    for i in range(n):
        yield (
            kb[i, :klen].tobytes(), int(seqs[i]), int(vtypes[i]),
            vb[i, :int(vlens[i])].tobytes(),
        )


def planar_props(klen: int, vlen: int, seq32: bool) -> List[int]:
    """The "planar" props value: [klen, vlen, seq32] (ints for JSON)."""
    return [int(klen), int(vlen), int(bool(seq32))]
