"""Workload-adaptive compaction scheduling (RESYSTANCE-style).

Two cooperating pieces the engine plugs into its background compaction
thread, replacing the fixed "L0 >= trigger" loop:

- :class:`CompactionScheduler` — per-db candidate ranking from the
  round-14 pressure signals. Candidates are scored in comparable
  "pressure" units (1.0 = at-trigger): L0 file count vs the compaction/
  slowdown triggers (write-stall risk), per-level bytes vs the
  rocksdb-style level targets (compaction debt), and a WINDOWED
  read-amp (files consulted per get since the last pick) that drains L0
  early when the get path is paying for it. The delayed-write
  controller's stall signal multiplies the write-debt scores, so debt
  reduction accelerates precisely when admissions are being delayed.
  Ranking is event-driven: every flush install, compaction install,
  ingest, and set_options already notifies the engine's condition
  variable, and the compaction thread re-ranks on each wake instead of
  scanning on a timer. A manual queue carries post-ingest full
  compactions (``DB.schedule_compaction``; the admin BatchCompactor
  submits through it) so they obey the same priority order.

- :class:`IoBudget` — a token bucket pacing compaction OUTPUT writes so
  background IO yields to foreground latency. Shared with the
  delayed-write controller two ways: foreground WAL group-commit fsyncs
  register in-flight (compaction file writes briefly yield to them —
  the fsync the write path is waiting on should not queue behind a
  64 MB compaction write), and the controller's admission stalls feed
  ``note_stall`` (stall pressure OPENS the budget: when writes are
  being delayed by debt, compaction is the cure, not the disease).
  When the workload goes read-heavy (no foreground fsync recently) the
  budget opens up too. Rate 0 (the default) meters nothing — only the
  yield-to-foreground behavior is active. The foreground-activity
  register is class-level (process-wide): shard A's compaction yields
  to shard B's foreground fsync, because they share the disk.

Env knobs (see README "Tuning"): ``RSTPU_COMPACTION_SCHED=0`` reverts
to the fixed trigger loop, ``RSTPU_COMPACT_BUDGET_BYTES`` sets the
budget rate (bytes/s), ``RSTPU_MAX_SUBCOMPACTIONS`` caps key-range
subcompaction parallelism (storage/native_compaction.py).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import List, Optional

from ..testing import failpoints as fp
from ..utils.stats import Stats

# Pressure score at or above which a candidate is runnable (L0 exactly
# at level0_compaction_trigger scores 1.0 — legacy-trigger parity).
PICK_THRESHOLD = 1.0
# Windowed read-amp (files consulted per get since the last pick) at
# which an L0 drain is worth running BELOW the file-count trigger.
# An L0 drain rewrites the L1 overlap, so it must not fire for a
# read-amp L0 can't explain: the bar is high (6 files per get) and at
# least 3 L0 files must exist — firing at 2 files under a fat L1 was
# measured to double compaction write-amp for ~1 file of read-amp.
READ_AMP_TRIGGER = 6.0
# ... and only with this many gets in the window (a handful of cold
# reads must not schedule a compaction).
READ_AMP_MIN_GETS = 128
READ_AMP_MIN_L0_FILES = 3
# Stall boost: write-debt scores multiply by 1 + min(cap, pressure/ms).
STALL_BOOST_MS = 50.0
STALL_BOOST_CAP = 2.0
# Level-debt compactions are BATCH work (they move whole levels): under
# live foreground load they compete with serving for CPU and only pay
# off indirectly, so they run when the foreground has been idle this
# long (valley drain) — or immediately once the stall-boosted debt
# score crosses LEVEL_URGENT_SCORE (debt so deep it is slowing the L0
# drain chain; the boost means admission stalls pull this forward,
# which is the RESYSTANCE feedback loop). Measured in PERF round 16:
# without this gate the level mover cost ~3x get p99 BELOW the knee
# while buying nothing.
IDLE_DRAIN_SEC = 2.0
LEVEL_URGENT_SCORE = 4.0
# Stall-pressure EWMA decay constant (seconds).
STALL_DECAY_SEC = 5.0
# IoBudget: foreground considered "recent" within this window; outside
# it the mix is read-heavy and the budget opens by READ_HEAVY_FACTOR.
READ_HEAVY_AFTER_SEC = 1.0
READ_HEAVY_FACTOR = 8.0
# Stall pressure above STALL_BOOST_MS opens the budget up to this much.
BUDGET_STALL_FACTOR_CAP = 4.0
# Bound any single yield/pacing sleep so a compaction can't park long.
# The fg yield is sized for one fsync (~1ms on a healthy disk): under
# continuous group-commit traffic a longer bound let the compaction
# thread spend whole drains waiting while L0 climbed to the stop
# trigger — the death spiral PERF round 16 measured (p99 spikes only
# in the scheduler-on arm).
MAX_YIELD_SEC = 0.005
MAX_PACE_SEC = 0.25


@dataclass
class Pick:
    """One runnable compaction candidate. ``kind`` is ``l0`` (L0→L1
    drain), ``level`` (debt-driven level→level+1, ``level`` = source),
    or ``manual`` (queued full compaction)."""

    kind: str
    level: int
    score: float
    reason: str = ""


class IoBudget:
    """Token-bucket pacing for compaction output IO, with a process-wide
    foreground-fsync register compaction writes yield to. One instance
    per DB (its rate knob is per-db; the fg register is class-level)."""

    # process-wide foreground activity (all shards share the disk)
    _fg_lock = threading.Lock()
    _fg_cv = threading.Condition(_fg_lock)
    _fg_inflight = 0
    _fg_last = 0.0

    def __init__(self, rate_bytes_per_sec: int = 0):
        self._lock = threading.Lock()
        self._rate = max(0, int(rate_bytes_per_sec))
        self._tokens = float(self._rate)
        self._refilled = time.monotonic()
        self._stall_pressure = 0.0
        self._stall_at = time.monotonic()

    # -- foreground side (WalWriter.sync_to) ---------------------------

    @classmethod
    def fg_fsync_begin(cls) -> None:
        with cls._fg_lock:
            IoBudget._fg_inflight += 1
            IoBudget._fg_last = time.monotonic()

    @classmethod
    def fg_fsync_end(cls) -> None:
        with cls._fg_cv:
            IoBudget._fg_inflight -= 1
            IoBudget._fg_last = time.monotonic()
            cls._fg_cv.notify_all()

    # -- delayed-write-controller side (engine admission stalls) -------

    def note_stall(self, stall_ms: float) -> None:
        """An admission paid ``stall_ms`` in the delayed-write
        controller: raise the decayed stall-pressure signal (read by
        the scheduler's priority boost AND the budget's rate)."""
        now = time.monotonic()
        with self._lock:
            self._decay_locked(now)
            self._stall_pressure += max(0.0, stall_ms)

    def stall_pressure(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._decay_locked(now)
            return self._stall_pressure

    def _decay_locked(self, now: float) -> None:
        dt = now - self._stall_at
        if dt > 0:
            self._stall_pressure *= 2.718281828 ** (-dt / STALL_DECAY_SEC)
            self._stall_at = now

    # -- rate knob -----------------------------------------------------

    def set_rate(self, rate_bytes_per_sec: int) -> None:
        with self._lock:
            self._rate = max(0, int(rate_bytes_per_sec))
            self._tokens = min(self._tokens, float(self._rate))

    @property
    def rate(self) -> int:
        return self._rate

    def _effective_rate_locked(self, now: float) -> float:
        """The metered rate after the two opening factors: read-heavy
        mix (no recent foreground fsync) and delayed-write stall
        pressure (debt reduction is what un-delays writes)."""
        eff = float(self._rate)
        if now - IoBudget._fg_last > READ_HEAVY_AFTER_SEC:
            eff *= READ_HEAVY_FACTOR
        self._decay_locked(now)
        if self._stall_pressure > STALL_BOOST_MS:
            eff *= min(BUDGET_STALL_FACTOR_CAP,
                       self._stall_pressure / STALL_BOOST_MS)
        return eff

    # -- compaction side -----------------------------------------------

    def throttle(self, nbytes: int) -> float:
        """Account ``nbytes`` of compaction output IO; sleep as needed.
        Called by the compaction write sinks after each output file.
        Returns seconds slept. Two tiers:

        1. yield-to-foreground: if a foreground WAL fsync is in flight
           RIGHT NOW, wait (bounded) for it to finish before eating
           more disk bandwidth — this is the tail-latency tier.
        2. token pacing: consume from the bucket at the effective rate;
           a dry bucket sleeps the shortfall (bounded). Rate 0 skips
           this tier entirely.
        """
        slept = 0.0
        # Yield ONLY while the foreground is healthy: once admissions
        # are being delayed, compaction IS the cure — waiting for every
        # group-commit fsync would throttle the drain precisely when
        # the write path most needs it (the stall signal instead OPENS
        # the budget below).
        if IoBudget._fg_inflight > 0 \
                and self.stall_pressure() < STALL_BOOST_MS:
            fp.hit("compact.yield")
            Stats.get().incr("compaction.yields")
            deadline = time.monotonic() + MAX_YIELD_SEC
            with self._fg_cv:
                while IoBudget._fg_inflight > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._fg_cv.wait(remaining)
            slept += max(0.0, time.monotonic() - deadline + MAX_YIELD_SEC)
        if self._rate <= 0 or nbytes <= 0:
            return slept
        sleep_for = 0.0
        now = time.monotonic()
        with self._lock:
            eff = self._effective_rate_locked(now)
            self._tokens = min(
                float(self._rate),
                self._tokens + (now - self._refilled) * eff)
            self._refilled = now
            self._tokens -= float(nbytes)
            if self._tokens < 0 and eff > 0:
                sleep_for = min(MAX_PACE_SEC, -self._tokens / eff)
        if sleep_for > 0:
            fp.hit("compact.yield")
            Stats.get().incr("compaction.yields")
            time.sleep(sleep_for)
            slept += sleep_for
        return slept


def adaptive_chunk_entries(base_entries: int, io_budget) -> int:
    """Workload-adaptive chunk sizing for the streaming bounded-memory
    merge (storage/stream_merge.py) — the same stall signal that boosts
    debt-drain priority SHRINKS the merge's working set: while the
    delayed-write controller is stalling admissions the memtables are
    growing, so the compaction should hold less lane memory and hit its
    refill/yield seams more often. Halves per stall-pressure doubling
    over STALL_BOOST_MS, floored at a quarter of the configured chunk.
    The chunk cuts this sizes are the streaming analog of the round-16
    subcompaction slice boundaries: both are key-aligned partitions of
    one compaction's merge, sized by foreground pressure."""
    if io_budget is None:
        return base_entries
    pressure = io_budget.stall_pressure()
    if pressure <= STALL_BOOST_MS:
        return base_entries
    shrink = min(4.0, pressure / STALL_BOOST_MS)
    return max(base_entries // 4, int(base_entries / shrink))


class CompactionScheduler:
    """Per-db compaction candidate ranking. All ``*_locked`` methods
    run under the engine's DB lock (the engine's compaction thread and
    submitters both hold it); the scheduler itself adds no locks."""

    def __init__(self, db) -> None:
        self._db = db
        self._manual: List[Future] = []  # guarded by db._lock
        # read-amp window base: (gets_total, files_consulted_total) at
        # the last executed pick
        self._ra_base = (0, 0)

    # -- manual queue (post-ingest / BatchCompactor submissions) -------

    def submit_manual_locked(self, fut: Future) -> None:
        self._manual.append(fut)

    def take_manual_locked(self) -> List[Future]:
        futs, self._manual = self._manual, []
        return futs

    def fail_pending_locked(self, exc: BaseException) -> None:
        for f in self.take_manual_locked():
            if not f.done():
                f.set_exception(exc)

    def has_manual_locked(self) -> bool:
        return bool(self._manual)

    # -- ranking -------------------------------------------------------

    def note_picked_locked(self) -> None:
        """Reset the read-amp window at every executed pick."""
        db = self._db
        self._ra_base = (db._gets_total, db._files_consulted_total)

    def _stall_boost(self) -> float:
        budget = getattr(self._db, "_io_budget", None)
        if budget is None:
            return 1.0
        return 1.0 + min(STALL_BOOST_CAP,
                         budget.stall_pressure() / STALL_BOOST_MS)

    def pick_locked(self) -> Optional[Pick]:
        """The best runnable candidate, or None when nothing is worth
        compacting. Caller holds the DB lock."""
        db = self._db
        opts = db.options
        best: Optional[Pick] = None
        if not opts.disable_auto_compaction:
            boost = self._stall_boost()
            best = self._l0_candidate(boost)
            lvl = self._level_candidate(boost)
            if lvl is not None and (best is None or lvl.score > best.score):
                best = lvl
        if self._manual:
            # A queued full compaction subsumes every per-level
            # candidate (it drains L0 AND all level debt), so it ranks
            # at the head whenever anything is runnable — including
            # when nothing else is (its submitter is waiting on it).
            score = max(PICK_THRESHOLD, best.score if best else 0.0)
            return Pick("manual", -1, score, "queued full compaction")
        return best

    def _l0_candidate(self, boost: float) -> Optional[Pick]:
        db = self._db
        opts = db.options
        files0 = len(db._levels[0])
        trigger = max(1, opts.level0_compaction_trigger)
        score = files0 / trigger
        reason = f"l0_files={files0}/{trigger}"
        # approaching the slowdown/stop triggers is write-stall risk:
        # escalate so an L0 pile-up outranks mere level debt
        slowdown = max(trigger, opts.level0_slowdown_writes_trigger)
        if files0 >= slowdown:
            score += 2.0 * (files0 - slowdown + 1)
            reason += " at-slowdown"
        score *= boost
        # windowed read-amp: the get path is consulting many files per
        # lookup — draining L0 (the overlap driver) is the cure even
        # below the file-count trigger
        gets0, consulted0 = self._ra_base
        dget = db._gets_total - gets0
        if dget >= READ_AMP_MIN_GETS and files0 >= READ_AMP_MIN_L0_FILES:
            ra = (db._files_consulted_total - consulted0) / dget
            if ra >= READ_AMP_TRIGGER:
                ra_score = ra / READ_AMP_TRIGGER
                if ra_score > score:
                    score = ra_score
                    reason = f"read_amp={ra:.1f}"
        if score >= PICK_THRESHOLD and files0 >= READ_AMP_MIN_L0_FILES:
            return Pick("l0", 0, score, reason)
        if files0 >= max(1, opts.level0_compaction_trigger):
            # legacy-trigger parity (covers trigger <= 1 configs)
            return Pick("l0", 0, max(score, PICK_THRESHOLD), reason)
        return None

    def _level_candidate(self, boost: float) -> Optional[Pick]:
        """Debt-driven level→level+1: score = level bytes / target
        (rocksdb's compaction score), boosted by stall pressure.
        Deferred while the foreground is busy unless the boosted score
        is URGENT (see IDLE_DRAIN_SEC/LEVEL_URGENT_SCORE above)."""
        db = self._db
        opts = db.options
        idle = (time.monotonic() - db._last_write_mono) > IDLE_DRAIN_SEC
        # Eligibility compares the RAW score (the boost would otherwise
        # promote any modest debt to "urgent" whenever soft-tier
        # admission delays are ticking — measured to cost ~3x get p99
        # below the knee for zero stall benefit); the boost still
        # raises an ELIGIBLE candidate's rank vs other work.
        floor = PICK_THRESHOLD if idle else LEVEL_URGENT_SCORE
        target = float(opts.max_bytes_for_level_base)
        best: Optional[Pick] = None
        # the last level has nowhere to compact into; allow_ingest_behind
        # additionally reserves the TRUE bottom level for ingested-behind
        # files (same reservation as compact_range), so the deepest
        # eligible source must install one level above it
        top = len(db._levels) - 1
        if opts.allow_ingest_behind:
            top -= 1
        for lvl in range(1, top):
            files = db._levels[lvl]
            if files:
                level_bytes = sum(
                    db._readers[n].file_size for n in files
                    if n in db._readers)
                raw = level_bytes / target
                score = raw * boost
                if raw >= floor and (
                        best is None or score > best.score):
                    best = Pick("level", lvl, score,
                                f"L{lvl}={level_bytes}B/target={int(target)}"
                                + ("" if idle else " urgent"))
            target *= max(1, opts.max_bytes_for_level_multiplier)
        return best
