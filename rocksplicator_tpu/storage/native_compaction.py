"""NativeCompactionBackend — array-path compaction on the CPU.

The engine's default backend. Two faces:

- ``merge_runs`` (inherited from CpuCompactionBackend): the streaming
  heap-merge. For per-entry tuple IO this IS the fastest CPU path — the
  array backends lose the resolve win back to Python pack/unpack loops
  (measured: tuple-interface numpy path 4× slower than heapq).
- ``merge_runs_to_files``: the DIRECT sink. When every input run reads
  as lanes (sink-written planar/uniform TSSTs decode straight to
  arrays) and widths are uniform, the merge runs as
  ``cpu_merge_resolve`` (storage/native C when loaded, numpy
  otherwise), blooms build in bulk with no per-key Python, and outputs
  write as PLANAR files via the vectorized array writer — no per-entry
  Python anywhere in the pipeline. Returns None for anything the lane
  representation can't express; the engine then takes the tuple path.

This mirrors TpuCompactionBackend.merge_runs_to_files (tpu/backend.py)
with the device kernel swapped for the native CPU resolve — the same
capability the reference gets from RocksDB's C++ compaction
(db/compaction_job.cc), built array-first so the TPU and CPU sinks stay
structurally interchangeable behind the CompactionBackend seam.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Tuple

import numpy as np

from .compaction import CpuCompactionBackend
from .merge import MergeOperator, UInt64AddOperator

log = logging.getLogger(__name__)

_PUT, _DELETE, _MERGE = 1, 2, 3

# bound the in-memory lane concatenation (~48 B/entry of lanes)
MAX_DIRECT_ENTRIES = 1 << 22

# Key-range subcompactions engage only when every slice would carry at
# least this many entries — below it the thread fan-out costs more than
# the parallel resolve buys (tests lower it to force slicing on small
# fixtures).
MIN_SLICE_ENTRIES = 1 << 15


class NativeCompactionBackend(CpuCompactionBackend):
    name = "native"

    def merge_runs_to_files(
        self,
        runs: List,
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
        path_factory,
        block_bytes: int,
        compression: int,
        bits_per_key: int,
        target_file_bytes: int,
        max_subcompactions: int = 1,
        io_budget=None,
        mem_tracker=None,
        memory_budget_bytes: int = 0,
    ) -> Optional[List[Tuple[str, dict]]]:
        """[(path, props)], [] for an all-tombstoned result, or None →
        the engine's tuple path. (Shared with CpuCompactionBackend —
        see direct_merge_runs_to_files below.)"""
        return direct_merge_runs_to_files(
            runs, merge_op, drop_tombstones, path_factory, block_bytes,
            compression, bits_per_key, target_file_bytes,
            max_subcompactions=max_subcompactions, io_budget=io_budget,
            mem_tracker=mem_tracker,
            memory_budget_bytes=memory_budget_bytes,
        )

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _arrays_from_entries(entries, pack_entries) -> Optional[dict]:
        if not entries:
            return None
        b = pack_entries(entries)
        n = b.num_valid()
        return {
            "key_words_be": b.key_words_be[:n], "key_len": b.key_len[:n],
            "seq_hi": b.seq_hi[:n], "seq_lo": b.seq_lo[:n],
            "vtype": b.vtype[:n], "val_words": b.val_words[:n],
            "val_len": b.val_len[:n],
        }

    @staticmethod
    def _sort_cols(part: dict):
        """The merge comparator's lexicographic columns, built by THE
        canonical helper (ops/compaction_kernel.composite_key_lanes —
        every consumer of the composite order shares it). The native
        MrRec packs these lanes pairwise into u64s, which preserves
        lexicographic order, so a run sorted by these columns is sorted
        for the k-way merge."""
        from ..ops.compaction_kernel import composite_key_lanes

        kw = np.asarray(part["key_words_be"], dtype=np.uint32)
        lanes = composite_key_lanes(
            np.zeros(kw.shape[0], dtype=np.uint32),  # all rows valid
            (kw[:, w] for w in range(kw.shape[1])),
            np.asarray(part["key_len"], dtype=np.uint32),
            np.asarray(part["seq_hi"], dtype=np.uint32),
            np.asarray(part["seq_lo"], dtype=np.uint32),
            uniform_klen=False, seq32=False,
        )
        return [np.asarray(lane) for lane in lanes]

    @classmethod
    def _run_is_sorted(cls, part: dict) -> bool:
        cols = cls._sort_cols(part)
        n = len(cols[0])
        if n <= 1:
            return True
        gt = np.zeros(n - 1, dtype=bool)
        eq = np.ones(n - 1, dtype=bool)
        for col in cols:
            x, y = col[:-1], col[1:]
            gt |= eq & (y > x)
            eq &= y == x
        return bool((gt | eq).all())

    @classmethod
    def _resolve(cls, parts: List[dict], lanes: dict, total: int, vw: int,
                 merge_op, drop_tombstones: bool):
        from ..ops.kv_format import KVBatch
        from ..storage.native.binding import get_native
        from ..tpu.backend import cpu_merge_resolve

        lib = get_native()
        if (lib is not None
                and getattr(lib, "has_merge_resolve_runs", False)
                and lanes["key_words_be"].shape[1] == 6
                and all(cls._run_is_sorted(p) for p in parts)):
            # pre-sorted runs (the normal compaction case): O(n log k)
            # k-way merge instead of the O(n log n) full re-sort
            offsets = np.zeros(len(parts) + 1, dtype=np.uint64)
            np.cumsum([p["key_len"].shape[0] for p in parts],
                      out=offsets[1:])
            seq = (lanes["seq_hi"].astype(np.uint64) << np.uint64(32)) \
                | lanes["seq_lo"].astype(np.uint64)
            out = lib.merge_resolve_runs(
                lanes["key_words_be"], lanes["key_len"], seq,
                lanes["vtype"], lanes["val_words"], lanes["val_len"],
                offsets, merge_op is not None, drop_tombstones,
            )
            count = out[6]
            arrays = {
                "key_words_be": out[0][:count], "key_len": out[1][:count],
                "seq_hi": (out[2][:count] >> np.uint64(32)).astype(
                    np.uint32),
                "seq_lo": (out[2][:count]
                           & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                "vtype": out[3][:count].astype(lanes["vtype"].dtype),
                "val_words": out[4][:count], "val_len": out[5][:count],
            }
            return arrays, count

        batch = KVBatch(
            key_words_be=lanes["key_words_be"],
            # LE lanes are for bloom hashing only — the CPU resolve and
            # the bulk bloom below derive bytes from the BE lanes
            key_words_le=lanes["key_words_be"],
            key_len=lanes["key_len"],
            seq_hi=lanes["seq_hi"], seq_lo=lanes["seq_lo"],
            vtype=lanes["vtype"], val_words=lanes["val_words"],
            val_len=lanes["val_len"],
            valid=np.ones(total, dtype=bool),
            val_bytes=vw * 4,
        )
        out, count = cpu_merge_resolve(
            batch, uint64_add=merge_op is not None,
            drop_tombstones=drop_tombstones,
        )
        arrays = {
            "key_words_be": out[0], "key_len": out[1],
            "seq_hi": out[2], "seq_lo": out[3], "vtype": out[4],
            "val_words": out[5], "val_len": out[6],
        }
        return arrays, count

    @staticmethod
    def _bulk_bloom(sub: dict, n: int, klen0: int, bits_per_key: int):
        from .bloom import BloomFilter

        kb = (
            np.ascontiguousarray(sub["key_words_be"][:n].astype(">u4"))
            .view(np.uint8).reshape(n, -1)[:, :klen0]
        )
        lens = np.minimum(
            np.asarray(sub["key_len"][:n], dtype=np.uint64),
            np.uint64(kb.shape[1]))
        return BloomFilter.build_from_arrays(kb, lens, bits_per_key)


def read_runs_as_lanes(
    runs: List, merge_op: Optional[MergeOperator],
    max_entries: int = MAX_DIRECT_ENTRIES,
) -> Optional[Tuple[List[dict], dict, int, int]]:
    """Decode input runs (SSTReaders or entry iterables) straight into
    concatenated lane arrays. Returns (parts, lanes, total, vw) or None
    when the lane representation can't express the inputs (per-run
    checks bail early, before materializing the rest). Shared by the
    direct compaction sink and the batched cross-shard service.

    Deliberately single-threaded: the per-block Python between the
    GIL-releasing zlib/numpy stretches convoys badly under a thread
    fan-out (measured 2.6x SLOWER with 4 decode threads) — the decode
    phase parallelizes by CHUNK in the planned streaming merge, not by
    thread here."""
    from ..ops.kv_format import UnsupportedBatch, pack_entries
    from ..tpu.format import read_sst_arrays

    def decode_one(run) -> Optional[dict]:
        if hasattr(run, "iterate"):  # an SSTReader
            arr = read_sst_arrays(run)
            if arr is None:
                arr = NativeCompactionBackend._arrays_from_entries(
                    list(run.iterate()), pack_entries)
        else:
            arr = NativeCompactionBackend._arrays_from_entries(
                list(run), pack_entries)
        return arr

    parts: List[dict] = []
    total = 0
    try:
        for arr in (decode_one(run) for run in runs):
            if arr is not None:
                if merge_op is not None:
                    # uint64-add fold semantics require 8-byte values
                    # (see the precondition comment in
                    # direct_merge_runs_to_files); checked PER RUN so a
                    # disqualifying workload bails after one run, not a
                    # full assembly
                    nd = arr["val_len"][arr["vtype"] != _DELETE]
                    if len(nd) and not (nd == 8).all():
                        return None
                parts.append(arr)
                total += arr["key_len"].shape[0]
                if total > max_entries:
                    # bail BEFORE materializing the rest — the cap
                    # exists to bound host memory, not to be checked
                    # after the allocation it should have prevented
                    return None
    except UnsupportedBatch:
        return None
    if total == 0:
        return None
    vw = max(p["val_words"].shape[1] for p in parts)
    for p in parts:
        w = p["val_words"].shape[1]
        if w < vw:
            p["val_words"] = np.pad(p["val_words"], [(0, 0), (0, vw - w)])
    fields = ("key_words_be", "key_len", "seq_hi", "seq_lo", "vtype",
              "val_words", "val_len")
    lanes = {f: np.concatenate([p[f] for p in parts]) for f in fields}
    return parts, lanes, total, vw


def lanes_resolvable(lanes: dict, merge_op: Optional[MergeOperator]) -> bool:
    """True when the array merge-resolve can express these lanes' MERGE
    semantics (the PLANAR-sink preconditions shared by every array
    compaction path)."""
    if merge_op is None and bool((lanes["vtype"] == _MERGE).any()):
        return False
    # PLANAR sink preconditions (same as the TPU sink): uniform keys,
    # uniform non-delete value widths
    kl = lanes["key_len"]
    if len(kl) and not (kl == kl[0]).all():
        return False
    is_del = lanes["vtype"] == _DELETE
    non_del_vlens = lanes["val_len"][~is_del]
    if len(non_del_vlens) and not (
            non_del_vlens == non_del_vlens[0]).all():
        return False
    # uint64-add RESOLUTION assumes 8-byte values: the fold rewrites
    # every PUT segment to the operand sum, and a non-8-byte PUT
    # parses as 0 (stream semantics only invoke the operator when
    # operands exist, so a lone non-8-byte PUT must stay verbatim —
    # which the array fold cannot express). Route such shapes to the
    # tuple path.
    if (merge_op is not None and len(non_del_vlens)
            and not (non_del_vlens == 8).all()):
        return False
    return True


def write_resolved_lanes(
    arrays: dict, count: int, path_factory, block_bytes: int,
    compression: int, bits_per_key: int, target_file_bytes: int,
    io_budget=None,
) -> Optional[List[Tuple[str, dict]]]:
    """Write resolved lanes as PLANAR SSTs split at target_file_bytes
    with bulk-built blooms — the shared array file sink. None when the
    planar layout can't express the rows; a mid-loop failure cleans up
    every file already written (nothing would ever GC the orphans).
    ``io_budget`` (compaction callers only) throttles after each output
    file so compaction IO yields to foreground fsyncs."""
    from ..tpu.format import planar_stride, planar_widths, \
        write_sst_from_arrays

    widths = planar_widths(arrays, count)
    if widths is None:
        return None
    klen0, vlen0 = widths
    stride = planar_stride(klen0, vlen0)
    entries_per_file = max(1024, target_file_bytes // max(1, stride))
    block_entries = max(64, block_bytes // max(1, stride))
    outputs: List[Tuple[str, dict]] = []

    def cleanup():
        for p, _ in outputs:
            try:
                os.remove(p)
            except OSError:
                pass

    try:
        for start in range(0, count, entries_per_file):
            end = min(start + entries_per_file, count)
            sub = {f: arrays[f][start:end] for f in arrays}
            bloom = NativeCompactionBackend._bulk_bloom(
                sub, end - start, klen0, bits_per_key)
            path = path_factory()
            props = write_sst_from_arrays(
                sub, end - start, path,
                bloom_words=bloom.words,
                block_entries=block_entries,
                compression=compression,
                bits_per_key=bits_per_key,
                planar=True,
            )
            if props is None:  # should not happen after width checks
                cleanup()
                return None
            outputs.append((path, props))
            if io_budget is not None:
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = (end - start) * stride
                io_budget.throttle(size)
    except BaseException:
        # a mid-loop failure (disk full on file 2 of 3) must not
        # leak file 1: the engine falls back to the tuple path and
        # nothing would ever reference or GC the orphan
        cleanup()
        raise
    return outputs


# ---------------------------------------------------------------------------
# key-range subcompactions (rocksdb max_subcompactions analog)
# ---------------------------------------------------------------------------
#
# One large compaction splits into disjoint KEY-RANGE slices executed in
# parallel across cores. Boundaries are chosen from the input runs' own
# key distribution (evenly spaced rows of each decoded SST — the lane
# image of the files' fence/block-index keys) and are plain KEYS, so a
# key's whole entry group — MERGE operand chains, duplicate seqs,
# tombstone stacks — lands in exactly one slice by construction and the
# per-slice resolve is byte-equivalent to the unsliced single pass
# (pinned by the slice-boundary matrix test). Slice outputs concatenate
# in boundary order and install atomically as ONE generation.


def _part_key(part: dict, i: int, klen: int) -> bytes:
    """Key bytes of row ``i`` (uniform width ``klen`` — guaranteed by
    lanes_resolvable before slicing is attempted)."""
    return part["key_words_be"][i].astype(">u4").tobytes()[:klen]


def _first_row_ge(part: dict, key: bytes, klen: int) -> int:
    """First row index with key >= ``key`` in a (key asc)-sorted run."""
    lo, hi = 0, part["key_len"].shape[0]
    while lo < hi:
        mid = (lo + hi) // 2
        if _part_key(part, mid, klen) < key:
            lo = mid + 1
        else:
            hi = mid
    return lo


def choose_slice_boundaries(parts: List[dict], nslices: int,
                            klen: int) -> List[bytes]:
    """Up to ``nslices - 1`` boundary KEYS approximating equal-weight
    quantiles of the merged key distribution: each run contributes
    evenly spaced sample rows proportional to its size (the decoded
    form of its SST fence array), the pooled samples sort, and the
    quantile points dedupe. May return fewer boundaries than asked
    (skewed or tiny key sets)."""
    total = sum(p["key_len"].shape[0] for p in parts)
    if total == 0 or nslices <= 1:
        return []
    per_total = max(nslices * 8, 64)
    samples: List[bytes] = []
    for part in parts:
        n = part["key_len"].shape[0]
        if n == 0:
            continue
        take = max(1, min(n, (per_total * n + total - 1) // total))
        idx = np.linspace(0, n - 1, take).astype(int)
        samples.extend(_part_key(part, int(i), klen) for i in idx)
    samples.sort()
    bounds: List[bytes] = []
    lo_key = samples[0]
    for s in range(1, nslices):
        b = samples[(s * len(samples)) // nslices]
        if b > lo_key and (not bounds or b > bounds[-1]):
            bounds.append(b)
    return bounds


def plan_subcompactions(parts: List[dict], total: int,
                        max_subcompactions: int, klen: int) -> List[bytes]:
    """Boundary keys for this compaction, or [] to run unsliced. Slices
    only when the parallelism is asked for, every slice would clear
    MIN_SLICE_ENTRIES, and every run is (key, seq)-sorted — the bisect
    cut is only meaningful on sorted runs (unsorted inputs take the
    full-lexsort resolve unsliced)."""
    nslices = min(int(max_subcompactions), total // max(1, MIN_SLICE_ENTRIES))
    if nslices <= 1:
        return []
    if not all(NativeCompactionBackend._run_is_sorted(p) for p in parts):
        return []
    return choose_slice_boundaries(parts, nslices, klen)


def slice_parts(parts: List[dict], bounds: List[bytes], si: int,
                klen: int, cuts: List[List[int]],
                fields: Optional[Tuple[str, ...]] = None) -> List[dict]:
    """Slice ``si``'s row ranges of every part (``cuts[p]`` = the
    per-part boundary row indices from _first_row_ge)."""
    if fields is None:
        fields = ("key_words_be", "key_len", "seq_hi", "seq_lo", "vtype",
                  "val_words", "val_len")
    out: List[dict] = []
    for p, c in zip(parts, cuts):
        lo = c[si - 1] if si > 0 else 0
        hi = c[si] if si < len(bounds) else p["key_len"].shape[0]
        if hi > lo:
            out.append({f: p[f][lo:hi] for f in fields})
    return out


def _subcompact_to_files(
    parts: List[dict], bounds: List[bytes], klen: int, vw: int,
    merge_op: Optional[MergeOperator], drop_tombstones: bool,
    path_factory, block_bytes: int, compression: int, bits_per_key: int,
    target_file_bytes: int, io_budget,
) -> List[Tuple[str, dict]]:
    """Resolve + write every key-range slice in parallel; outputs
    concatenate in boundary order (still globally key-sorted and
    non-overlapping). Any slice failure sweeps every file already
    written by every slice and re-raises — the caller falls back to the
    unsliced/tuple path, and nothing would ever GC the orphans."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from ..observability.span import start_span
    from ..testing import failpoints as fp
    from ..utils.stats import Stats

    cuts = [[_first_row_ge(p, b, klen) for b in bounds] for p in parts]
    nsl = len(bounds) + 1
    results: List[Optional[List[Tuple[str, dict]]]] = [None] * nsl
    written_lock = threading.Lock()
    written_paths: List[str] = []

    def tracking_factory() -> str:
        path = path_factory()
        with written_lock:
            written_paths.append(path)
        return path

    def run_slice(si: int) -> None:
        fp.hit("compact.subcompact")
        Stats.get().incr("compaction.subcompactions")
        sub_parts = slice_parts(parts, bounds, si, klen, cuts)
        if not sub_parts:
            results[si] = []
            return
        fields = sub_parts[0].keys()
        sub_lanes = {f: np.concatenate([p[f] for p in sub_parts])
                     for f in fields}
        sub_total = sub_lanes["key_len"].shape[0]
        arrays, count = NativeCompactionBackend._resolve(
            sub_parts, sub_lanes, sub_total, vw, merge_op,
            drop_tombstones)
        if count == 0:
            results[si] = []
            return
        outs = write_resolved_lanes(
            arrays, count, tracking_factory, block_bytes, compression,
            bits_per_key, target_file_bytes, io_budget=io_budget)
        if outs is None:  # cannot happen after the global width checks
            raise RuntimeError(f"slice {si}: planar sink declined")
        results[si] = outs

    with start_span("compact.subcompactions", slices=nsl):
        with ThreadPoolExecutor(
            max_workers=min(nsl, os.cpu_count() or 2),
            thread_name_prefix="subcompact",
        ) as pool:
            futs = [pool.submit(run_slice, si) for si in range(nsl)]
            errs = []
            for f in futs:
                try:
                    f.result()
                except BaseException as e:
                    errs.append(e)
        if errs:
            with written_lock:
                for p in written_paths:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
            raise errs[0]
    return [o for outs in results for o in (outs or [])]


def direct_merge_runs_to_files(
    runs: List,
    merge_op: Optional[MergeOperator],
    drop_tombstones: bool,
    path_factory,
    block_bytes: int,
    compression: int,
    bits_per_key: int,
    target_file_bytes: int,
    max_subcompactions: int = 1,
    io_budget=None,
    mem_tracker=None,
    memory_budget_bytes: int = 0,
) -> Optional[List[Tuple[str, dict]]]:
    """The CPU array compaction pipeline: runs → lanes → merge-resolve
    (native C when loaded, numpy lexsort+reduceat otherwise) → PLANAR
    files. [(path, props)], [] for an all-tombstoned result, or None →
    the engine's tuple path. Shared by CpuCompactionBackend and
    NativeCompactionBackend so every CPU-configured engine compacts
    array-to-array when the inputs allow it.

    Inputs whose projected lane image exceeds the compaction memory
    budget (or the MAX_DIRECT_ENTRIES cap) stream through the chunked
    bounded-memory merge instead of materializing here — byte-identical
    output, working set fixed by RSTPU_COMPACT_MEM_BUDGET
    (storage/stream_merge.py). Smaller compactions keep the in-RAM
    path: it already fits the ceiling, and key-range subcompactions
    (``max_subcompactions > 1``) can then resolve+write disjoint slices
    in parallel across cores. ``io_budget`` paces the output writes so
    compaction IO yields to foreground fsyncs; ``mem_tracker`` records
    the materialized-bytes high-water for the
    ``compaction.peak_bytes_materialized`` gauge on both paths."""
    from ..observability.span import start_span
    from .stream_merge import maybe_stream_merge

    if merge_op is not None and not isinstance(merge_op, UInt64AddOperator):
        return None
    streamed = maybe_stream_merge(
        runs, merge_op, drop_tombstones, path_factory, block_bytes,
        compression, bits_per_key, target_file_bytes,
        io_budget=io_budget, mem_tracker=mem_tracker,
        memory_budget_bytes=memory_budget_bytes,
    )
    if streamed is not None:
        return streamed
    read = read_runs_as_lanes(runs, merge_op)
    if read is None:
        return None
    parts, lanes, total, vw = read
    if not lanes_resolvable(lanes, merge_op):
        return None
    # in-RAM accounting for the peak gauge: per-run parts plus their
    # concatenation are live together right now
    inram_bytes = 2 * int(sum(a.nbytes for a in lanes.values()))
    if mem_tracker is not None:
        mem_tracker.add(inram_bytes)
    try:
        if max_subcompactions > 1:
            kl = lanes["key_len"]
            klen = int(kl[0]) if len(kl) else 0
            bounds = plan_subcompactions(
                parts, total, max_subcompactions, klen)
            if bounds:
                return _subcompact_to_files(
                    parts, bounds, klen, vw, merge_op, drop_tombstones,
                    path_factory, block_bytes, compression, bits_per_key,
                    target_file_bytes, io_budget)
        with start_span("compact.resolve", entries=total):
            arrays, count = NativeCompactionBackend._resolve(
                parts, lanes, total, vw, merge_op, drop_tombstones)
        if count == 0:
            return []  # fully compacted away — nothing to write
        return write_resolved_lanes(
            arrays, count, path_factory, block_bytes, compression,
            bits_per_key, target_file_bytes, io_budget=io_budget,
        )
    finally:
        if mem_tracker is not None:
            mem_tracker.sub(inram_bytes)
