"""RLZ1 — the framework's fast byte codec (LZ4/snappy-class).

The reference compresses SST blocks with Snappy/ZSTD (RocksDB block
compression) and RPC channels with snappy transforms
(common/thrift_client_pool.h:277-284). Neither library is in the image,
and zlib costs real CPU on the ingest path — so this is an owned codec:
greedy LZ77, depth-1 hash table, byte-aligned tokens, built for encode
speed over ratio. The native module (storage/native/tsst_native.cc
``rlz_compress``/``rlz_decompress``) is the production path; this file
owns the format and provides the pure-Python fallback used when the
native build is unavailable.

Format (little-endian)::

    u32 raw_len
    tokens until raw_len output bytes:
      0x01..0x7F          literal run of <tag> bytes, bytes follow inline
      0x80|L, u16 dist    match: copy L+4 bytes (4..131) starting <dist>
                          bytes back in the OUTPUT (1..65535); may overlap
                          itself (run encoding), copied front-to-back

Worst case (incompressible input): 4 + n + ceil(n/127) bytes — callers
size buffers with :func:`max_compressed_len`.
"""

from __future__ import annotations

from typing import Optional

_MIN_MATCH = 4
_MAX_MATCH = 131
_MAX_DIST = 65535


def max_compressed_len(n: int) -> int:
    return 4 + n + (n + 126) // 127 + 3


def _py_compress(data: bytes) -> bytes:
    n = len(data)
    if n > 0xFFFFFFFF:
        raise ValueError("rlz: input exceeds the u32 raw_len field")
    out = bytearray(n.to_bytes(4, "little"))
    table: dict = {}
    i = 0
    lit_start = 0
    while i + _MIN_MATCH <= n:
        gram = data[i:i + 4]
        cand = table.get(gram)
        table[gram] = i
        if cand is not None and i - cand <= _MAX_DIST:
            max_len = min(_MAX_MATCH, n - i)
            length = 4
            while (length < max_len
                   and data[cand + length] == data[i + length]):
                length += 1
            run = i - lit_start
            while run > 0:
                take = min(127, run)
                out.append(take)
                out += data[lit_start:lit_start + take]
                lit_start += take
                run -= take
            dist = i - cand
            out.append(0x80 | (length - _MIN_MATCH))
            out += dist.to_bytes(2, "little")
            i += length
            lit_start = i
            if i + _MIN_MATCH <= n:
                table[data[i - 1:i + 3]] = i - 1
        else:
            i += 1
    run = n - lit_start
    while run > 0:
        take = min(127, run)
        out.append(take)
        out += data[lit_start:lit_start + take]
        lit_start += take
        run -= take
    return bytes(out)


def _py_decompress(data: bytes, max_out: int) -> bytes:
    if len(data) < 4:
        raise ValueError("rlz: truncated header")
    raw_len = int.from_bytes(data[:4], "little")
    if raw_len > max_out:
        raise ValueError(f"rlz: declared length {raw_len} > cap {max_out}")
    out = bytearray()
    r, n = 4, len(data)
    while len(out) < raw_len:
        if r >= n:
            raise ValueError("rlz: truncated stream")
        tag = data[r]
        r += 1
        if tag & 0x80:
            length = (tag & 0x7F) + _MIN_MATCH
            if r + 2 > n:
                raise ValueError("rlz: truncated match")
            dist = int.from_bytes(data[r:r + 2], "little")
            r += 2
            w = len(out)
            if dist == 0 or dist > w or w + length > raw_len:
                raise ValueError("rlz: bad match")
            if dist >= length:
                out += out[w - dist:w - dist + length]
            else:
                # overlapping run: replicate the period in slices (O(n)
                # total, no per-byte interpreter loop — a native-less
                # receiver decodes run-heavy frames at C speed)
                pattern = bytes(out[w - dist:w])
                out += (pattern * (length // dist + 1))[:length]
        else:
            if tag == 0:
                raise ValueError("rlz: zero literal tag")
            if r + tag > n or len(out) + tag > raw_len:
                raise ValueError("rlz: bad literal run")
            out += data[r:r + tag]
            r += tag
    return bytes(out)


def _native():
    from .native.binding import get_native

    lib = get_native()
    if lib is not None and lib.has_rlz:
        return lib
    return None


def compress(data: bytes) -> bytes:
    if len(data) > 0xFFFFFFFF:
        raise ValueError("rlz: input exceeds the u32 raw_len field")
    lib = _native()
    if lib is not None:
        return lib.rlz_compress(data)
    return _py_compress(data)


def decompress(data: bytes, max_out: int) -> bytes:
    """Bounded decode: raises ValueError if the declared output exceeds
    ``max_out`` (zip-bomb guard) or the stream is malformed."""
    lib = _native()
    if lib is not None:
        out = lib.rlz_decompress(data, max_out)
        if out is None:
            raise ValueError("rlz: malformed stream (native)")
        return out
    return _py_decompress(data, max_out)


def native_available() -> bool:
    return _native() is not None
