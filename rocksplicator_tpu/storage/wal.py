"""Write-ahead log with sequence numbers and update shipping.

Reference contracts (pinned by the reference's rocksdb_assumption_test.cpp
and relied on by the replicator):
- every seq-consuming op gets a sequence number; a batch occupies the range
  [start_seq, start_seq + count - 1]
- ``get_updates_since(seq)`` returns every batch whose range intersects
  [seq, ∞), in order, as (start_seq, raw_batch_bytes) — the replicator ships
  the raw bytes (replicated_db.cpp:486-540)
- WAL history survives memtable flushes for ``wal_ttl_seconds`` so followers
  can catch up (performance.cpp uses WAL TTL 1h)

Record format per entry (little-endian):
    u64 start_seq
    u32 batch_len
    u32 crc32(batch)
    batch bytes

Segments roll at ``segment_bytes``; file names are ``wal-<first_seq>.log``.
Torn tails (crash mid-append) are truncated on recovery.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

from ..testing import failpoints as fp
from .errors import Corruption, StorageError

_REC_HEAD = struct.Struct("<QII")


def _fsync_file(f) -> None:
    """All WAL data/segment fsyncs funnel through the ``wal.fsync``
    failpoint (delay = a stalling device, fail = a dying one)."""
    fp.hit("wal.fsync")
    os.fsync(f.fileno())


class WalWriter:
    """Appender with GROUP-COMMIT durability (rocksdb write-group
    analog). ``append`` (serialized by the engine's DB lock) buffers +
    flushes to the OS and returns a monotonically increasing token;
    ``sync_to(token)`` — called OUTSIDE the DB lock — makes every
    append up to that token durable with ONE fsync shared by all
    concurrently-waiting sync writers: the first waiter in becomes the
    leader, snapshots the published append token, fsyncs once, and
    every writer whose token that snapshot covers returns without
    touching the disk. Readers never block on an fsync."""

    def __init__(
        self,
        wal_dir: str,
        segment_bytes: int = 64 * 1024 * 1024,
    ):
        self._dir = wal_dir
        self._segment_bytes = segment_bytes
        self._file = None
        self._file_size = 0
        # group-commit state: tokens are published under the appender's
        # lock; _sync_lock serializes fsync leaders and file swaps
        self._sync_lock = threading.Lock()  # rstpu-check: io-mutex group-commit fsync leader lock — fsync under it IS the mechanism
        self._append_token = 0
        self._synced_token = 0
        # non-sync workloads pay no roll-time fsync; the first sync
        # request catches up any segments closed un-fsynced before it
        self._sync_used = False
        self._closed_unsynced = False
        # False whenever a segment dirent was created without a
        # directory fsync; set True only by a SUCCESSFUL dir fsync, so
        # a failed attempt is retried by the next sync instead of the
        # durability claim silently standing
        self._dir_synced = False
        # Optional compaction_scheduler.IoBudget (set by the engine when
        # adaptive compaction scheduling is on): foreground group-commit
        # fsyncs register in-flight so compaction output writes yield to
        # them instead of queueing the latency-critical fsync behind a
        # large background write.
        self.io_budget = None
        os.makedirs(wal_dir, exist_ok=True)

    def append(self, start_seq: int, batch_bytes: bytes) -> int:
        """Buffer one record and flush it to the OS. Returns the sync
        token covering it — pass to ``sync_to`` for durability. Must be
        externally serialized (the engine holds the DB lock)."""
        fp.hit("wal.append")
        if self._file is None or self._file_size >= self._segment_bytes:
            self._roll(start_seq)
        rec = _REC_HEAD.pack(
            start_seq, len(batch_bytes), zlib.crc32(batch_bytes) & 0xFFFFFFFF
        )
        assert self._file is not None
        try:
            cut = fp.torn_point("wal.append", len(rec) + len(batch_bytes))
            if cut is not None:
                # torn write: a prefix of the record reaches the OS and
                # the writer sees a failed append (crash-shaped fault)
                self._file.write((rec + batch_bytes)[:cut])
                self._file.flush()
                raise fp.FailpointError(f"torn WAL append at +{cut}B")
            self._file.write(rec)
            self._file.write(batch_bytes)
            # flush BEFORE publishing the token: a sync leader snapshotting
            # the token must find these bytes already in the OS, so its
            # fsync alone durably covers them
            self._file.flush()
        except BaseException:
            # A record that failed part-way (torn injection, ENOSPC, EIO)
            # would corrupt every LATER append in this still-live process:
            # scans stop at the first bad CRC, so subsequent committed
            # records become unreachable. Truncate back to the record
            # boundary so the log stays hole-free; if even that fails the
            # reopen-time torn-tail truncation is the backstop.
            try:
                if not self._file.closed:
                    self._file.truncate(self._file_size)
                    self._file.flush()
            except (OSError, ValueError):
                pass
            raise
        self._file_size += len(rec) + len(batch_bytes)
        self._append_token += 1
        return self._append_token

    def append_many(self, records: List[Tuple[int, bytes]]) -> int:
        """Buffer a GROUP of records with ONE flush (and one token
        publish) at the end — the follower apply path commits a whole
        pull response per call, so the per-record flush syscall (the
        dominant cost of per-record append on the apply hot path) is
        paid once per response instead of once per update. Same
        serialization contract as ``append``; rolls mid-group flush the
        outgoing segment first."""
        assert records
        fp.hit("wal.append")
        pending = 0
        # rollback point if the group fails part-way: the last offset
        # covered by a PUBLISHED token, valid only for published_file —
        # truncate() on a DIFFERENT (fresh post-roll) file would
        # zero-EXTEND it, and 16 zero bytes decode as a valid empty
        # record (seq 0, len 0, crc32(b"")==0): phantom records
        published_file = self._file
        published_size = self._file_size if self._file is not None else 0
        try:
            for start_seq, batch_bytes in records:
                if (self._file is None
                        or self._file_size >= self._segment_bytes):
                    if pending:
                        # flush + publish the group's records in the
                        # outgoing segment BEFORE rolling: _roll decides
                        # sync coverage (and _closed_unsynced) from the
                        # published token
                        self._file.flush()
                        self._append_token += pending
                        pending = 0
                        # the rollback boundary must advance WITH the
                        # publish: if _roll itself fails, truncating
                        # below this point would delete records whose
                        # tokens are already claimable by sync_to
                        published_size = self._file_size
                    self._roll(start_seq)
                    published_file = self._file
                    published_size = self._file_size
                rec = _REC_HEAD.pack(
                    start_seq, len(batch_bytes),
                    zlib.crc32(batch_bytes) & 0xFFFFFFFF,
                )
                cut = fp.torn_point(
                    "wal.append", len(rec) + len(batch_bytes))
                if cut is not None:
                    # torn group append: same crash-shaped fault as the
                    # single-record path (the follower batched-apply WAL
                    # is hit through HERE, not append)
                    self._file.write((rec + batch_bytes)[:cut])
                    self._file.flush()
                    raise fp.FailpointError(
                        f"torn WAL group append at +{cut}B")
                self._file.write(rec)
                self._file.write(batch_bytes)
                self._file_size += len(rec) + len(batch_bytes)
                pending += 1
            # one flush covers the group; publish AFTER it (sync leaders
            # snapshotting the token must find every covered byte in the OS)
            self._file.flush()
            self._append_token += pending
            return self._append_token
        except BaseException:
            # The group failed part-way: unpublished records (complete or
            # torn) must not linger — the caller never committed them, so
            # on replay/serve they would be phantoms under seqs the engine
            # will reassign to DIFFERENT content. Truncate back to the
            # published boundary; reopen-time torn-tail truncation is the
            # backstop if even this fails. Only the file the boundary
            # belongs to may be truncated: after a failed _roll the
            # current file is a fresh segment with nothing unpublished
            # in it (rolls publish first), so it is left alone.
            try:
                if (self._file is not None
                        and self._file is published_file
                        and not self._file.closed):
                    self._file.truncate(published_size)
                    self._file.flush()
                    self._file_size = published_size
            except (OSError, ValueError):
                # ValueError: the file closed under us (a failed _roll);
                # the original fault must propagate, not this cleanup
                pass
            raise

    def sync_to(self, token: int) -> None:
        """Group commit: durable up to ``token`` (and opportunistically
        everything appended by the time the leader's fsync starts).
        Safe to call concurrently from many writers without the DB
        lock; appends may proceed in parallel (BufferedWriter is
        internally locked, and unsynced appends simply ride a later
        fsync)."""
        if token <= self._synced_token:
            return
        with self._sync_lock:
            self._sync_used = True
            if token <= self._synced_token:
                return  # a leader's fsync covered us while we waited
            f = self._file
            if f is None:
                return
            cover = self._append_token
            self._catchup_closed_segments_locked()
            if not self._dir_synced:
                # segment dirents created before sync was in use
                self._fsync_dir_locked()
            budget = self.io_budget
            if budget is not None:
                budget.fg_fsync_begin()
            try:
                _fsync_file(f)
            finally:
                if budget is not None:
                    budget.fg_fsync_end()
            if cover > self._synced_token:
                self._synced_token = cover

    def _catchup_closed_segments_locked(self) -> None:
        """One-time sweep: fsync segments that rolled closed before the
        first sync request (rolls skip the fsync until sync is in use,
        so plain workloads never stall on it). Caller holds _sync_lock."""
        if not self._closed_unsynced:
            return
        for _seq, path in _segments(self._dir):
            try:
                fd = os.open(path, os.O_RDONLY)
            except FileNotFoundError:
                continue  # purged — durability is moot
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        self._fsync_dir_locked()  # their dirents too
        self._closed_unsynced = False

    def _fsync_dir_locked(self) -> None:
        # a failing open/fsync on our own directory must PROPAGATE: the
        # caller is mid-durability-claim, and the sticky flag stays
        # False so the next sync retries
        fd = os.open(self._dir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        self._dir_synced = True

    def _roll(self, first_seq: int) -> None:
        fp.hit("wal.roll")
        # the sync lock pins the outgoing file against a concurrent
        # leader's fsync on its (about-to-be-closed) descriptor
        with self._sync_lock:
            if self._file is not None:
                if self._append_token > self._synced_token:
                    if self._sync_used:
                        # a later sync_to can only fsync the NEW file;
                        # make the outgoing segment durable now so its
                        # tokens are honestly covered (one fsync per
                        # segment roll, only once sync is in use)
                        self._file.flush()
                        _fsync_file(self._file)
                        self._synced_token = self._append_token
                    else:
                        # plain workload: skip the stall, remember that
                        # a first sync request must sweep closed
                        # segments before claiming coverage
                        self._closed_unsynced = True
                self._file.close()
            path = os.path.join(self._dir, f"wal-{first_seq:020d}.log")
            self._file = open(path, "ab")
            self._file_size = self._file.tell()
            if self._sync_used:
                # persist the new segment's directory entry: an fsynced
                # FILE is not durable if power loss drops its dirent
                self._fsync_dir_locked()
            else:
                self._dir_synced = False  # new dirent, not yet durable

    def sync(self) -> None:
        """Unconditional full sync (flush + fsync of the active
        segment, catching up any segments closed un-fsynced)."""
        with self._sync_lock:
            self._sync_used = True
            f = self._file
            if f is None:
                return
            cover = self._append_token
            self._catchup_closed_segments_locked()
            if not self._dir_synced:
                self._fsync_dir_locked()
            f.flush()
            _fsync_file(f)
            if cover > self._synced_token:
                self._synced_token = cover

    def close(self) -> None:
        # the sync lock pins the descriptor against an in-flight group
        # leader's fsync (same rule as _roll). A dirty tail — data OR
        # dirents — is made fully durable before closing and claiming
        # coverage: a sync writer that appended but has not yet reached
        # sync_to must find its bytes durable (its sync_to no-ops after
        # close), and a cleanly closed WAL survives power loss outright.
        with self._sync_lock:
            if self._file is not None:
                if (self._append_token > self._synced_token
                        or self._closed_unsynced):
                    self._catchup_closed_segments_locked()
                    if not self._dir_synced:
                        self._fsync_dir_locked()
                    self._file.flush()
                    _fsync_file(self._file)
                    self._synced_token = self._append_token
                self._file.close()
                self._file = None


def _segments(wal_dir: str) -> List[Tuple[int, str]]:
    """Sorted (first_seq, path) of WAL segments."""
    out = []
    try:
        names = os.listdir(wal_dir)
    except FileNotFoundError:
        return []
    for name in names:
        if name.startswith("wal-") and name.endswith(".log"):
            try:
                first_seq = int(name[4:-4])
            except ValueError:
                continue
            out.append((first_seq, os.path.join(wal_dir, name)))
    return sorted(out)


def _iter_segment(
    path: str, truncate_torn: bool = False, tolerate_tail: bool = False
) -> Iterator[Tuple[int, bytes]]:
    """Yields (start_seq, batch_bytes) from one segment.

    ``truncate_torn`` truncates a torn tail in place (recovery path).
    ``tolerate_tail`` treats a bad/incomplete record as end-of-data without
    raising — used on the ACTIVE segment, which a concurrent writer may be
    mid-appending.
    """
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return  # segment purged between listing and open — fine, it was
        # fully persisted (purge never removes unpersisted segments)

    from .native.binding import NATIVE

    if NATIVE is not None:
        records, bad_crc_at = NATIVE.wal_scan(data)
        if bad_crc_at >= 0 and not (truncate_torn or tolerate_tail):
            raise Corruption(f"WAL crc mismatch in {path} at offset {bad_crc_at}")
        good_end = (
            records[-1][1] + records[-1][2] if records else 0
        )
        for seq, off, ln in records:
            yield seq, data[off:off + ln]
        if good_end < len(data) and truncate_torn:
            with open(path, "r+b") as f:
                f.truncate(good_end)
        return

    pos = 0
    good_end = 0
    while pos + _REC_HEAD.size <= len(data):
        start_seq, blen, crc = _REC_HEAD.unpack_from(data, pos)
        body_start = pos + _REC_HEAD.size
        body_end = body_start + blen
        if body_end > len(data):
            break  # torn / still-being-written tail
        body = data[body_start:body_end]
        if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
            if truncate_torn or tolerate_tail:
                break  # treat as torn from here
            raise Corruption(f"WAL crc mismatch in {path} at offset {pos}")
        yield start_seq, body
        pos = body_end
        good_end = pos
    if good_end < len(data) and truncate_torn:
        with open(path, "r+b") as f:
            f.truncate(good_end)


def oldest_seq(wal_dir: str) -> Optional[int]:
    """First sequence number the WAL can still serve (the oldest
    surviving segment's name seq), or None for an empty/missing WAL.
    The needRebuildDB check uses this: a replica whose local seq is
    BELOW a donor's oldest WAL seq can never catch up over the
    replication plane (the serve path raises "WAL gap … puller must
    rebuild") and must rebuild from a snapshot instead."""
    segs = _segments(wal_dir)
    return segs[0][0] if segs else None


def iter_updates(
    wal_dir: str, since_seq: int = 0, truncate_torn: bool = False
) -> Iterator[Tuple[int, bytes]]:
    """Every batch whose seq range intersects [since_seq, ∞), in order, as
    (start_seq, batch_bytes).

    GetUpdatesSince parity: a batch straddling ``since_seq`` IS returned
    (callers normally pass latest_local+1, a batch boundary, but the
    contract holds regardless). Safe against concurrent append (active
    segment tail tolerated) and concurrent purge (missing segments skipped).
    """
    from .records import decode_batch

    segs = _segments(wal_dir)
    yielded_any = False
    for i, (first_seq, path) in enumerate(segs):
        # Skip segments that end before since_seq (next segment's first_seq
        # bounds this one).
        if i + 1 < len(segs) and segs[i + 1][0] <= since_seq:
            continue
        is_last = i + 1 == len(segs)
        # Torn tails are only legitimate in the LAST segment (crash mid-
        # append). A CRC mismatch mid-log is real corruption and must raise,
        # not silently truncate committed records.
        for start_seq, body in _iter_segment(
            path,
            truncate_torn=truncate_torn and is_last,
            tolerate_tail=is_last,
        ):
            if start_seq >= since_seq:
                yielded_any = True
                yield start_seq, body
            elif not yielded_any:
                # Possible straddler: include iff its range reaches since_seq.
                if start_seq + decode_batch(body).count() - 1 >= since_seq:
                    yielded_any = True
                    yield start_seq, body


class WalTailCursor:
    """Resumable streaming cursor over the WAL tail.

    ``iter_updates`` is a one-shot generator: once it reaches the live
    tail it is exhausted for good, so a serve path that drains to the
    tail must re-open — re-reading and re-CRC-ing the ENTIRE active
    segment per pull (quadratic in segment fill; measured as the
    dominant serve cost once leader writes pipeline). This cursor stays
    valid at the tail: iterating raises StopIteration when it runs out
    of complete records, and iterating AGAIN later continues from the
    remembered (segment, offset) — new appends stream with zero
    re-scanning. Segment rolls are followed automatically (a newer
    segment file means the current one is final).

    Iterator of (start_seq, batch_bytes) with the same contract as
    ``iter_updates``: every batch whose seq range intersects
    [since_seq, ∞), in order, including a straddler batch.

    Single-consumer; not thread-safe. ``resumable`` marks the contract
    for cursor caches that would otherwise drop exhausted iterators.
    """

    resumable = True

    # read-ahead chunk: one pread per ~chunk of records instead of three
    # small reads per record
    _CHUNK = 1 << 20

    def __init__(self, wal_dir: str, since_seq: int = 0,
                 segment_bytes: Optional[int] = None):
        self._dir = wal_dir
        self._since = since_seq
        self._f = None
        self._first_seq: Optional[int] = None  # current segment's name seq
        self._offset = 0
        self._positioned = False
        self._yielded_any = False
        # roll-check guard: a segment never rolls before reaching
        # segment_bytes, so tail hits below that size skip the listdir
        # entirely (the dominant cursor cost when serves drain to the
        # tail every pull)
        self._segment_bytes = segment_bytes
        self._eof_hits = 0  # consecutive tail hits since last real roll check
        self._buf = b""
        self._buf_off = 0  # file offset corresponding to _buf[0]

    def __iter__(self) -> "WalTailCursor":
        return self

    def __next__(self) -> Tuple[int, bytes]:
        rec = self.read_next()
        if rec is None:
            raise StopIteration
        return rec

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None

    # -- internals ---------------------------------------------------------

    def _position(self) -> bool:
        """First use: pick the starting segment (same skip rule as
        iter_updates) and skip-scan record HEADERS to since_seq — no CRC
        work, no body copies — so even the one-time cold cost is far
        below a full-segment re-read."""
        segs = _segments(self._dir)
        if not segs:
            return False
        start_i = 0
        for i in range(len(segs)):
            if i + 1 < len(segs) and segs[i + 1][0] <= self._since:
                start_i = i + 1
        self._open_segment(segs[start_i])
        self._skip_to_since()
        self._positioned = True
        return True

    def _open_segment(self, seg: Tuple[int, str]) -> None:
        self.close()
        first_seq, path = seg
        try:
            self._f = open(path, "rb")
        except FileNotFoundError:
            # purged between listing and open: the records it held were
            # persisted; signal a gap and let the puller rebuild
            raise ValueError(
                f"WAL gap: segment {path} purged under cursor"
            ) from None
        self._first_seq = first_seq
        self._offset = 0
        self._buf = b""
        self._buf_off = 0

    def _skip_to_since(self) -> None:
        """Header-jump within the opened segment to the first record with
        start_seq >= since, handling the straddler (previous record whose
        range reaches since) by rewinding one record when needed. Reads
        go through the chunked read-ahead buffer: the unbuffered version
        paid two syscalls per skipped record, which made every cursor
        reposition O(segment records) in syscalls."""
        assert self._f is not None
        size = os.fstat(self._f.fileno()).st_size
        prev_off: Optional[int] = None
        while True:
            hdr = self._read_at(self._offset, _REC_HEAD.size)
            if len(hdr) < _REC_HEAD.size:
                break  # tail — nothing at/after since yet
            start_seq, blen, _crc = _REC_HEAD.unpack(hdr)
            if start_seq >= self._since:
                if start_seq > self._since and prev_off is not None:
                    # possible straddler: include the previous record iff
                    # its range reaches since (one body decode, once)
                    p_hdr = self._read_at(prev_off, _REC_HEAD.size)
                    p_seq, p_blen, _ = _REC_HEAD.unpack(p_hdr)
                    body = self._read_at(prev_off + _REC_HEAD.size, p_blen)
                    if len(body) == p_blen:
                        from .records import decode_batch

                        if p_seq + decode_batch(body).count() - 1 >= self._since:
                            self._offset = prev_off
                break
            if self._offset + _REC_HEAD.size + blen > size:
                break  # torn/in-flight tail record
            prev_off = self._offset
            self._offset += _REC_HEAD.size + blen

    def _roll_if_closed(self) -> bool:
        """At EOF: if the writer rolled to a newer segment, the current
        one is final — advance. Returns True when a new segment was
        opened (caller should retry reading). Guarded so the common
        live-tail hit costs one fstat, NOT a directory listing: a
        SIZE-triggered roll never happens below segment_bytes. A
        re-created WalWriter on an existing dir, however, starts a new
        segment regardless of the old one's size, so every 32nd
        consecutive tail hit does the real listing anyway — bounded
        staleness instead of a silently parked-forever cursor."""
        if self._first_seq is None or self._f is None:
            return False
        if self._segment_bytes is not None:
            self._eof_hits += 1
            if self._eof_hits & 0x1F:
                try:
                    size = os.fstat(self._f.fileno()).st_size
                    if size < self._segment_bytes:
                        return False
                except OSError:
                    pass
        segs = _segments(self._dir)
        newer = [s for s in segs if s[0] > self._first_seq]
        if not newer:
            return False
        self._open_segment(min(newer))
        return True

    def _read_at(self, off: int, n: int) -> bytes:
        """Bytes [off, off+n) of the current segment through the
        read-ahead buffer (one big read per ~chunk of records instead of
        seek+read syscalls per record). Short result = live tail; a
        later call from the same offset re-reads and sees new appends."""
        end = off + n
        if off < self._buf_off or end > self._buf_off + len(self._buf):
            f = self._f
            f.seek(off)
            self._buf = f.read(max(n, self._CHUNK))
            self._buf_off = off
        rel = off - self._buf_off
        return self._buf[rel:rel + n]

    def read_next(self) -> Optional[Tuple[int, bytes]]:
        """Next complete record, or None at the live tail (cursor stays
        valid — call again after more appends)."""
        if not self._positioned and not self._position():
            return None
        while True:
            if self._f is None:
                return None
            hdr = self._read_at(self._offset, _REC_HEAD.size)
            if len(hdr) < _REC_HEAD.size:
                if self._roll_if_closed():
                    continue
                return None
            start_seq, blen, crc = _REC_HEAD.unpack(hdr)
            body = self._read_at(self._offset + _REC_HEAD.size, blen)
            if len(body) < blen:
                # in-flight append (writer flushed header before body);
                # only legitimate at the ACTIVE tail — if the writer
                # already rolled onward, it's a truncated closed segment
                if self._roll_if_closed():
                    continue
                return None
            if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                raise Corruption(
                    f"WAL crc mismatch under tail cursor in segment "
                    f"wal-{self._first_seq}.log at offset {self._offset}"
                )
            self._offset += _REC_HEAD.size + blen
            self._yielded_any = True
            self._eof_hits = 0
            return start_seq, body

    def read_many(self, max_records: int) -> List[Tuple[int, bytes]]:
        """Up to ``max_records`` complete records in one call. Records
        already resident in the read-ahead buffer are parsed in a tight
        loop (one struct unpack + one slice per record) instead of two
        ``_read_at`` round-trips each — the replication serve path reads
        whole responses at a time, and the per-record call overhead was
        a measurable share of serve CPU under pipelined load. Falls back
        to ``read_next`` for refills, rolls, and the live tail."""
        out: List[Tuple[int, bytes]] = []
        head = _REC_HEAD
        hsize = head.size
        while len(out) < max_records:
            buf = self._buf
            end = len(buf)
            rel = self._offset - self._buf_off
            if self._f is not None and 0 <= rel < end:
                while len(out) < max_records and rel + hsize <= end:
                    start_seq, blen, crc = head.unpack_from(buf, rel)
                    if rel + hsize + blen > end:
                        break  # record straddles the buffer edge
                    body = buf[rel + hsize:rel + hsize + blen]
                    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                        self._offset = self._buf_off + rel
                        raise Corruption(
                            f"WAL crc mismatch under tail cursor in segment "
                            f"wal-{self._first_seq}.log at offset {self._offset}"
                        )
                    rel += hsize + blen
                    out.append((start_seq, body))
                self._offset = self._buf_off + rel
                if out:
                    self._yielded_any = True
                    self._eof_hits = 0
                if len(out) >= max_records:
                    break
            rec = self.read_next()  # refill / roll / tail
            if rec is None:
                break
            out.append(rec)
        return out


def purge_obsolete(
    wal_dir: str,
    persisted_seq: int,
    ttl_seconds: float,
    now: Optional[float] = None,
    archive_sink=None,
) -> int:
    """Delete segments that are (a) fully persisted into SSTs AND (b) older
    than the TTL. Keeping flushed WAL for the TTL is what lets followers
    catch up from the leader's log (reference WAL TTL). Returns count.

    ``archive_sink(path)`` (storage.archive.WalArchiver.sink) is called on
    each sealed segment BEFORE deletion — point-in-time restore replays
    the archive over a checkpoint. A sink failure stops the purge and
    keeps the segment: history is never destroyed un-archived."""
    now = time.time() if now is None else now
    segs = _segments(wal_dir)
    removed = 0
    for i, (first_seq, path) in enumerate(segs):
        if i + 1 >= len(segs):
            break  # never delete the active (last) segment
        next_first = segs[i + 1][0]
        if next_first - 1 > persisted_seq:
            break  # contains unpersisted updates
        if now - os.path.getmtime(path) < ttl_seconds:
            break
        if archive_sink is not None:
            try:
                archive_sink(path)
            except Exception:
                logging.getLogger(__name__).exception(
                    "WAL archive of %s failed; keeping segment", path)
                break
        os.remove(path)
        removed += 1
    return removed


def latest_seq(wal_dir: str) -> int:
    """Highest sequence number present in the WAL (0 if empty)."""
    last = 0
    for start_seq, body in iter_updates(wal_dir, 0, truncate_torn=False):
        from .records import decode_batch

        last = start_seq + decode_batch(body).count() - 1
    return last
