"""Storage engine error taxonomy."""


class StorageError(Exception):
    pass


class NotFoundError(StorageError):
    pass


class Corruption(StorageError):
    pass


class InvalidArgument(StorageError):
    pass
