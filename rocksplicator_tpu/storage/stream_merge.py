"""Streaming bounded-memory compaction: the chunked k-way lane merge.

The round-9 array compaction pipeline (native_compaction.py) decodes
EVERY input run into RAM before resolving — the last O(dataset)
allocation in the engine (fine at 200k entries, an OOM at production
level sizes). This module replaces that merge with a streaming pipeline
whose working set is a fixed budget regardless of level size, the shape
Co-KV (arxiv 1807.04151) and LUDA (arxiv 2004.03054) use for
host/device compaction offload:

- each input run is read through a fixed-size lane *window*
  (tpu/format.SstBlockLaneSource — block-granular decode-on-demand,
  probing but never filling the decoded-block LRU);
- the merge advances in *chunks*: the cut key is the minimum loaded
  frontier over runs that still have undecoded blocks, so every key
  strictly below the cut is fully loaded in every run and one
  merge-resolve call sees each key's whole entry stack — per-key
  resolution is byte-identical to the unsliced pass by construction;
- when a single key's entry group spans a window boundary (a giant
  MERGE-operand chain, a dup-key run, a tombstone stack crossing
  blocks), its loaded rows are CARRIED raw across the chunk boundary
  and resolved together with the rest of the group once the cut passes
  the key — the straddle-state the slice-boundary matrix pins;
- resolved chunks stream into a per-file buffer that reproduces the
  unsliced sink's file splits exactly (same lazy width derivation, same
  entries-per-file arithmetic), so outputs are byte-identical
  file-for-file, emitted as input windows drain — and still installed
  by the engine as ONE atomic generation;
- a pluggable ChunkResolver runs the resolve: the CPU resolver is the
  shared native/numpy merge-resolve; the TPU resolver
  (tpu/compaction_service.TpuChunkResolver) launches the device kernel
  and materializes one chunk BEHIND the decode — decode of chunk
  N+1 overlaps chunk N's device→host transfer (the double-buffered
  chunk shape the silicon bench needs; the resolve itself still syncs
  at submit — see TpuChunkResolver's honest-scope note).

The ceiling is load-bearing: :class:`CompactionMemoryBudget`
(``RSTPU_COMPACT_MEM_BUDGET`` / DBOptions.compaction_memory_budget_bytes)
sizes the windows, window sizes HALVE while the process is over budget
(degrade, never abort), and the per-compaction high-water feeds the
``compaction.peak_bytes_materialized`` gauge the acceptance test
asserts against. Failpoint seams ``compact.stream.chunk`` /
``compact.stream.refill`` make the crash-at-any-chunk story testable:
no output is ever installed unless the whole pipeline finishes, so a
kill at any seam leaves reopen exactly pre-compaction.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..observability.span import start_span
from ..testing import failpoints as fp
from ..utils.stats import Stats

_PUT, _DELETE, _MERGE = 1, 2, 3

# window/chunk lanes carry both key byte orders (the TPU resolver wants
# LE for bloom hashing); the CPU resolver concatenates only CPU_FIELDS
from ..ops.kv_format import LANE_FIELDS as FIELDS  # noqa: E402

CPU_FIELDS = tuple(f for f in FIELDS if f != "key_words_le")

# --- knobs (README "Tuning") ---------------------------------------------
# per-refill window target in entries; the chunk the resolver sees is
# roughly nruns windows
ENV_CHUNK_ENTRIES = "RSTPU_COMPACT_CHUNK_ENTRIES"
DEFAULT_CHUNK_ENTRIES = 1 << 16
# process-wide hard ceiling on live compaction lane bytes
ENV_MEM_BUDGET = "RSTPU_COMPACT_MEM_BUDGET"
DEFAULT_MEM_BUDGET = 256 << 20
# "auto" streams when the projected in-RAM working set exceeds the
# budget (or the direct path's entry cap); "1"/"always" streams every
# streamable full compaction; "0"/"never" disables streaming
ENV_STREAM_MODE = "RSTPU_COMPACT_STREAM"
# window degradation floor (block granularity still applies above it)
MIN_WINDOW_ENTRIES = 256

# test/chaos overrides (same pattern as native_compaction's
# MIN_SLICE_ENTRIES: chaos lowers the scale so streaming and its seams
# are reachable on tiny chaos memtables)
STREAM_MODE_OVERRIDE: Optional[str] = None
CHUNK_ENTRIES_OVERRIDE: Optional[int] = None


def stream_mode() -> str:
    if STREAM_MODE_OVERRIDE is not None:
        return STREAM_MODE_OVERRIDE
    raw = os.environ.get(ENV_STREAM_MODE, "auto").lower()
    if raw in ("0", "never", "false"):
        return "never"
    if raw in ("1", "always", "true"):
        return "always"
    return "auto"


def default_chunk_entries() -> int:
    if CHUNK_ENTRIES_OVERRIDE is not None:
        return int(CHUNK_ENTRIES_OVERRIDE)
    try:
        return max(MIN_WINDOW_ENTRIES,
                   int(os.environ.get(ENV_CHUNK_ENTRIES,
                                      DEFAULT_CHUNK_ENTRIES)))
    except ValueError:
        return DEFAULT_CHUNK_ENTRIES


class _StreamDecline(Exception):
    """The inputs turned out inexpressible mid-stream (width drift, a
    MERGE record without an operator, kernel fallback flag): clean up
    every written output and let the caller take the non-streaming
    path."""


class CompactionMemoryBudget:
    """Process-wide ceiling on live compaction lane bytes. One instance
    serves every DB in the process (concurrent compactions share RAM
    the way they share the disk); per-compaction accounting hangs off
    :meth:`tracker`."""

    _instance: Optional["CompactionMemoryBudget"] = None
    _instance_lock = threading.Lock()

    def __init__(self, budget_bytes: int):
        self.budget_bytes = max(1, int(budget_bytes))
        self._lock = threading.Lock()
        self._live = 0

    @classmethod
    def get(cls) -> "CompactionMemoryBudget":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    try:
                        cap = int(os.environ.get(
                            ENV_MEM_BUDGET, DEFAULT_MEM_BUDGET))
                    except ValueError:
                        cap = DEFAULT_MEM_BUDGET
                    cls._instance = cls(cap)
        return cls._instance

    @classmethod
    def reset_for_test(cls, budget_bytes: Optional[int] = None) -> None:
        with cls._instance_lock:
            cls._instance = (
                cls(budget_bytes) if budget_bytes is not None else None)

    def _add(self, nbytes: int) -> None:
        with self._lock:
            self._live += nbytes

    def _sub(self, nbytes: int) -> None:
        with self._lock:
            self._live -= nbytes

    def live_bytes(self) -> int:
        with self._lock:
            return self._live

    def tracker(self) -> "MemTracker":
        return MemTracker(self)


class MemTracker:
    """Per-compaction view onto the process budget: live bytes, the
    high-water mark the ``compaction.peak_bytes_materialized`` gauge
    reports, and release back to the process counter on close()."""

    def __init__(self, budget: CompactionMemoryBudget):
        self._budget = budget
        self._lock = threading.Lock()
        self.live = 0
        self.peak = 0

    def add(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._lock:
            self.live += nbytes
            if self.live > self.peak:
                self.peak = self.live
        self._budget._add(nbytes)

    def sub(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._lock:
            self.live -= nbytes
        self._budget._sub(nbytes)

    def process_live(self) -> int:
        return self._budget.live_bytes()

    @property
    def budget_bytes(self) -> int:
        return self._budget.budget_bytes

    def close(self) -> None:
        """Release any residual accounting (windows alive at pipeline
        exit) back to the process counter; peak is preserved."""
        with self._lock:
            residual, self.live = self.live, 0
        if residual:
            self._budget._sub(residual)


def _lanes_nbytes(lanes: dict) -> int:
    return int(sum(np.asarray(a).nbytes for a in lanes.values()))


def _row_key(win: dict, i: int, klen: int) -> bytes:
    return win["key_words_be"][i].astype(">u4").tobytes()[:klen]


def _first_ge(win: dict, lo: int, hi: int, key: bytes, klen: int) -> int:
    while lo < hi:
        mid = (lo + hi) // 2
        if _row_key(win, mid, klen) < key:
            lo = mid + 1
        else:
            hi = mid
    return lo


def _first_gt(win: dict, lo: int, hi: int, key: bytes, klen: int) -> int:
    while lo < hi:
        mid = (lo + hi) // 2
        if _row_key(win, mid, klen) <= key:
            lo = mid + 1
        else:
            hi = mid
    return lo


class _RunCursor:
    """One input run's decode window: a block-granular slice of its lane
    image, refilled as the merge frontier drains it."""

    def __init__(self, source, vw: int, klen: int, tracker: MemTracker):
        self._src = source
        self._vw = vw
        self._klen = klen
        self._tracker = tracker
        self._next_block = 0
        self._win: Optional[dict] = None
        self._pos = 0
        self._n = 0
        self.win_bytes = 0

    @property
    def file_done(self) -> bool:
        return self._next_block >= self._src.num_blocks

    @property
    def empty(self) -> bool:
        return self._pos >= self._n

    @property
    def exhausted(self) -> bool:
        return self.empty and self.file_done

    def refill(self, target_entries: int) -> int:
        """Replace the drained window with >= target_entries fresh rows
        (block granular; only ever called on an EMPTY cursor — a
        stalled cut's unconsumed rows move out via take_eq, not by
        extending the window). Returns the RETIRED byte count of the
        replaced window — the pipeline defers releasing it until the
        in-flight chunk holding views of it has been collected."""
        fp.hit("compact.stream.refill")
        Stats.get().incr("compaction.stream_refills")
        parts: List[dict] = []
        rows = 0
        while self._next_block < self._src.num_blocks \
                and rows < target_entries:
            lanes = self._src.decode_blocks(
                self._next_block, self._next_block + 1)
            self._next_block += 1
            w = lanes["val_words"].shape[1]
            if w < self._vw:
                lanes["val_words"] = np.pad(
                    lanes["val_words"], [(0, 0), (0, self._vw - w)])
            rows += lanes["key_len"].shape[0]
            parts.append(lanes)
        retired = self.win_bytes
        if len(parts) == 1:
            self._win = parts[0]
        else:
            self._win = {f: np.concatenate([p[f] for p in parts])
                         for f in FIELDS}
        self._pos = 0
        self._n = self._win["key_len"].shape[0]
        self.win_bytes = _lanes_nbytes(self._win)
        self._tracker.add(self.win_bytes)
        return retired

    def frontier_key(self) -> bytes:
        """Last loaded key: every undecoded row of this run is >= it."""
        return _row_key(self._win, self._n - 1, self._klen)

    def take_lt(self, cut: Optional[bytes]) -> Optional[dict]:
        """Consume rows with key < cut (all remaining rows when cut is
        None); returns a lane-slice view or None."""
        if self.empty:
            return None
        hi = self._n if cut is None else _first_ge(
            self._win, self._pos, self._n, cut, self._klen)
        if hi <= self._pos:
            return None
        sl = {f: self._win[f][self._pos:hi] for f in FIELDS}
        self._pos = hi
        return sl

    def take_eq(self, cut: bytes) -> Optional[dict]:
        """Consume rows with key == cut as a COPY (carry rows must not
        pin a window the next refill retires)."""
        if self.empty:
            return None
        lo = _first_ge(self._win, self._pos, self._n, cut, self._klen)
        hi = _first_gt(self._win, lo, self._n, cut, self._klen)
        if hi <= lo:
            return None
        sl = {f: self._win[f][lo:hi].copy() for f in FIELDS}
        self._pos = hi
        return sl

    def release(self) -> None:
        self._tracker.sub(self.win_bytes)
        self.win_bytes = 0
        self._win = None


class CpuChunkResolver:
    """The shared native/numpy merge-resolve, run synchronously — one
    chunk in flight at a time (``pipelined = False``: the pipeline
    collects each chunk immediately, so consumed windows release before
    the next refill instead of staying pinned a whole extra chunk the
    way the device double buffer requires)."""

    fields = CPU_FIELDS
    pipelined = False

    def submit(self, parts: List[dict], lanes: dict, total: int, vw: int,
               merge_op, drop_tombstones: bool):
        from .native_compaction import NativeCompactionBackend

        return NativeCompactionBackend._resolve(
            parts, lanes, total, vw, merge_op, drop_tombstones)

    def collect(self, handle) -> Tuple[dict, int]:
        return handle


class _FileBufferSink:
    """Streaming output sink byte-identical to write_resolved_lanes:
    resolved chunks buffer per OUTPUT FILE (bounded by
    target_file_bytes, not dataset size) and each file writes through
    the same planar writer + bulk bloom with the same width derivation
    — klen from the first resolved row, vlen from the first non-delete
    resolved row — so file splits and bytes match the unsliced pass
    exactly."""

    def __init__(self, path_factory, block_bytes: int, compression: int,
                 bits_per_key: int, target_file_bytes: int,
                 tracker: MemTracker, io_budget=None,
                 plan_klen: int = 0, plan_vlen: int = 0):
        self._pf = path_factory
        self._block_bytes = block_bytes
        self._compression = compression
        self._bits_per_key = bits_per_key
        self._target_file_bytes = target_file_bytes
        self._tracker = tracker
        self._io_budget = io_budget
        self._plan_klen = plan_klen
        self._plan_vlen = plan_vlen
        self._buf: List[dict] = []
        self._buf_rows = 0
        self._buf_bytes = 0
        self._klen: Optional[int] = None
        self._vlen: Optional[int] = None
        self._epf = 0  # entries per file, once widths are known
        self._block_entries = 0
        self.outputs: List[Tuple[str, dict]] = []

    def _derive_widths(self, arrays: dict, count: int) -> None:
        from ..tpu.format import planar_stride

        if self._klen is None and count:
            self._klen = int(arrays["key_len"][0])
        if self._vlen is None:
            non_del = np.flatnonzero(arrays["vtype"][:count] != _DELETE)
            if len(non_del):
                self._vlen = int(arrays["val_len"][int(non_del[0])])
        if self._klen is not None and self._vlen is not None \
                and not self._epf:
            stride = planar_stride(self._klen, self._vlen)
            self._epf = max(
                1024, self._target_file_bytes // max(1, stride))
            self._block_entries = max(
                64, self._block_bytes // max(1, stride))

    def append(self, arrays: dict, count: int) -> None:
        if count == 0:
            return
        # trimmed rows COPY out of the resolver's chunk-sized output:
        # a [:count] view would pin the full base allocation (pow2-
        # padded on the TPU resolver) while the tracker counted only
        # the view — under heavy dedup the untracked bases would dwarf
        # the ceiling. count == base rows keeps the whole-array view.
        sub = {}
        for f in CPU_FIELDS:
            a = np.asarray(arrays[f])
            sub[f] = a if a.shape[0] == count else a[:count].copy()
        self._buf.append(sub)
        self._buf_rows += count
        nb = _lanes_nbytes(sub)
        self._buf_bytes += nb
        self._tracker.add(nb)
        self._derive_widths(sub, count)
        # vlen stays unknown while the resolved stream is all-tombstone
        # (drop_tombstones=False): buffer until a value appears — the
        # unsliced pass derives vlen from the SAME first non-delete row,
        # and splitting earlier would diverge from its file boundaries.
        # That wait must not defeat the ceiling: once a full file's
        # worth (by the PLANNED value width, which every later
        # non-delete row is width-checked to match) is buffered, seed
        # vlen from the plan. Any stream with a value ANYWHERE is still
        # byte-identical — the unsliced pass would retroactively use
        # the same vlen for this prefix; only a 100%-tombstone output
        # larger than one file now splits by the planned width instead
        # of the degenerate vlen=0 (same entries, bounded memory — the
        # honest trade, noted in PARITY).
        if not self._epf and self._vlen is None:
            from ..tpu.format import planar_stride

            stride = planar_stride(self._plan_klen, self._plan_vlen)
            plan_epf = max(1024,
                           self._target_file_bytes // max(1, stride))
            if self._buf_rows >= plan_epf:
                self._vlen = self._plan_vlen
                self._derive_widths(sub, count)
        while self._epf and self._buf_rows >= self._epf:
            self._flush_file(self._epf)

    def _pop_rows(self, n: int) -> dict:
        taken: List[dict] = []
        need = n
        while need > 0:
            head = self._buf[0]
            hn = head["key_len"].shape[0]
            if hn <= need:
                taken.append(self._buf.pop(0))
                need -= hn
            else:
                taken.append({f: head[f][:need] for f in CPU_FIELDS})
                self._buf[0] = {f: head[f][need:] for f in CPU_FIELDS}
                need = 0
        self._buf_rows -= n
        if len(taken) == 1:
            return taken[0]
        return {f: np.concatenate([p[f] for p in taken])
                for f in CPU_FIELDS}

    def _flush_file(self, n: int) -> None:
        from .native_compaction import NativeCompactionBackend
        from ..tpu.format import write_sst_from_arrays

        sub = self._pop_rows(n)
        bloom = NativeCompactionBackend._bulk_bloom(
            sub, n, self._klen, self._bits_per_key)
        path = self._pf()
        props = write_sst_from_arrays(
            sub, n, path,
            bloom_words=bloom.words,
            block_entries=self._block_entries,
            compression=self._compression,
            bits_per_key=self._bits_per_key,
            planar=True,
        )
        if props is None:
            # widths the planar layout can't express slipped past the
            # window checks — decline, caller takes the non-stream path
            raise _StreamDecline("planar sink declined a file slice")
        self.outputs.append((path, props))
        # accounting: written rows leave the buffer
        remaining = _lanes_nbytes_list(self._buf)
        self._tracker.sub(self._buf_bytes - remaining)
        self._buf_bytes = remaining
        if self._io_budget is not None:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            if size:
                self._io_budget.throttle(size)

    def finish(self) -> List[Tuple[str, dict]]:
        if self._buf_rows:
            if not self._epf:
                # an all-tombstone resolved stream (kept tombstones,
                # no values): vlen degenerates to 0, as the unsliced
                # width derivation does
                self._vlen = 0 if self._vlen is None else self._vlen
                self._klen = (int(self._buf[0]["key_len"][0])
                              if self._klen is None else self._klen)
                self._derive_widths(self._buf[0],
                                    self._buf[0]["key_len"].shape[0])
            while self._buf_rows > self._epf:
                self._flush_file(self._epf)
            if self._buf_rows:
                self._flush_file(self._buf_rows)
        return self.outputs

    def abandon(self) -> None:
        """Sweep every written output (nothing would ever GC them)."""
        self._tracker.sub(self._buf_bytes)
        self._buf = []
        self._buf_bytes = 0
        self._buf_rows = 0
        for p, _ in self.outputs:
            try:
                os.remove(p)
            except OSError:
                pass
        self.outputs = []


def _lanes_nbytes_list(parts: List[dict]) -> int:
    return int(sum(_lanes_nbytes(p) for p in parts))


def _check_chunk_semantics(lanes: dict, merge_op) -> None:
    """The lanes_resolvable() preconditions, applied per chunk instead
    of per dataset (probes promise widths; vtype content can only be
    checked once decoded)."""
    if merge_op is None:
        if bool((lanes["vtype"] == _MERGE).any()):
            raise _StreamDecline("MERGE records without an operator")
    else:
        is_del = lanes["vtype"] == _DELETE
        vl = lanes["val_len"][~is_del]
        if len(vl) and not (vl == 8).all():
            raise _StreamDecline("uint64add needs 8-byte values")


def plan_stream(runs, merge_op):
    """Probe every run for block-granular streamability. Returns
    (sources, total, klen, vlen, vw) or None when any run can't stream
    or the runs' widths are incompatible (the in-RAM path decides for
    itself — it has its own declines)."""
    from ..tpu.format import SstBlockLaneSource

    sources = []
    for run in runs:
        if not hasattr(run, "iterate"):
            return None
        src = SstBlockLaneSource.probe(run)
        if src is None:
            return None
        sources.append(src)
    if not sources:
        return None
    klens = {s.klen for s in sources}
    vlens = {s.vlen for s in sources}
    if len(klens) != 1 or len(vlens) != 1:
        return None
    klen, vlen = klens.pop(), vlens.pop()
    if merge_op is not None and vlen != 8:
        return None
    total = sum(s.num_entries for s in sources)
    if total == 0:
        return None
    vw = max(2, (vlen + 3) // 4)
    return sources, total, klen, vlen, vw


def est_row_bytes(vw: int) -> int:
    """Lane bytes per decoded window row (both key byte orders + the
    scalar lanes + the value words)."""
    return 68 + 4 * vw


def maybe_stream_merge(
    runs: List,
    merge_op,
    drop_tombstones: bool,
    path_factory,
    block_bytes: int,
    compression: int,
    bits_per_key: int,
    target_file_bytes: int,
    io_budget=None,
    mem_tracker: Optional[MemTracker] = None,
    memory_budget_bytes: int = 0,
    resolver=None,
) -> Optional[List[Tuple[str, dict]]]:
    """Run the streaming pipeline when the mode and the inputs call for
    it. Returns [(path, props)] (possibly []) on success, None when the
    caller should take the in-RAM/tuple path (not streamable, below the
    auto threshold, mode off, or declined mid-stream — any written
    outputs are swept before returning)."""
    mode = stream_mode()
    if mode == "never":
        return None
    plan = plan_stream(runs, merge_op)
    if plan is None:
        return None
    sources, total, klen, vlen, vw = plan
    budget = CompactionMemoryBudget.get()
    budget_bytes = int(memory_budget_bytes) or budget.budget_bytes
    if mode == "auto":
        from .native_compaction import MAX_DIRECT_ENTRIES

        # the in-RAM path holds per-run parts PLUS their concatenation
        projected = 2 * total * est_row_bytes(vw)
        if projected <= budget_bytes and total <= MAX_DIRECT_ENTRIES:
            return None
    from ..ops.kv_format import UnsupportedBatch

    tracker = mem_tracker or budget.tracker()
    try:
        return _run_pipeline(
            sources, total, klen, vlen, vw, merge_op, drop_tombstones,
            path_factory, block_bytes, compression, bits_per_key,
            target_file_bytes, io_budget, tracker, budget_bytes,
            resolver or CpuChunkResolver())
    except (UnsupportedBatch, _StreamDecline) as e:
        Stats.get().incr("compaction.stream_declines")
        logging.getLogger(__name__).info(
            "streaming merge declined (%s); using in-RAM path", e)
        return None
    finally:
        tracker.close()


def _run_pipeline(
    sources, total: int, klen: int, vlen: int, vw: int, merge_op,
    drop_tombstones: bool, path_factory, block_bytes: int,
    compression: int, bits_per_key: int, target_file_bytes: int,
    io_budget, tracker: MemTracker, budget_bytes: int, resolver,
) -> List[Tuple[str, dict]]:
    from .compaction_scheduler import adaptive_chunk_entries

    nruns = len(sources)
    row_bytes = est_row_bytes(vw)
    chunk_target = default_chunk_entries()
    sink = _FileBufferSink(
        path_factory, block_bytes, compression, bits_per_key,
        target_file_bytes, tracker, io_budget=io_budget,
        plan_klen=klen, plan_vlen=vlen)
    cursors = [_RunCursor(s, vw, klen, tracker) for s in sources]
    carry_parts: List[dict] = []
    carry_key: Optional[bytes] = None
    pending = None           # in-flight resolver handle (double buffer)
    pending_release = 0      # retired window bytes pinned by `pending`
    retired_bytes = 0        # retired windows the NEXT submit will pin
    try:
        with start_span("compact.stream", runs=nruns, entries=total,
                        budget_bytes=budget_bytes):
            while True:
                # window sizing from the ACTUAL headroom left under the
                # ceiling — live bytes already count the sink's file
                # buffer, the in-flight chunk, and windows the double
                # buffer still pins, so refills shrink as any of them
                # grow (degrade, never abort: the floor is one block's
                # granularity). Stall pressure shrinks the chunk too
                # (compaction should hold LESS memory precisely while
                # admissions are being delayed).
                eff_chunk = adaptive_chunk_entries(chunk_target, io_budget)
                headroom = budget_bytes - tracker.process_live()
                # /5: a window generation coexists with its chunk
                # CONCAT copy (same size), the resolved chunk, the
                # sink's file buffer, and (pipelined) the previous
                # generation the double buffer still pins — plus
                # block-granularity rounding on every refill
                w_budget = (headroom // 5) // max(1, nruns * row_bytes)
                w = max(MIN_WINDOW_ENTRIES,
                        min(eff_chunk // max(1, nruns), w_budget))
                for c in cursors:
                    if c.empty and not c.file_done:
                        retired_bytes += c.refill(w)
                cut: Optional[bytes] = None
                for c in cursors:
                    if not c.empty and not c.file_done:
                        k = c.frontier_key()
                        if cut is None or k < cut:
                            cut = k
                parts: List[dict] = []
                if carry_key is not None and (
                        cut is None or carry_key < cut):
                    parts.extend(carry_parts)
                    retired_bytes += _lanes_nbytes_list(carry_parts)
                    carry_parts, carry_key = [], None
                for c in cursors:
                    sl = c.take_lt(cut)
                    if sl is not None:
                        parts.append(sl)
                if not parts:
                    if cut is None:
                        break  # every run exhausted, no carry left
                    # stall: the cut key's group spans the bounding
                    # run's window end — carry its loaded rows raw and
                    # refill before cutting again
                    for c in cursors:
                        sl = c.take_eq(cut)
                        if sl is not None:
                            carry_parts.append(sl)
                            tracker.add(_lanes_nbytes(sl))
                    carry_key = cut
                    continue
                fp.hit("compact.stream.chunk")
                Stats.get().incr("compaction.stream_chunks")
                lanes = {
                    f: np.concatenate([p[f] for p in parts])
                    if len(parts) > 1 else parts[0][f]
                    for f in resolver.fields
                }
                # the multi-part concatenation is a real second copy of
                # the consumed window rows (the in-RAM path counts the
                # same 2x for the same reason); it lives through
                # submit() and is accounted for that span
                concat_bytes = (_lanes_nbytes(lanes)
                                if len(parts) > 1 else 0)
                tracker.add(concat_bytes)
                chunk_n = int(lanes["key_len"].shape[0])
                _check_chunk_semantics(lanes, merge_op)

                def drain_pending():
                    nonlocal pending, pending_release
                    if pending is None:
                        return
                    arrays, count = resolver.collect(pending)
                    sink.append(arrays, count)
                    tracker.sub(pending_release)
                    pending, pending_release = None, 0

                drain_pending()
                pending = resolver.submit(
                    parts, lanes, chunk_n, vw, merge_op, drop_tombstones)
                # both resolvers fully consume the concat inside
                # submit() (CPU resolves it, TPU ships it to device and
                # syncs) — drop our references WITH the accounting, on
                # the pipelined path too, so the freed bytes and the
                # tracker agree before the next window sizing
                tracker.sub(concat_bytes)
                del parts, lanes
                # windows retired before this submit stay pinned by the
                # chunk's views until it is collected
                pending_release, retired_bytes = retired_bytes, 0
                if not getattr(resolver, "pipelined", True):
                    # synchronous resolver: nothing overlaps, release
                    # the consumed windows before the next refill
                    drain_pending()
            if pending is not None:
                arrays, count = resolver.collect(pending)
                sink.append(arrays, count)
                tracker.sub(pending_release)
                pending_release = 0
            outputs = sink.finish()
            Stats.get().incr("compaction.stream_merges")
            return outputs
    except BaseException:
        sink.abandon()
        raise
    finally:
        for c in cursors:
            c.release()
