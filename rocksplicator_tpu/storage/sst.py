"""TSST — the sorted-string-table file format.

Reference: RocksDB SST files (the engine's persistent sorted runs), incl.
the properties the admin plane reads and the ``global_seqno`` mechanism
used by ``IngestExternalFile`` (admin_handler.cpp:1819-1827 ingests with
``allow_global_seqno``).

Layout (all little-endian):

    [data block 0] ... [data block N-1]
    [bloom block]
    [index block]     per block: varstr last_key, u64 offset, u32 size, u8 compressed
    [props JSON]
    [footer]          fixed size, see _FOOTER

Data block entry: u32 key_len, key, u64 seq, u8 vtype, u32 val_len, val —
entries strictly sorted by (key asc, seq desc). Blocks optionally
zlib-compressed (standing in for the reference's Snappy/ZSTD block
compression; the codec byte keeps the format open for a TPU-side encoder).

A file-level ``global_seqno`` overrides per-entry seqs at read time —
exactly how ingestion assigns sequence numbers without rewriting the file.
"""

from __future__ import annotations

import bisect
import itertools
import json
import os
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

from ..testing import failpoints as fp
from ..utils.stats import Stats
from . import rlz
from .bloom import BloomFilter
from .errors import Corruption, InvalidArgument
from .records import OpType

MAGIC = b"TSSTv1\x00\x00"
_FOOTER = struct.Struct("<QQQQIQB8s")  # bloom_off, index_off, props_off,
# global_seqno, num_blocks, num_entries, flags, magic
_ENTRY_HEAD = struct.Struct("<I")
_ENTRY_META = struct.Struct("<QBI")
_INDEX_ENTRY = struct.Struct("<QIB")

COMPRESSION_NONE = 0
COMPRESSION_ZLIB = 1
# PLANAR block encodings (storage/planar.py): struct-of-array u32 planes
# instead of an entry byte stream. Same index/footer container; the codec
# nibble selects decoding per block.
BLOCK_PLANAR = 2
BLOCK_PLANAR_ZLIB = 3
# RLZ1 (storage/rlz.py + native rlz_compress): the fast owned codec —
# snappy-class speed for the ingest path where zlib's CPU cost bites
# (the reference's Snappy/ZSTD block compression analog)
COMPRESSION_RLZ = 4
BLOCK_PLANAR_RLZ = 5

# bytes per entry besides key+value: u32 klen, u64 seq, u8 vtype, u32 vlen
ENTRY_FIXED_OVERHEAD = _ENTRY_HEAD.size + _ENTRY_META.size

FLAG_HAS_GLOBAL_SEQNO = 1

# ---------------------------------------------------------------------------
# Decoded-block cache
# ---------------------------------------------------------------------------

# Default budget for the process-global decoded-block LRU. Every `get`
# that touches an SST used to re-read AND re-decompress its block from
# disk; the cache holds decompressed (checksum-verified) block payloads.
# Env-tunable: RSTPU_BLOCK_CACHE_BYTES=0 disables, any other value is the
# byte budget. (rocksdb analog: block_cache / LRUCache.)
BLOCK_CACHE_DEFAULT_BYTES = 32 << 20
_BLOCK_CACHE_ENV = "RSTPU_BLOCK_CACHE_BYTES"

_cache_tokens = itertools.count(1)


class BlockCache:
    """Byte-budgeted process-global LRU of decompressed data blocks,
    keyed by (reader token, block index). Per-reader tokens — not paths —
    key the entries, so a file GC'd and a new file reusing its name can
    never alias; SSTReader.close() drops its token's entries (file GC
    closes readers, which is the invalidation hook).

    Counters on /stats: ``storage.block_cache.hit`` for every cache-served
    block, ``storage.block_cache.miss`` for point-read fills. Bulk scans
    (compaction sources, iterators) probe the cache but do not fill or
    count misses — they would evict the working set and skew the rate
    (rocksdb's fill_cache=false convention)."""

    _instance: Optional["BlockCache"] = None
    _disabled = False
    _instance_lock = threading.Lock()

    def __init__(self, capacity_bytes: int):
        self.capacity = int(capacity_bytes)
        self._lock = threading.Lock()
        self._blocks: "OrderedDict[Tuple[int, int], bytes]" = OrderedDict()
        self._bytes = 0
        self._by_token: Dict[int, set] = {}

    # -- singleton --------------------------------------------------------

    @classmethod
    def get_instance(cls) -> Optional["BlockCache"]:
        if cls._instance is None and not cls._disabled:
            with cls._instance_lock:
                if cls._instance is None and not cls._disabled:
                    try:
                        cap = int(os.environ.get(
                            _BLOCK_CACHE_ENV, BLOCK_CACHE_DEFAULT_BYTES))
                    except ValueError:
                        cap = BLOCK_CACHE_DEFAULT_BYTES
                    if cap > 0:
                        cls._instance = cls(cap)
                    else:
                        cls._disabled = True
        return cls._instance

    @classmethod
    def reset_for_test(cls, capacity: Optional[int] = None) -> None:
        """Drop the singleton; next use re-reads the env (or uses the
        explicit ``capacity``)."""
        with cls._instance_lock:
            cls._disabled = False
            if capacity is None:
                cls._instance = None
            elif capacity > 0:
                cls._instance = cls(capacity)
            else:
                cls._instance = None
                cls._disabled = True

    # -- cache ops --------------------------------------------------------

    def get(self, token: int, idx: int) -> Optional[bytes]:
        with self._lock:
            raw = self._blocks.get((token, idx))
            if raw is not None:
                self._blocks.move_to_end((token, idx))
            return raw

    def put(self, token: int, idx: int, raw: bytes) -> None:
        size = len(raw)
        if size > self.capacity:
            return
        with self._lock:
            key = (token, idx)
            if key in self._blocks:
                self._blocks.move_to_end(key)
                return
            self._blocks[key] = raw
            self._bytes += size
            self._by_token.setdefault(token, set()).add(idx)
            while self._bytes > self.capacity and self._blocks:
                (t, i), v = self._blocks.popitem(last=False)
                self._bytes -= len(v)
                idxs = self._by_token.get(t)
                if idxs is not None:
                    idxs.discard(i)
                    if not idxs:
                        del self._by_token[t]

    def drop(self, token: int) -> None:
        """Invalidate every block of one reader (close/file-GC hook)."""
        with self._lock:
            idxs = self._by_token.pop(token, None)
            if not idxs:
                return
            for i in idxs:
                raw = self._blocks.pop((token, i), None)
                if raw is not None:
                    self._bytes -= len(raw)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"bytes": self._bytes, "blocks": len(self._blocks),
                    "capacity": self.capacity}


def _encode_entry(key: bytes, seq: int, vtype: int, value: bytes) -> bytes:
    return (
        _ENTRY_HEAD.pack(len(key))
        + key
        + _ENTRY_META.pack(seq, vtype, len(value))
        + value
    )


class SSTWriter:
    """Writes entries in strictly ascending (key, -seq) order."""

    def __init__(
        self,
        path: str,
        block_bytes: int = 32 * 1024,
        compression: int = COMPRESSION_ZLIB,
        bits_per_key: int = 10,
    ):
        self._path = path
        self._block_bytes = block_bytes
        self._compression = compression
        self._bits_per_key = bits_per_key
        self._file = open(path, "wb")
        self._block: List[bytes] = []
        self._block_size = 0
        self._index: List[Tuple[bytes, int, int, int]] = []
        self._offset = 0
        self._keys: List[bytes] = []
        self._last_key: Optional[bytes] = None
        self._last_seq = 0
        self._num_entries = 0
        self._min_key: Optional[bytes] = None
        self._max_key: Optional[bytes] = None
        self._min_seq: Optional[int] = None
        self._max_seq = 0
        self._raw_bytes = 0
        self._finished = False

    def add(self, key: bytes, seq: int, vtype: int, value: bytes) -> None:
        if self._last_key is not None and (
            key < self._last_key or (key == self._last_key and seq >= self._last_seq)
        ):
            raise InvalidArgument(
                f"keys out of order: {key!r}@{seq} after {self._last_key!r}@{self._last_seq}"
            )
        if self._last_key != key:
            self._keys.append(key)
        self._last_key, self._last_seq = key, seq
        # entries buffer as tuples; the whole block encodes in ONE native
        # call at flush (tsst_encode_block) instead of per-entry Python
        esize = ENTRY_FIXED_OVERHEAD + len(key) + len(value)
        self._block.append((key, seq, int(vtype), value))
        self._block_size += esize
        self._raw_bytes += esize
        self._num_entries += 1
        if self._min_key is None:
            self._min_key = key
        self._max_key = key
        if self._min_seq is None or seq < self._min_seq:
            self._min_seq = seq
        self._max_seq = max(self._max_seq, seq)
        if self._block_size >= self._block_bytes:
            self._flush_block()

    def add_encoded_block(self, block_payload: bytes, last_key: bytes,
                          num_entries: int, keys: List[bytes],
                          min_key: bytes, max_key: bytes,
                          min_seq: int, max_seq: int,
                          compressed: bool, codec: Optional[int] = None
                          ) -> None:
        """Accepts a pre-encoded data block — the TPU encode kernel's output
        path: blocks arrive already packed (and optionally compressed) and
        are appended without re-serialization. ``codec`` overrides the
        compressed flag for non-entry-stream encodings (BLOCK_PLANAR*)."""
        if self._block:
            self._flush_block()
        self._file.write(block_payload)
        if codec is None:
            codec = COMPRESSION_ZLIB if compressed else COMPRESSION_NONE
        self._index.append(
            (last_key, self._offset, len(block_payload), codec)
        )
        self._offset += len(block_payload)
        self._keys.extend(keys)
        self._num_entries += num_entries
        self._raw_bytes += len(block_payload)
        if self._min_key is None:
            self._min_key = min_key
        self._max_key = max_key
        if self._min_seq is None or min_seq < self._min_seq:
            self._min_seq = min_seq
        self._max_seq = max(self._max_seq, max_seq)
        self._last_key = max_key
        self._last_seq = 0

    def _flush_block(self) -> None:
        if not self._block:
            return
        from .native.binding import NATIVE

        if NATIVE is not None:
            raw = NATIVE.encode_block(
                [e[0] for e in self._block], [e[1] for e in self._block],
                [e[2] for e in self._block], [e[3] for e in self._block],
            )
        else:
            raw = b"".join(_encode_entry(*e) for e in self._block)
        codec = self._compression
        if codec == COMPRESSION_ZLIB:
            payload = zlib.compress(raw, 1)
        elif codec == COMPRESSION_RLZ:
            payload = rlz.compress(raw)
        else:
            payload = raw
        if len(payload) >= len(raw):
            codec, payload = COMPRESSION_NONE, raw
        assert self._last_key is not None
        self._index.append((self._last_key, self._offset, len(payload), codec))
        self._file.write(payload)
        self._offset += len(payload)
        self._block = []
        self._block_size = 0

    def finish(self, global_seqno: Optional[int] = None,
               extra_props: Optional[Dict] = None,
               precomputed_bloom: Optional[BloomFilter] = None) -> Dict:
        """``precomputed_bloom`` lets a kernel-built bitmap (byte-identical
        format) be written directly — the TPU pipeline's sink path."""
        if self._finished:
            raise InvalidArgument("finish() called twice")
        self._flush_block()
        bloom_off = self._offset
        bloom = (
            precomputed_bloom if precomputed_bloom is not None
            else BloomFilter.build(self._keys, self._bits_per_key)
        )
        bloom_bytes = bloom.to_bytes()
        self._file.write(bloom_bytes)
        index_off = bloom_off + len(bloom_bytes)
        index_parts = []
        for last_key, off, size, codec in self._index:
            index_parts.append(struct.pack("<I", len(last_key)))
            index_parts.append(last_key)
            index_parts.append(_INDEX_ENTRY.pack(off, size, codec))
        index_bytes = b"".join(index_parts)
        self._file.write(index_bytes)
        props_off = index_off + len(index_bytes)
        props = {
            "num_entries": self._num_entries,
            "num_keys": len(self._keys),
            "raw_bytes": self._raw_bytes,
            "min_key": self._min_key.hex() if self._min_key is not None else None,
            "max_key": self._max_key.hex() if self._max_key is not None else None,
            "min_seq": self._min_seq or 0,
            "max_seq": self._max_seq,
        }
        if extra_props:
            props.update(extra_props)
        props_bytes = json.dumps(props).encode("utf-8")
        self._file.write(props_bytes)
        flags = FLAG_HAS_GLOBAL_SEQNO if global_seqno is not None else 0
        self._file.write(
            _FOOTER.pack(
                bloom_off, index_off, props_off,
                global_seqno if global_seqno is not None else 0,
                len(self._index), self._num_entries, flags, MAGIC,
            )
        )
        # fsync BEFORE the manifest can reference this file: the engine
        # purges WAL once the manifest is durable, so an un-fsynced SST
        # would leave a durable manifest pointing at pages power loss
        # can drop, with no WAL left to replay. (The dirent rides the
        # manifest writer's directory fsync, which happens after this.)
        self._file.flush()
        fp.hit("sst.fsync")
        os.fsync(self._file.fileno())
        self._file.close()
        # Only now is the file complete — a failure anywhere above leaves
        # _finished False so abandon() still closes and removes it.
        self._finished = True
        return props

    def abandon(self) -> None:
        if not self._finished:
            self._file.close()
            try:
                os.remove(self._path)
            except OSError:
                pass


class SSTReader:
    """Thread-safe reader: block reads use positioned pread so concurrent
    gets/iterators never race on a shared file offset."""

    def __init__(self, path: str):
        self._path = path
        self._fd = os.open(path, os.O_RDONLY)
        file_size = os.fstat(self._fd).st_size
        if file_size < _FOOTER.size:
            os.close(self._fd)
            raise Corruption(f"{path}: too small for footer")
        try:
            footer_raw = os.pread(self._fd, _FOOTER.size, file_size - _FOOTER.size)
            (
                bloom_off, index_off, props_off, global_seqno,
                num_blocks, num_entries, flags, magic,
            ) = _FOOTER.unpack(footer_raw)
            if magic != MAGIC:
                raise Corruption(f"{path}: bad magic")
        except Corruption:
            os.close(self._fd)
            raise
        self.global_seqno: Optional[int] = (
            global_seqno if flags & FLAG_HAS_GLOBAL_SEQNO else None
        )
        self.num_entries = num_entries
        # cached once at open: the engine's level-bytes / write-amp
        # gauges sum these under the DB lock without touching the fs
        self.file_size = file_size
        self._bloom = BloomFilter.from_bytes(
            os.pread(self._fd, index_off - bloom_off, bloom_off)
        )
        index_raw = os.pread(self._fd, props_off - index_off, index_off)
        self._index: List[Tuple[bytes, int, int, int]] = []
        pos = 0
        for _ in range(num_blocks):
            (klen,) = struct.unpack_from("<I", index_raw, pos)
            pos += 4
            last_key = index_raw[pos:pos + klen]
            pos += klen
            off, size, codec = _INDEX_ENTRY.unpack_from(index_raw, pos)
            pos += _INDEX_ENTRY.size
            self._index.append((last_key, off, size, codec))
        props_raw = os.pread(
            self._fd, file_size - _FOOTER.size - props_off, props_off
        )
        self.props: Dict = json.loads(props_raw.decode("utf-8")) if props_raw else {}
        self._verified_blocks: set = set()
        self._cache_token = next(_cache_tokens)
        # block last_keys for bisect (get_entries_many groups keys/block)
        self._last_keys: List[bytes] = [e[0] for e in self._index]

    # -- reads ------------------------------------------------------------

    def _read_block(self, block_idx: int, fill_cache: bool = True) -> bytes:
        cache = BlockCache.get_instance()
        if cache is not None:
            raw = cache.get(self._cache_token, block_idx)
            if raw is not None:
                Stats.get().incr("storage.block_cache.hit")
                return raw
        _last_key, off, size, codec = self._index[block_idx]
        payload = os.pread(self._fd, size, off)
        if codec in (COMPRESSION_ZLIB, BLOCK_PLANAR_ZLIB):
            raw = zlib.decompress(payload)
        elif codec in (COMPRESSION_RLZ, BLOCK_PLANAR_RLZ):
            # bound: a block decodes to at most a handful of block_bytes
            # (the writer flushes at the threshold); 64 MiB is far above
            # any legitimate block and guards a crafted header
            raw = rlz.decompress(payload, 64 << 20)
        elif codec in (COMPRESSION_NONE, BLOCK_PLANAR):
            raw = payload
        else:
            # a file from a newer writer (future codec) must fail LOUDLY,
            # not parse compressed bytes as entries
            raise Corruption(
                f"unsupported block codec {codec} (newer writer?)")
        self._verify_block_chk(block_idx, raw)
        if cache is not None and fill_cache:
            # only verified payloads enter the cache (a cached block skips
            # re-verification, like the _verified_blocks memo)
            Stats.get().incr("storage.block_cache.miss")
            cache.put(self._cache_token, block_idx, raw)
        return raw

    def _block_is_planar(self, block_idx: int) -> bool:
        return self._index[block_idx][3] in (
            BLOCK_PLANAR, BLOCK_PLANAR_ZLIB, BLOCK_PLANAR_RLZ)

    def _verify_block_chk(self, block_idx: int, raw: bytes) -> None:
        """Device-computed per-block integrity checksums (props
        "block_chk", written by the TPU sink — ops/block_encode.py).
        Files without the prop (v1 / flush-written) skip verification;
        crafted/foreign prop shapes degrade to no verification rather
        than raising arbitrary exceptions (same convention as the
        'uniform' prop). A verified block index is cached so repeated
        point lookups don't recompute the checksum."""
        chk = self.props.get("block_chk")
        try:
            if (
                not isinstance(chk, dict)
                or chk.get("algo") not in ("poly1", "poly1w")
                or block_idx >= len(chk["values"])
                or block_idx in self._verified_blocks
            ):
                return
            algo = chk["algo"]
            want = int(chk["values"][block_idx]) & 0xFFFFFFFF
            if algo == "poly1w":
                block_len = int(chk["block_words"])
            else:
                block_len = int(chk["block_bytes"])
        except (KeyError, TypeError, ValueError):
            return  # foreign/crafted prop — treat as absent
        if algo == "poly1w":
            # word-domain MAC over a planar block's plane words (the
            # 16-byte header is host-written and excluded)
            import numpy as np

            from .planar import PLANAR_HEADER
            from ..utils.checksum import poly_checksum_words

            if (
                len(raw) < PLANAR_HEADER.size
                or (len(raw) - PLANAR_HEADER.size) % 4
            ):
                raise Corruption(
                    f"block {block_idx}: truncated planar block "
                    f"({len(raw)} bytes)"
                )
            words = np.frombuffer(raw, dtype="<u4",
                                  offset=PLANAR_HEADER.size)
            got = poly_checksum_words(words, length=block_len)
        else:
            from ..utils.checksum import poly_checksum

            got = poly_checksum(raw, length=block_len)
        if got != want:
            raise Corruption(
                f"block {block_idx} checksum mismatch: "
                f"{got:#010x} != {want:#010x}"
            )
        self._verified_blocks.add(block_idx)

    @staticmethod
    def _iter_block(raw: bytes) -> Iterator[Tuple[bytes, int, int, bytes]]:
        from .native.binding import NATIVE

        if NATIVE is not None:
            yield from NATIVE.decode_block(raw)
            return
        pos = 0
        while pos < len(raw):
            (klen,) = _ENTRY_HEAD.unpack_from(raw, pos)
            pos += _ENTRY_HEAD.size
            key = raw[pos:pos + klen]
            pos += klen
            seq, vtype, vlen = _ENTRY_META.unpack_from(raw, pos)
            pos += _ENTRY_META.size
            value = raw[pos:pos + vlen]
            pos += vlen
            yield key, seq, vtype, value

    def _block_iter(
        self, block_idx: int, raw: bytes
    ) -> Iterator[Tuple[bytes, int, int, bytes]]:
        """Per-block decode dispatch: planar blocks (codec nibble) decode
        via the plane codec; entry-stream blocks via _iter_block."""
        if self._block_is_planar(block_idx):
            from .planar import iter_planar_block

            return iter_planar_block(raw)
        return self._iter_block(raw)

    def _effective_seq(self, seq: int) -> int:
        return self.global_seqno if self.global_seqno is not None else seq

    def may_contain(self, key: bytes) -> bool:
        return self._bloom.may_contain(key)

    def get_entries(self, key: bytes) -> List[Tuple[int, int, bytes]]:
        """ALL entries for key, newest first: [(seq, vtype, value)].
        Multiple entries occur for stacked MERGE operands — callers must
        fold through the whole stack, not just the newest."""
        if not self._bloom.may_contain(key):
            return []
        # binary search over block last_keys for the first candidate block
        lo, hi = 0, len(self._index) - 1
        block = None
        while lo <= hi:
            mid = (lo + hi) // 2
            if self._index[mid][0] < key:
                lo = mid + 1
            else:
                block = mid
                hi = mid - 1
        if block is None:
            return []
        from .native.binding import NATIVE

        out: List[Tuple[int, int, bytes]] = []
        # Entries for one key are contiguous and (seq desc)-ordered but may
        # span a block boundary.
        for b in range(block, len(self._index)):
            raw = self._read_block(b)
            done = False
            if NATIVE is None:
                native_res = None
            elif self._block_is_planar(b):
                native_res = NATIVE.planar_get_entries(raw, key)
            else:
                native_res = NATIVE.get_entries(raw, key)
            if native_res is not None:
                matches, past_end = native_res
                out.extend(
                    (self._effective_seq(seq), vtype, value)
                    for seq, vtype, value in matches
                )
                done = past_end
            else:
                for k, seq, vtype, value in self._block_iter(b, raw):
                    if k == key:
                        out.append((self._effective_seq(seq), vtype, value))
                    elif k > key:
                        done = True
                        break
            if done or (out and b < len(self._index) - 1
                        and self._index[b][0] > key):
                break
        return out

    def get(self, key: bytes) -> Optional[Tuple[int, int, bytes]]:
        """Newest entry for key: (seq, vtype, value) or None."""
        entries = self.get_entries(key)
        return entries[0] if entries else None

    def get_entries_many(
        self, keys: List[bytes], hashes=None
    ) -> Dict[bytes, List[Tuple[int, int, bytes]]]:
        """Entry stacks (newest first, as get_entries) for MANY keys:
        blooms checked in one batch, keys sorted and grouped per block so
        each touched block is read (or cache-hit) and decoded ONCE —
        the multi_get path. Keys with no entries are absent from the
        result. ``hashes`` is an optional ``(row_of_key, h1, mask)``
        triple from ``bloom.hash_many`` so a multi-SST read hashes each
        key once, not once per file."""
        import numpy as np

        out: Dict[bytes, List[Tuple[int, int, bytes]]] = {}
        if not self._index or not keys:
            return out
        cand = sorted(set(keys))
        if hashes is not None:
            rows_of, h1_all, mask_all = hashes
            rows = np.fromiter((rows_of[k] for k in cand),
                               dtype=np.intp, count=len(cand))
            mask = self._bloom.may_contain_hashed(
                h1_all[rows], mask_all[rows])
        else:
            mask = self._bloom.may_contain_many(cand)
        per_block: Dict[int, List[bytes]] = {}
        for k, ok in zip(cand, mask):
            if not ok:
                continue
            b = bisect.bisect_left(self._last_keys, k)
            if b < len(self._index):
                per_block.setdefault(b, []).append(k)
        heap = sorted(per_block)
        pos = 0
        while pos < len(heap):
            b = heap[pos]
            pos += 1
            want = per_block[b]
            raw = self._read_block(b)
            entries = list(self._block_iter(b, raw))
            ekeys = [e[0] for e in entries]
            for k in want:
                j = bisect.bisect_left(ekeys, k)
                while j < len(entries) and ekeys[j] == k:
                    _k, seq, vtype, value = entries[j]
                    out.setdefault(k, []).append(
                        (self._effective_seq(seq), vtype, value))
                    j += 1
                if j == len(entries) and b + 1 < len(self._index):
                    # the key's stack may continue into the next block
                    # (same continuation rule as get_entries)
                    nxt = per_block.get(b + 1)
                    if nxt is None:
                        per_block[b + 1] = [k]
                        # keep the worklist ordered: b+1 precedes any
                        # later scheduled block or is processed next
                        heap.insert(pos, b + 1)
                    elif k not in nxt:
                        nxt.append(k)
        return out

    def iterate(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, int, int, bytes]]:
        """All entries (key, seq, vtype, value) in order, [start, end)."""
        for i, (last_key, _off, _size, _codec) in enumerate(self._index):
            if start is not None and last_key < start:
                continue
            # bulk scan: probe the cache but don't fill it (a compaction
            # or full iteration would evict the point-read working set)
            for key, seq, vtype, value in self._block_iter(
                    i, self._read_block(i, fill_cache=False)):
                if start is not None and key < start:
                    continue
                if end is not None and key >= end:
                    return
                yield key, self._effective_seq(seq), vtype, value

    def min_key(self) -> Optional[bytes]:
        mk = self.props.get("min_key")
        return bytes.fromhex(mk) if mk else None

    def max_key(self) -> Optional[bytes]:
        mk = self.props.get("max_key")
        return bytes.fromhex(mk) if mk else None

    def max_seq(self) -> int:
        if self.global_seqno is not None:
            return self.global_seqno
        return self.props.get("max_seq", 0)

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1
            cache = BlockCache.get_instance()
            if cache is not None:
                # file GC closes readers — cached blocks die with them
                cache.drop(self._cache_token)
