"""WriteBatch: the unit of atomic writes and of replication shipping.

Reference: rocksdb::WriteBatch. The replication layer ships raw batch bytes
to followers (rocksdb_replicator/rocksdb_wrapper.cpp:13-28 deserializes the
raw WriteBatch, re-stamps the timestamp, applies locally), and the leader
stamps a wall-clock timestamp into each batch via ``PutLogData``
(replicated_db.cpp:115-117) which consumes no sequence number. This module
keeps those contracts.

Wire format (little-endian):
    u32 num_ops
    per op:
        u8  op_type
        u32 key_len,  key bytes     (LOG_DATA: key empty)
        u32 val_len,  val bytes

PUT/DELETE/MERGE consume one sequence number each; LOG_DATA consumes none
(mirrors RocksDB, and the engine-assumption tests pin this).
"""

from __future__ import annotations

import enum
import struct
import time
from typing import Iterator, List, Optional, Tuple

from .errors import Corruption

_U32 = struct.Struct("<I")
_OPHEAD = struct.Struct("<BI")


class OpType(enum.IntEnum):
    PUT = 1
    DELETE = 2
    MERGE = 3
    LOG_DATA = 4


# Log-data payloads written by the replication layer: 8-byte little-endian
# wall-clock milliseconds (replicated_db.cpp stamps ms for the lag metric).
_TS = struct.Struct("<Q")


class WriteBatch:
    __slots__ = ("_ops",)

    def __init__(self) -> None:
        self._ops: List[Tuple[OpType, bytes, bytes]] = []

    def put(self, key: bytes, value: bytes) -> "WriteBatch":
        self._ops.append((OpType.PUT, bytes(key), bytes(value)))
        return self

    def delete(self, key: bytes) -> "WriteBatch":
        self._ops.append((OpType.DELETE, bytes(key), b""))
        return self

    def merge(self, key: bytes, operand: bytes) -> "WriteBatch":
        self._ops.append((OpType.MERGE, bytes(key), bytes(operand)))
        return self

    def put_log_data(self, blob: bytes) -> "WriteBatch":
        self._ops.append((OpType.LOG_DATA, b"", bytes(blob)))
        return self

    # -- replication timestamp helpers ------------------------------------

    def stamp_timestamp_ms(self, now_ms: Optional[int] = None) -> "WriteBatch":
        """Leader-side stamp (replicated_db.cpp:115-117)."""
        ts = int(time.time() * 1000) if now_ms is None else now_ms
        return self.put_log_data(_TS.pack(ts))

    def extract_timestamp_ms(self) -> Optional[int]:
        """Last LOG_DATA 8-byte timestamp, if any (follower lag metric)."""
        for op, _key, val in reversed(self._ops):
            if op is OpType.LOG_DATA and len(val) == _TS.size:
                return _TS.unpack(val)[0]
        return None

    def strip_log_data(self) -> "WriteBatch":
        """Copy without LOG_DATA ops (follower re-stamps its own)."""
        out = WriteBatch()
        out._ops = [t for t in self._ops if t[0] is not OpType.LOG_DATA]
        return out

    # -- introspection ----------------------------------------------------

    def count(self) -> int:
        """Number of sequence-number-consuming ops."""
        return sum(1 for op, _k, _v in self._ops if op is not OpType.LOG_DATA)

    def __len__(self) -> int:
        return len(self._ops)

    def ops(self) -> Iterator[Tuple[OpType, bytes, bytes]]:
        return iter(self._ops)

    def byte_size(self) -> int:
        return _U32.size + sum(
            _OPHEAD.size + _U32.size + len(k) + len(v) for _op, k, v in self._ops
        )

    # -- serialization ----------------------------------------------------

    def encode(self) -> bytes:
        parts = [_U32.pack(len(self._ops))]
        for op, key, val in self._ops:
            parts.append(_OPHEAD.pack(op, len(key)))
            parts.append(key)
            parts.append(_U32.pack(len(val)))
            parts.append(val)
        return b"".join(parts)


def scan_batch_meta(data) -> Tuple[int, Optional[int]]:
    """(count, timestamp_ms) by skimming op HEADERS only — no key/value
    slicing, no WriteBatch construction. The replication serve path needs
    exactly these two facts per shipped update; a full decode_batch +
    extract_timestamp_ms pair cost two O(bytes) passes per update on the
    hot serve path."""
    buf = bytes(data)
    if len(buf) < _U32.size:
        raise Corruption("batch too short")
    (num_ops,) = _U32.unpack_from(buf, 0)
    pos = _U32.size
    count = 0
    ts: Optional[int] = None
    try:
        for _ in range(num_ops):
            op_raw, key_len = _OPHEAD.unpack_from(buf, pos)
            pos += _OPHEAD.size + key_len
            (val_len,) = _U32.unpack_from(buf, pos)
            pos += _U32.size
            if op_raw == OpType.LOG_DATA:
                if val_len == _TS.size:
                    ts = _TS.unpack_from(buf, pos)[0]
            else:
                count += 1
            pos += val_len
        if pos > len(buf):
            raise Corruption("truncated batch")
    except struct.error as e:
        raise Corruption(f"bad batch: {e}") from e
    return count, ts


def decode_batch(data) -> WriteBatch:
    buf = bytes(data)
    if len(buf) < _U32.size:
        raise Corruption("batch too short")
    (num_ops,) = _U32.unpack_from(buf, 0)
    pos = _U32.size
    batch = WriteBatch()
    try:
        for _ in range(num_ops):
            op_raw, key_len = _OPHEAD.unpack_from(buf, pos)
            pos += _OPHEAD.size
            key = buf[pos:pos + key_len]
            if len(key) != key_len:
                raise Corruption("truncated key")
            pos += key_len
            (val_len,) = _U32.unpack_from(buf, pos)
            pos += _U32.size
            val = buf[pos:pos + val_len]
            if len(val) != val_len:
                raise Corruption("truncated value")
            pos += val_len
            batch._ops.append((OpType(op_raw), key, val))
    except (struct.error, ValueError) as e:
        raise Corruption(f"bad batch encoding: {e}") from e
    if pos != len(buf):
        raise Corruption("trailing bytes in batch")
    return batch
