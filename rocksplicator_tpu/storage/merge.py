"""Merge operators.

Reference: rocksdb::AssociativeMergeOperator;
examples/counter_service/merge_operator.h:20-40 implements the counter bump
as a uint64-add associative merge.
"""

from __future__ import annotations

import struct
from typing import List, Optional

_U64 = struct.Struct("<q")


class MergeOperator:
    name = "base"

    def merge(self, key: bytes, existing: Optional[bytes], operands: List[bytes]) -> bytes:
        raise NotImplementedError

    def partial_merge(self, key: bytes, operands: List[bytes]) -> Optional[bytes]:
        """Associative collapse of operands without the base value; None if
        not supported."""
        return None


class UInt64AddOperator(MergeOperator):
    """Counter bump (merge_operator.h:20-40): values are little-endian
    int64; merge sums base + operands. Malformed values reset to 0 like the
    reference's defensive parse."""

    name = "uint64add"

    @staticmethod
    def _parse(v: Optional[bytes]) -> int:
        if v is None or len(v) != _U64.size:
            return 0
        return _U64.unpack(v)[0]

    def merge(self, key: bytes, existing: Optional[bytes], operands: List[bytes]) -> bytes:
        total = self._parse(existing)
        for op in operands:
            total += self._parse(op)
        total &= (1 << 64) - 1
        if total >= 1 << 63:
            total -= 1 << 64
        return _U64.pack(total)

    def partial_merge(self, key: bytes, operands: List[bytes]) -> Optional[bytes]:
        return self.merge(key, None, operands)


MERGE_OPERATORS = {
    UInt64AddOperator.name: UInt64AddOperator,
}
