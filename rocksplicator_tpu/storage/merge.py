"""Merge operators — the ONE home for MERGE-operand folding semantics.

Reference: rocksdb::AssociativeMergeOperator;
examples/counter_service/merge_operator.h:20-40 implements the counter bump
as a uint64-add associative merge.

Two faces of the same semantics live here so they cannot drift:

- ``resolve_entry_group``: the scalar per-key fold the tuple compaction
  path (storage/compaction.resolve_stream) applies to one key's entry
  stack — newest PUT/DELETE wins, MERGE operands above it fold in,
  tombstones drop at the bottom level.
- ``uint64_wrap`` / ``uint64add_segment_sums``: the wraparound arithmetic
  the vectorized array resolve (tpu/backend.numpy_merge_resolve, the
  native C resolve, and the TPU kernel) applies per sorted key segment.
  ``tests/test_flush_drain.py`` cross-checks the two faces entry-exactly.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

_U64 = struct.Struct("<q")


def uint64_wrap(total: int) -> int:
    """Canonical uint64-add overflow semantics → signed int64 range.
    Single source of truth shared by the scalar operator and (as plain
    int64 wraparound) the vectorized segment fold."""
    total &= (1 << 64) - 1
    if total >= 1 << 63:
        total -= 1 << 64
    return total


def uint64add_segment_sums(vals, contrib, bounds):
    """Vectorized uint64-add fold: per-segment sums of ``vals`` (int64)
    where ``contrib`` is True, segments starting at ``bounds`` — numpy
    int64 wraparound is element-exact with :func:`uint64_wrap` (the
    cross-check test pins it). Used by the array merge-resolve paths."""
    import numpy as np

    with np.errstate(over="ignore"):
        return np.add.reduceat(np.where(contrib, vals, 0), bounds)


class MergeOperator:
    name = "base"

    def merge(self, key: bytes, existing: Optional[bytes], operands: List[bytes]) -> bytes:
        raise NotImplementedError

    def partial_merge(self, key: bytes, operands: List[bytes]) -> Optional[bytes]:
        """Associative collapse of operands without the base value; None if
        not supported."""
        return None


class UInt64AddOperator(MergeOperator):
    """Counter bump (merge_operator.h:20-40): values are little-endian
    int64; merge sums base + operands. Malformed values reset to 0 like the
    reference's defensive parse."""

    name = "uint64add"

    @staticmethod
    def _parse(v: Optional[bytes]) -> int:
        if v is None or len(v) != _U64.size:
            return 0
        return _U64.unpack(v)[0]

    def merge(self, key: bytes, existing: Optional[bytes], operands: List[bytes]) -> bytes:
        total = self._parse(existing)
        for op in operands:
            total += self._parse(op)
        return _U64.pack(uint64_wrap(total))

    def partial_merge(self, key: bytes, operands: List[bytes]) -> Optional[bytes]:
        return self.merge(key, None, operands)


MERGE_OPERATORS = {
    UInt64AddOperator.name: UInt64AddOperator,
}

# entry: (key, seq, vtype, value) — mirrors storage/compaction.Entry
from .records import OpType as _OpType

_PUT, _DELETE, _MERGE = _OpType.PUT, _OpType.DELETE, _OpType.MERGE


def resolve_entry_group(
    group: List[Tuple[bytes, int, int, bytes]],
    merge_op: Optional[MergeOperator],
    drop_tombstones: bool,
) -> List[Tuple[bytes, int, int, bytes]]:
    """Fold one key's entry stack — newest (highest seq) first — to its
    surviving entries. THE scalar definition of LSM merge resolution;
    storage/compaction.resolve_stream delegates here, and the array
    resolves implement the identical semantics over lanes (cross-checked
    in tests).

    Usually returns one entry; an unresolved MERGE chain without a
    partial-merge-capable operator survives as multiple entries, like
    RocksDB keeps stacked merge operands."""
    key = group[0][0]
    top_seq = group[0][1]
    operands: List[bytes] = []
    for _key, seq, vtype, value in group:
        if vtype == _PUT:
            if operands and merge_op:
                return [(key, top_seq, _PUT,
                         merge_op.merge(key, value, list(reversed(operands))))]
            return [(key, top_seq, _PUT, value)]
        if vtype == _DELETE:
            if operands and merge_op:
                return [(key, top_seq, _PUT,
                         merge_op.merge(key, None, list(reversed(operands))))]
            if drop_tombstones:
                return []
            return [(key, top_seq, _DELETE, b"")]
        if vtype == _MERGE:
            operands.append(value)
    # Only MERGE ops seen for this key.
    if drop_tombstones and merge_op:
        # Bottom level: no older data can exist — fold to a final value.
        return [(key, top_seq, _PUT,
                 merge_op.merge(key, None, list(reversed(operands))))]
    if merge_op:
        partial = merge_op.partial_merge(key, list(reversed(operands)))
        if partial is not None:
            return [(key, top_seq, _MERGE, partial)]
    # No (partial-merge-capable) operator: keep the chain intact.
    return [e for e in group if e[2] == _MERGE]
