"""WAL archival + point-in-time restore (PITR).

Reference seam: the admin plane's incremental BackupEngine chains plus
the 1h WAL TTL feeding replication (admin_handler.cpp backup paths;
performance.cpp's WAL-TTL setup). The reference can rebuild any point
covered by a backup chain; here the same capability is checkpoint +
archived-WAL replay:

- ``WalArchiver.sink`` is handed to ``DBOptions.wal_archive_sink`` (or
  directly to ``wal.purge_obsolete``): every sealed WAL segment is
  uploaded to the object store BEFORE its TTL deletion, keyed by its
  first sequence number (the segment file name already encodes it).
- ``restore_db_to_seq`` downloads a checkpoint backup (storage.backup),
  then replays archived + still-live WAL batches on top, stopping at
  ``to_seq`` — restoring the DB to any historical sequence point that
  is >= the checkpoint's seq.

Archive layout under ``<prefix>/``: the segment files verbatim
(``wal-<first_seq:020d>.log``) — the archive directory IS a valid WAL
directory, so ``wal.iter_updates`` replays it unchanged once fetched.

Upgrade note: dbmeta written by the backup manager records its own
``wal_prefix`` (per DB incarnation); older dbmeta without it falls back
to the caller-passed prefix. Restoring ACROSS that layout boundary
(checkpoint from before per-incarnation prefixes, WAL tail after) needs
the explicit wal_prefix of the segment range being replayed — a
``to_seq`` restore fails loudly (PITR gap / archive-ends-early) rather
than returning silently short.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
from typing import Dict, Optional

from ..utils.objectstore import ObjectStore
from . import wal as wal_mod
from .engine import DB, DBOptions
from .errors import StorageError
from .records import decode_batch

log = logging.getLogger(__name__)


class WalArchiver:
    """Uploads sealed WAL segments to an object store. Idempotent per
    segment (a re-upload overwrites with identical bytes — segments are
    sealed, hence immutable, when the purge offers them)."""

    def __init__(self, store: ObjectStore, prefix: str):
        import threading

        self._store = store
        self._prefix = prefix.rstrip("/")
        # Serializes read+upload per archiver: without it, archive_live
        # could read a PARTIAL active segment, lose the CPU while the
        # purge ships the sealed full segment and deletes it, then land
        # its stale put last — permanently truncating archived history.
        # Engine purge and backup thread must share ONE archiver per DB.
        self._mutex = threading.Lock()
        # names shipped while SEALED (immutable): archive_live skips them
        # on later passes instead of re-uploading identical bytes.
        # Callers must use one archiver per DB INCARNATION (segment names
        # repeat with new content across a destroy+recreate — see
        # backup_manager._archiver).
        self._sealed_shipped: set = set()

    @property
    def prefix(self) -> str:
        return self._prefix

    def sink(self, path: str) -> None:
        """wal.purge_obsolete archive hook: ship one sealed segment."""
        key = f"{self._prefix}/{os.path.basename(path)}"
        with self._mutex:
            with open(path, "rb") as f:
                self._store.put_object_bytes(key, f.read())
            self._sealed_shipped.add(os.path.basename(path))
        log.info("archived WAL segment %s -> %s", path, key)

    def archive_live(self, db: DB) -> int:
        """Ship EVERY current WAL segment of an open DB — including the
        active one — so the archive covers history up to 'now' (rocksdb's
        backup copies live WAL the same way). Safe because uploads are
        whole-file and keyed by name: a growing active segment simply
        overwrites its archived copy with a longer version on the next
        call, and replay tolerates a torn tail on the last segment.
        Returns the number of segments shipped. Typical driver: the
        periodic backup thread (admin.backup_manager), right after its
        checkpoint upload."""
        n = 0
        segs = wal_mod._segments(db._wal_dir)
        for i, (_first_seq, path) in enumerate(segs):
            name = os.path.basename(path)
            sealed = i + 1 < len(segs)  # every segment but the ACTIVE one
            if sealed and name in self._sealed_shipped:
                continue  # immutable + already in the archive
            try:
                if sealed:
                    self.sink(path)
                else:
                    # ship the active tail WITHOUT marking it sealed: it
                    # is still growing and must re-ship next pass
                    key = f"{self._prefix}/{name}"
                    with self._mutex:
                        with open(path, "rb") as f:
                            self._store.put_object_bytes(key, f.read())
            except FileNotFoundError:
                continue  # purged (and therefore archived) under us
            n += 1
        return n

    def fetch_all(self, dest_dir: str) -> int:
        """Download every archived segment into ``dest_dir`` (a WAL-dir
        layout). Returns the number of segments fetched."""
        os.makedirs(dest_dir, exist_ok=True)
        n = 0
        for key in sorted(self._store.list_objects(self._prefix + "/")):
            name = key.rsplit("/", 1)[-1]
            if not (name.startswith("wal-") and name.endswith(".log")):
                continue
            with open(os.path.join(dest_dir, name), "wb") as f:
                f.write(self._store.get_object_bytes(key))
            n += 1
        return n


def replay_wal_dir(db: DB, wal_dir: str, to_seq: Optional[int]) -> int:
    """Replay WAL batches from ``wal_dir`` into an open DB, in sequence
    order, starting just past the DB's current seq and stopping after
    the batch containing ``to_seq`` (None = everything). Returns the
    number of batches applied. Raises on a sequence gap — a restore that
    silently skipped history would be worse than one that fails."""
    applied = 0
    expected = db.latest_sequence_number() + 1
    for start_seq, raw in wal_mod.iter_updates(wal_dir, expected):
        if to_seq is not None and start_seq > to_seq:
            break
        batch = decode_batch(raw)
        if start_seq + batch.count() - 1 < expected:
            continue  # fully below the checkpoint — already restored
        if start_seq != expected:
            raise StorageError(
                f"PITR gap: need seq {expected}, archive resumes at "
                f"{start_seq} — archive is missing a segment")
        got = db.write(batch)
        assert got == start_seq, (got, start_seq)
        applied += 1
        expected = db.latest_sequence_number() + 1
    if to_seq is not None and db.latest_sequence_number() < to_seq:
        raise StorageError(
            f"PITR: archive ends at seq {db.latest_sequence_number()}, "
            f"requested {to_seq}")
    return applied


def restore_db_to_seq(
    store: ObjectStore,
    backup_prefix: str,
    wal_prefix: str,
    db_path: str,
    to_seq: Optional[int] = None,
    options: Optional[DBOptions] = None,
    parallelism: int = 8,
) -> Dict:
    """Point-in-time restore: checkpoint backup + archived-WAL replay up
    to ``to_seq`` (None = latest archived). Picks the NEWEST checkpoint
    with seq <= to_seq from the prefix's versioned dbmeta chain
    (``dbmeta-<seq>``, written by every backup pass) — successive
    incremental backups into one prefix therefore advance nothing past
    restorability. Returns the chosen dbmeta augmented with
    ``restored_seq``. The restored DB is closed on return (same contract
    as restore_db: the caller reopens)."""
    from .backup import DBMETA_KEY, restore_db

    dbmeta_key = DBMETA_KEY
    if to_seq is not None:
        base = backup_prefix.rstrip("/") + "/" + DBMETA_KEY + "-"
        chain = []
        for key in store.list_objects(
                backup_prefix.rstrip("/") + "/" + DBMETA_KEY):
            tail = key[len(base):] if key.startswith(base) else ""
            if tail.isdigit():
                chain.append(int(tail))
        usable = sorted(s for s in chain if s <= to_seq)
        if usable:
            dbmeta_key = f"{DBMETA_KEY}-{usable[-1]:020d}"
        elif chain:
            # decide from the listing BEFORE downloading anything: the
            # requested point predates the whole chain
            raise StorageError(
                f"PITR: every checkpoint in {backup_prefix} is past seq "
                f"{to_seq} (oldest is {min(chain)}); the requested point "
                f"predates the backup chain")
    dbmeta = restore_db(
        store, backup_prefix, db_path, options=options,
        parallelism=parallelism, dbmeta_key=dbmeta_key)
    ckpt_seq = int(dbmeta.get("seq", 0))
    if to_seq is not None and to_seq < ckpt_seq:
        shutil.rmtree(db_path, ignore_errors=True)
        raise StorageError(
            f"PITR: every checkpoint in {backup_prefix} is past seq "
            f"{to_seq} (oldest usable is {ckpt_seq}); the requested "
            f"point predates the backup chain")
    tmp = tempfile.mkdtemp(prefix="rstpu-pitr-wal-")
    db = None
    try:
        # A dbmeta written by the backup manager names its own archive
        # prefix (per DB incarnation); it wins over the caller's guess
        WalArchiver(store, dbmeta.get("wal_prefix")
                    or wal_prefix).fetch_all(tmp)
        db = DB(db_path, options)
        replay_wal_dir(db, tmp, to_seq)
        dbmeta["restored_seq"] = db.latest_sequence_number()
        return dbmeta
    finally:
        if db is not None:
            db.close()
        shutil.rmtree(tmp, ignore_errors=True)
