// Native storage hot paths (C ABI, loaded via ctypes).
//
// The reference's entire storage engine is C++ (vendored RocksDB); this
// library provides the byte-crunching loops the Python engine spends its
// CPU time in — TSST block encode/decode, WAL record scanning with CRC,
// and bloom filter build/probe — with the exact same formats as the
// Python implementations (parity-tested). The TPU owns compaction math;
// this owns the host-side byte plumbing.
//
// Formats (must stay in lockstep with sst.py / wal.py / bloom.py):
//   block entry : u32 key_len | key | u64 seq | u8 vtype | u32 val_len | val
//   WAL record  : u64 start_seq | u32 batch_len | u32 crc32(batch) | batch
//   bloom       : register-blocked, FNV-1a over 6 LE u32 prefix words +
//                 length word, murmur fmix32 finalizer, K=6 bits from 5-bit
//                 slices of h2

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// crc32 (zlib-compatible, slice-by-1 table; built on first use)
// ---------------------------------------------------------------------------

struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

static const CrcTable& crc_table() {
  // C++11 magic static: thread-safe one-time init (no unsynchronized
  // flag race between concurrent first callers).
  static const CrcTable table;
  return table;
}

uint32_t tsst_crc32(const uint8_t* data, uint64_t len) {
  const CrcTable& tbl = crc_table();
  uint32_t c = 0xFFFFFFFFu;
  for (uint64_t i = 0; i < len; i++)
    c = tbl.t[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// TSST block codec
// ---------------------------------------------------------------------------

static inline void put_u32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
static inline void put_u64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }
static inline uint32_t get_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
static inline uint64_t get_u64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }

// Encode n entries into out. keys/vals are concatenated byte arrays with
// per-entry offsets (offsets[n] = total length). Returns bytes written,
// or -1 if out_cap is too small.
int64_t tsst_encode_block(
    const uint8_t* keys, const uint64_t* key_offsets,
    const uint64_t* seqs, const uint8_t* vtypes,
    const uint8_t* vals, const uint64_t* val_offsets,
    uint64_t n, uint8_t* out, uint64_t out_cap) {
  uint64_t pos = 0;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t klen = key_offsets[i + 1] - key_offsets[i];
    uint64_t vlen = val_offsets[i + 1] - val_offsets[i];
    uint64_t need = 4 + klen + 8 + 1 + 4 + vlen;
    if (pos + need > out_cap) return -1;
    put_u32(out + pos, (uint32_t)klen); pos += 4;
    memcpy(out + pos, keys + key_offsets[i], klen); pos += klen;
    put_u64(out + pos, seqs[i]); pos += 8;
    out[pos++] = vtypes[i];
    put_u32(out + pos, (uint32_t)vlen); pos += 4;
    memcpy(out + pos, vals + val_offsets[i], vlen); pos += vlen;
  }
  return (int64_t)pos;
}

// Decode a block: fills per-entry offset/seq/vtype arrays (caller sizes
// them at max_entries) and returns the entry count, or -1 on corruption /
// overflow. Key/value BYTES are not copied — offsets index into `data`.
int64_t tsst_decode_block(
    const uint8_t* data, uint64_t len, uint64_t max_entries,
    uint64_t* key_off, uint64_t* key_len,
    uint64_t* seqs, uint8_t* vtypes,
    uint64_t* val_off, uint64_t* val_len) {
  uint64_t pos = 0, i = 0;
  while (pos < len) {
    if (i >= max_entries) return -1;
    if (pos + 4 > len) return -1;
    uint32_t klen = get_u32(data + pos); pos += 4;
    if (pos + klen + 8 + 1 + 4 > len) return -1;
    key_off[i] = pos; key_len[i] = klen; pos += klen;
    seqs[i] = get_u64(data + pos); pos += 8;
    vtypes[i] = data[pos]; pos += 1;
    uint32_t vlen = get_u32(data + pos); pos += 4;
    if (pos + vlen > len) return -1;
    val_off[i] = pos; val_len[i] = vlen; pos += vlen;
    i++;
  }
  return (int64_t)i;
}

// Point lookup with early exit: walk the (sorted) block once, collect all
// entries for `key` (MERGE stacks span multiple entries), stop as soon as
// a greater key appears. One C call replaces a Python decode of the whole
// block. Returns the match count (0 = absent), -1 when max_matches was too
// small (caller retries bigger), -2 on corruption.
// Sets *past_end=1 iff the scan proved no later entry can match.
int64_t tsst_get_entries(
    const uint8_t* data, uint64_t len,
    const uint8_t* key, uint64_t klen, uint64_t max_matches,
    uint64_t* seqs, uint8_t* vtypes,
    uint64_t* val_off, uint64_t* val_len,
    int32_t* past_end) {
  *past_end = 0;
  uint64_t pos = 0, found = 0;
  while (pos < len) {
    if (pos + 4 > len) return -2;
    uint32_t eklen = get_u32(data + pos); pos += 4;
    if (pos + eklen + 8 + 1 + 4 > len) return -2;
    const uint8_t* ekey = data + pos; pos += eklen;
    uint64_t seq = get_u64(data + pos); pos += 8;
    uint8_t vt = data[pos]; pos += 1;
    uint32_t vlen = get_u32(data + pos); pos += 4;
    if (pos + vlen > len) return -2;
    uint64_t voff = pos; pos += vlen;
    uint64_t minlen = eklen < klen ? eklen : klen;
    int cmp = memcmp(ekey, key, minlen);
    if (cmp == 0 && eklen == klen) {
      if (found >= max_matches) return -1;
      seqs[found] = seq; vtypes[found] = vt;
      val_off[found] = voff; val_len[found] = vlen;
      found++;
    } else if (cmp > 0 || (cmp == 0 && eklen > klen)) {
      *past_end = 1;
      break;  // sorted: nothing later can match
    }
  }
  return (int64_t)found;
}

// ---------------------------------------------------------------------------
// WAL record scan
// ---------------------------------------------------------------------------

// Cheap structural pass (no CRC): count of complete records, so callers
// can allocate exact-size output arrays instead of len/16 upper bounds.
int64_t wal_count_records(const uint8_t* data, uint64_t len) {
  uint64_t pos = 0, i = 0;
  while (pos + 16 <= len) {
    uint32_t blen = get_u32(data + pos + 8);
    if (pos + 16 + blen > len) break;
    pos += 16 + blen;
    i++;
  }
  return (int64_t)i;
}

// Scans records; fills start_seqs/body_offsets/body_lens; returns count.
// Stops at a torn tail. Sets *bad_crc_at to the offset of a CRC-mismatched
// record (else -1) — callers decide whether that is corruption or a tail.
int64_t wal_scan(
    const uint8_t* data, uint64_t len, uint64_t max_records,
    uint64_t* start_seqs, uint64_t* body_offsets, uint64_t* body_lens,
    int64_t* bad_crc_at) {
  *bad_crc_at = -1;
  uint64_t pos = 0, i = 0;
  while (pos + 16 <= len && i < max_records) {
    uint64_t seq = get_u64(data + pos);
    uint32_t blen = get_u32(data + pos + 8);
    uint32_t crc = get_u32(data + pos + 12);
    uint64_t body = pos + 16;
    if (body + blen > len) break;  // torn tail
    if (tsst_crc32(data + body, blen) != crc) {
      *bad_crc_at = (int64_t)pos;
      break;
    }
    start_seqs[i] = seq;
    body_offsets[i] = body;
    body_lens[i] = blen;
    pos = body + blen;
    i++;
  }
  return (int64_t)i;
}

// ---------------------------------------------------------------------------
// bloom (format-identical to storage/bloom.py)
// ---------------------------------------------------------------------------

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16; h *= 0x85EBCA6Bu;
  h ^= h >> 13; h *= 0xC2B2AE35u;
  h ^= h >> 16; return h;
}

static inline void bloom_hash(const uint8_t* key, uint64_t klen,
                              uint32_t* h1, uint32_t* h2) {
  uint8_t prefix[24];
  memset(prefix, 0, 24);
  memcpy(prefix, key, klen < 24 ? klen : 24);
  uint32_t h = 2166136261u;
  for (int w = 0; w < 6; w++) {
    uint32_t word; memcpy(&word, prefix + 4 * w, 4);
    h = (h ^ word) * 16777619u;
  }
  h = (h ^ (uint32_t)klen) * 16777619u;
  *h1 = fmix32(h);
  *h2 = fmix32(h * 0x9E3779B1u + 1u);
}

void bloom_add_many(
    uint32_t* words, uint32_t num_words,
    const uint8_t* keys, const uint64_t* key_offsets, uint64_t n) {
  for (uint64_t i = 0; i < n; i++) {
    uint32_t h1, h2;
    uint64_t klen = key_offsets[i + 1] - key_offsets[i];
    bloom_hash(keys + key_offsets[i], klen, &h1, &h2);
    uint32_t mask = 0;
    for (int j = 0; j < 6; j++) mask |= 1u << ((h2 >> (5 * j)) & 31u);
    words[h1 % num_words] |= mask;
  }
}

int32_t bloom_may_contain(
    const uint32_t* words, uint32_t num_words,
    const uint8_t* key, uint64_t klen) {
  uint32_t h1, h2;
  bloom_hash(key, klen, &h1, &h2);
  uint32_t mask = 0;
  for (int j = 0; j < 6; j++) mask |= 1u << ((h2 >> (5 * j)) & 31u);
  return (words[h1 % num_words] & mask) == mask;
}

// ---------------------------------------------------------------------------
// RLZ1 — fast byte codec (LZ4/snappy-class; format owned by storage/rlz.py)
// ---------------------------------------------------------------------------
//
// The reference compresses SST blocks with Snappy/ZSTD and RPC channels
// with snappy transforms (thrift_client_pool.h:277-284); zlib (the only
// in-image codec) costs real CPU on the ingest path. RLZ1 is a greedy
// LZ77 with a depth-1 hash table — single pass, byte-aligned output,
// decode is a straight copy loop. Format (little-endian):
//
//   u32 raw_len
//   tokens until raw_len bytes are produced:
//     0x01..0x7F        literal run of <tag> bytes (follow inline)
//     0x80|L, u16 dist  match: copy L+4 bytes (4..131) from <dist> back
//                       (1..65535; may overlap itself, copied bytewise)
//
// Worst case (incompressible): 4 + n + ceil(n/127) bytes.

static inline uint32_t rlz_hash(uint32_t v) {
  // Fibonacci multiplicative hash of the next 4 bytes -> table index.
  return (v * 2654435761u) >> 18;  // 14-bit table
}

#define RLZ_TABLE_BITS 14
#define RLZ_MIN_MATCH 4u
#define RLZ_MAX_MATCH 131u
#define RLZ_MAX_DIST 65535u

int64_t rlz_compress(const uint8_t* src, uint64_t n,
                     uint8_t* dst, uint64_t cap) {
  if (n > 0xFFFFFFFFu) return -1;  // raw_len is a u32 header field
  if (cap < 4) return -1;
  put_u32(dst, (uint32_t)n);
  uint64_t w = 4;
  uint32_t table[1u << RLZ_TABLE_BITS];
  for (uint32_t i = 0; i < (1u << RLZ_TABLE_BITS); i++)
    table[i] = 0xFFFFFFFFu;
  uint64_t lit_start = 0;
  uint64_t i = 0;

  // emit pending literals [lit_start, end) in <=127-byte runs
  #define RLZ_FLUSH_LITS(end)                                    \
    do {                                                         \
      uint64_t run = (end) - lit_start;                          \
      while (run > 0) {                                          \
        uint64_t take = run > 127 ? 127 : run;                   \
        if (w + 1 + take > cap) return -1;                       \
        dst[w++] = (uint8_t)take;                                \
        memcpy(dst + w, src + lit_start, take);                  \
        w += take; lit_start += take; run -= take;               \
      }                                                          \
    } while (0)

  while (i + RLZ_MIN_MATCH <= n) {
    uint32_t v = get_u32(src + i);
    uint32_t h = rlz_hash(v);
    uint32_t cand = table[h];
    table[h] = (uint32_t)i;
    if (cand != 0xFFFFFFFFu && i - cand <= RLZ_MAX_DIST &&
        get_u32(src + cand) == v) {
      uint64_t len = RLZ_MIN_MATCH;
      uint64_t max_len = n - i;
      if (max_len > RLZ_MAX_MATCH) max_len = RLZ_MAX_MATCH;
      while (len < max_len && src[cand + len] == src[i + len]) len++;
      RLZ_FLUSH_LITS(i);
      if (w + 3 > cap) return -1;
      dst[w++] = (uint8_t)(0x80u | (len - RLZ_MIN_MATCH));
      uint32_t dist = (uint32_t)(i - cand);
      dst[w++] = (uint8_t)(dist & 0xFF);
      dst[w++] = (uint8_t)(dist >> 8);
      i += len;
      lit_start = i;
      // seed the table at the match tail so back-to-back repeats chain
      if (i + RLZ_MIN_MATCH <= n)
        table[rlz_hash(get_u32(src + i - 1))] = (uint32_t)(i - 1);
    } else {
      i++;
    }
  }
  RLZ_FLUSH_LITS(n);
  #undef RLZ_FLUSH_LITS
  return (int64_t)w;
}

// Returns decoded length, or -1 on malformed/overflowing input. Never
// reads past src+n; writes stay within dst+cap. When ``cap`` exceeds
// raw_len by >= 32 bytes of slack (the Python binding allocates it),
// copies use unconditional 16-byte "wildcopy" chunks that may scribble
// up to 15 bytes past the logical end — never past dst+cap — and are
// overwritten by subsequent tokens or ignored.
int64_t rlz_decompress(const uint8_t* src, uint64_t n,
                       uint8_t* dst, uint64_t cap) {
  if (n < 4) return -1;
  uint64_t raw_len = get_u32(src);
  if (raw_len > cap) return -1;
  uint64_t r = 4, w = 0;
  while (w < raw_len) {
    if (r >= n) return -1;
    uint8_t tag = src[r++];
    if (tag & 0x80u) {
      uint64_t len = (tag & 0x7Fu) + RLZ_MIN_MATCH;
      if (r + 2 > n) return -1;
      uint32_t dist = (uint32_t)src[r] | ((uint32_t)src[r + 1] << 8);
      r += 2;
      if (dist == 0 || dist > w || w + len > raw_len) return -1;
      if (dist >= len && dist >= 16 && w + len + 16 <= cap) {
        // wildcopy: dist >= 16 keeps every 16-byte chunk's read region
        // disjoint from its own write (no memcpy overlap); the tail
        // read tops out at w - dist + len + 15 < w + len + 16 <= cap
        uint64_t k = 0;
        do {
          memcpy(dst + w + k, dst + w - dist + k, 16);
          k += 16;
        } while (k < len);
        w += len;
      } else if (dist >= len) {
        memcpy(dst + w, dst + w - dist, len);  // disjoint: one copy
        w += len;
      } else {
        // overlapping run: replicate the period bytewise
        for (uint64_t k = 0; k < len; k++, w++) dst[w] = dst[w - dist];
      }
    } else {
      if (tag == 0) return -1;
      uint64_t take = tag;
      if (r + take > n || w + take > raw_len) return -1;
      if (w + take + 16 <= cap && r + take + 16 <= n) {
        // wildcopy needs slack on BOTH buffers (the tail chunk reads
        // up to 15 bytes past the literal run inside src)
        uint64_t k = 0;
        do {
          memcpy(dst + w + k, src + r + k, 16);
          k += 16;
        } while (k < take);
      } else {
        memcpy(dst + w, src + r, take);
      }
      r += take;
      w += take;
    }
  }
  return (int64_t)w;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// PLANAR block point lookup (storage/planar.py layout)
// ---------------------------------------------------------------------------
//
// Block: u32 n | u8 klen | u8 vlen_lo | u8 flags | u8 vlen_hi | u64 0
// (vlen = vlen_lo | vlen_hi<<8 — u16, byte 7 was reserved-zero so old
// files read back unchanged), then u32
// planes: key words (BE values, ceil(klen/4) x n), seq_lo (n), seq_hi
// (n, absent when flags&1), vtype (ceil(n/4), 4 packed/word), value
// words (LE values, ceil(vlen/4) x n). Keys ascending -> binary search,
// then the contiguous match run (MERGE stacks). -2 = malformed.

static inline int planar_cmp_key(
    const uint32_t* kw_planes, uint64_t n, uint64_t i,
    uint32_t bklen, const uint8_t* key, uint64_t klen) {
  // compare entry i's key bytes (BE bytes of each plane word) vs key
  uint64_t min_len = bklen < klen ? bklen : klen;
  for (uint64_t b = 0; b < min_len; b++) {
    uint32_t w; memcpy(&w, (const uint8_t*)(kw_planes + (b / 4) * n + i), 4);
    uint8_t eb = (uint8_t)(w >> (24 - 8 * (b % 4)));
    if (eb != key[b]) return eb < key[b] ? -1 : 1;
  }
  if (bklen == klen) return 0;
  return bklen < klen ? -1 : 1;
}

extern "C" int64_t tsst_planar_get_entries(
    const uint8_t* data, uint64_t len,
    const uint8_t* key, uint64_t klen, uint64_t max_matches,
    uint64_t* seqs, uint8_t* vtypes,
    uint8_t* out_vals, uint64_t vlen_cap, uint64_t* val_lens,
    int32_t* past_end) {
  *past_end = 0;
  if (len < 16) return -2;
  uint32_t n = get_u32(data);
  uint8_t bklen = data[4], flags = data[6];
  uint16_t bvlen = (uint16_t)data[5] | ((uint16_t)data[7] << 8);
  if (bklen == 0 || bklen > 24) return -2;
  uint64_t kw = (bklen + 3) / 4, vw = ((uint64_t)bvlen + 3) / 4;
  int seq32 = flags & 1;
  uint64_t words = (uint64_t)n * (kw + 1 + (seq32 ? 0 : 1) + vw)
                 + (n + 3) / 4;
  if (len != 16 + 4 * words) return -2;
  if (n == 0) return 0;
  const uint32_t* planes = (const uint32_t*)(data + 16);
  const uint32_t* kwp = planes;
  const uint32_t* seq_lo = planes + kw * n;
  const uint32_t* seq_hi = seq32 ? nullptr : seq_lo + n;
  const uint8_t* vtp = (const uint8_t*)(seq_lo + n + (seq32 ? 0 : n));
  const uint32_t* vvp = (const uint32_t*)(vtp + 4 * ((n + 3) / 4));

  // lower_bound: first index with entry key >= query key
  uint64_t lo = 0, hi = n;
  while (lo < hi) {
    uint64_t mid = (lo + hi) / 2;
    if (planar_cmp_key(kwp, n, mid, bklen, key, klen) < 0) lo = mid + 1;
    else hi = mid;
  }
  uint64_t found = 0;
  for (uint64_t i = lo; i < n; i++) {
    int c = planar_cmp_key(kwp, n, i, bklen, key, klen);
    if (c != 0) { if (c > 0) *past_end = 1; break; }
    if (found >= max_matches) return -1;
    uint64_t s = seq_lo[i];
    if (seq_hi) s |= ((uint64_t)seq_hi[i]) << 32;
    seqs[found] = s;
    uint8_t vt = vtp[i];
    vtypes[found] = vt;
    uint64_t vlen = (vt == 2) ? 0 : bvlen;
    if (vlen > vlen_cap) return -2;
    for (uint64_t b = 0; b < vlen; b++) {
      uint32_t w; memcpy(&w, (const uint8_t*)(vvp + (b / 4) * n + i), 4);
      out_vals[found * vlen_cap + b] = (uint8_t)(w >> (8 * (b % 4)));
    }
    val_lens[found] = vlen;
    found++;
  }
  return (int64_t)found;
}

// ---------------------------------------------------------------------------
// CPU merge-resolve — the framework's native compaction fallback
// ---------------------------------------------------------------------------
//
// Element-exact parity with tpu/backend.py numpy_merge_resolve (the same
// LSM resolution the TPU kernel computes): order by the canonical
// comparator — (key words asc, key_len asc, seq desc) — then resolve
// each key segment newest-wins with uint64-add operand folding above
// the first base and tombstone dropping. Two entry points share one
// comparator packing and ONE segment-resolve implementation:
//
//   cpu_merge_resolve       — unsorted input: packed-record std::sort
//   cpu_merge_resolve_runs  — PRE-SORTED runs: O(n log k) binary-heap
//                             k-way merge (callers verify sortedness)
//
// This is the single-core CPU path a host without an accelerator runs;
// the numpy implementation remains the fallback when the library is
// absent.

namespace {

// Comparator record: the 9 canonical u32 lanes packed pairwise into 5
// u64s (pairwise packing preserves lexicographic order). e's low half
// carries the input row index (tiebreak + payload lookup).
struct MrRec {
  uint64_t a, b, c, d, e;
  bool operator<(const MrRec& o) const {
    if (a != o.a) return a < o.a;
    if (b != o.b) return b < o.b;
    if (c != o.c) return c < o.c;
    if (d != o.d) return d < o.d;
    return e < o.e;
  }
};

struct MrInput {
  const uint32_t* kw;
  const uint32_t* klen;
  const uint64_t* seq;
  const uint8_t* vtype;
  const uint32_t* vw;
  const uint32_t* vlen;
  uint32_t kwn, vwn;
};

static inline void mr_pack(const MrInput& in, uint64_t i, MrRec* r) {
  const uint32_t* k = in.kw + (size_t)i * in.kwn;
  uint64_t w[6] = {0, 0, 0, 0, 0, 0};
  for (uint32_t x = 0; x < in.kwn; x++) w[x] = k[x];
  r->a = (w[0] << 32) | w[1];
  r->b = (w[2] << 32) | w[3];
  r->c = (w[4] << 32) | w[5];
  r->d = ((uint64_t)in.klen[i] << 32)
      | (uint32_t)~(uint32_t)(in.seq[i] >> 32);
  r->e = ((uint64_t)(uint32_t)~(uint32_t)in.seq[i] << 32) | (uint32_t)i;
}

static inline bool mr_same_key(const MrRec& x, const MrRec& y) {
  return x.a == y.a && x.b == y.b && x.c == y.c
      && (x.d >> 32) == (y.d >> 32);
}

static inline uint64_t mr_val64(const MrInput& in, uint64_t row) {
  uint64_t v = in.vw[(size_t)row * in.vwn];
  if (in.vwn > 1) v |= (uint64_t)in.vw[(size_t)row * in.vwn + 1] << 32;
  return v;
}

struct MrOutput {
  uint32_t* kw;
  uint32_t* klen;
  uint64_t* seq;
  uint8_t* vtype;
  uint32_t* vw;
  uint32_t* vlen;
  uint64_t count = 0;
};

// THE segment resolver (both entry points call exactly this): rows are
// one key's input row indices, newest (highest seq) first.
static void mr_resolve_segment(
    const MrInput& in, const uint64_t* rows, size_t nseg,
    int32_t uint64_add, int32_t drop_tombstones, MrOutput* out) {
  const uint8_t PUT = 1, DEL = 2, MERGE = 3;
  int64_t fb = -1;
  bool has_op = false;
  uint64_t sum = 0;
  for (size_t k = 0; k < nseg; k++) {
    uint64_t row = rows[k];
    uint8_t t = in.vtype[row];
    bool is_base = (t == PUT) || (t == DEL);
    if (is_base && fb < 0) fb = (int64_t)k;
    if (t == MERGE && (fb < 0 || (int64_t)k < fb)) {
      has_op = true;
      if (uint64_add && in.vlen[row] == 8) sum += mr_val64(in, row);
    }
  }
  bool base_is_put = false, base_is_del = false;
  if (fb >= 0) {
    uint64_t fb_row = rows[(size_t)fb];
    base_is_put = in.vtype[fb_row] == PUT;
    base_is_del = in.vtype[fb_row] == DEL;
    if (uint64_add && base_is_put && in.vlen[fb_row] == 8)
      sum += mr_val64(in, fb_row);
  }
  uint64_t rep = rows[0];
  uint8_t ovt = in.vtype[rep];
  uint64_t ovw0 = in.vw[(size_t)rep * in.vwn];
  uint64_t ovw1 = in.vwn > 1 ? in.vw[(size_t)rep * in.vwn + 1] : 0;
  uint32_t ovl = in.vlen[rep];
  bool dropped;
  if (uint64_add) {
    bool pure_operands = has_op && !base_is_put && !base_is_del;
    bool resolved_put = base_is_put || (has_op && base_is_del);
    if (resolved_put || pure_operands) {
      ovw0 = (uint32_t)(sum & 0xFFFFFFFFu);
      ovw1 = (uint32_t)(sum >> 32);
      ovl = 8;
    }
    if (resolved_put) ovt = PUT;
    else if (pure_operands) ovt = drop_tombstones ? PUT : MERGE;
    dropped = base_is_del && !has_op;
  } else {
    dropped = ovt == DEL;
  }
  if (drop_tombstones && dropped) return;
  uint64_t c = out->count;
  memcpy(out->kw + c * in.kwn, in.kw + (size_t)rep * in.kwn, in.kwn * 4);
  out->klen[c] = in.klen[rep];
  out->seq[c] = in.seq[rep];
  out->vtype[c] = ovt;
  // untouched value words beyond [0,1] come from the representative
  memcpy(out->vw + c * in.vwn, in.vw + (size_t)rep * in.vwn, in.vwn * 4);
  out->vw[c * in.vwn] = (uint32_t)ovw0;
  if (in.vwn > 1) out->vw[c * in.vwn + 1] = (uint32_t)ovw1;
  out->vlen[c] = ovl;
  out->count = c + 1;
}

}  // namespace

extern "C" int64_t cpu_merge_resolve(
    const uint32_t* kw, const uint32_t* klen, const uint64_t* seq,
    const uint8_t* vtype, const uint32_t* vw, const uint32_t* vlen,
    uint64_t n, uint32_t kwn, uint32_t vwn,
    int32_t uint64_add, int32_t drop_tombstones,
    uint32_t* out_kw, uint32_t* out_klen, uint64_t* out_seq,
    uint8_t* out_vtype, uint32_t* out_vw, uint32_t* out_vlen) {
  if (n == 0) return 0;
  if (kwn > 6) return -1;  // MrRec packs at most 6 key words
  MrInput in{kw, klen, seq, vtype, vw, vlen, kwn, vwn};
  MrOutput out{out_kw, out_klen, out_seq, out_vtype, out_vw, out_vlen};
  std::vector<MrRec> recs(n);
  for (uint64_t i = 0; i < n; i++) mr_pack(in, i, &recs[i]);
  // MSD bucket pass, then std::sort per bucket: n log(n/2048) instead
  // of n log n. The bucket key is the first 11 VARYING bits of the
  // comparator — real keysets share constant prefixes ("key000...", a
  // tenant id), so the varying-bit window is found by xor-folding each
  // packed word and bucketing just below the first difference. Order
  // is preserved because every more-significant bit is constant across
  // the dataset. Degenerate spreads (one bucket holding >n/2) fall
  // back to the plain whole-array sort.
  const uint32_t BUCKET_BITS = 11;
  const uint32_t NBUCKETS = 1u << BUCKET_BITS;
  bool bucketed = false;
  if (n >= 4096) {
    uint64_t xors[4] = {0, 0, 0, 0};
    for (uint64_t i = 0; i < n; i++) {
      xors[0] |= recs[i].a ^ recs[0].a;
      xors[1] |= recs[i].b ^ recs[0].b;
      xors[2] |= recs[i].c ^ recs[0].c;
      xors[3] |= recs[i].d ^ recs[0].d;
    }
    int word = -1;
    for (int w = 0; w < 4; w++)
      if (xors[w]) { word = w; break; }
    if (word >= 0) {
      int top = 63 - __builtin_clzll(xors[word]);
      uint32_t shift = top >= (int)BUCKET_BITS - 1
          ? (uint32_t)(top - (BUCKET_BITS - 1)) : 0u;
      auto key_of = [&](const MrRec& r) -> uint32_t {
        uint64_t w = word == 0 ? r.a : word == 1 ? r.b
            : word == 2 ? r.c : r.d;
        return (uint32_t)((w >> shift) & (NBUCKETS - 1));
      };
      std::vector<uint64_t> counts(NBUCKETS + 1, 0);
      for (uint64_t i = 0; i < n; i++) counts[key_of(recs[i]) + 1]++;
      uint64_t biggest = 0;
      for (uint32_t b = 1; b <= NBUCKETS; b++)
        if (counts[b] > biggest) biggest = counts[b];
      if (biggest <= n / 2) {
        for (uint32_t b = 0; b < NBUCKETS; b++)
          counts[b + 1] += counts[b];
        std::vector<MrRec> dist(n);
        std::vector<uint64_t> cursor(counts.begin(), counts.end() - 1);
        for (uint64_t i = 0; i < n; i++)
          dist[cursor[key_of(recs[i])]++] = recs[i];
        for (uint32_t b = 0; b < NBUCKETS; b++)
          std::sort(dist.begin() + counts[b],
                    dist.begin() + counts[b + 1]);
        recs.swap(dist);
        bucketed = true;
      }
    }
  }
  if (!bucketed) std::sort(recs.begin(), recs.end());
  std::vector<uint64_t> seg;
  seg.reserve(64);
  uint64_t i = 0;
  while (i < n) {
    uint64_t j = i;
    seg.clear();
    while (j < n && mr_same_key(recs[i], recs[j])) {
      seg.push_back((uint32_t)recs[j].e);
      j++;
    }
    mr_resolve_segment(in, seg.data(), seg.size(), uint64_add,
                       drop_tombstones, &out);
    i = j;
  }
  return (int64_t)out.count;
}

// K-way entry point over PRE-SORTED runs: run boundaries arrive as
// offsets into the concatenated input lanes. A run that is NOT sorted
// would silently merge wrong — the Python wrapper verifies sortedness
// per run (vectorized, cheap) before calling.
extern "C" int64_t cpu_merge_resolve_runs(
    const uint32_t* kw, const uint32_t* klen, const uint64_t* seq,
    const uint8_t* vtype, const uint32_t* vw, const uint32_t* vlen,
    const uint64_t* run_offsets,  // (n_runs+1,) into the n entries
    uint64_t n, uint32_t n_runs, uint32_t kwn, uint32_t vwn,
    int32_t uint64_add, int32_t drop_tombstones,
    uint32_t* out_kw, uint32_t* out_klen, uint64_t* out_seq,
    uint8_t* out_vtype, uint32_t* out_vw, uint32_t* out_vlen) {
  if (n == 0) return 0;
  if (kwn > 6 || n_runs == 0) return -1;
  MrInput in{kw, klen, seq, vtype, vw, vlen, kwn, vwn};
  MrOutput out{out_kw, out_klen, out_seq, out_vtype, out_vw, out_vlen};
  // run cursors + current head record per run; a binary heap of run ids
  // keyed by the head record (k is small — a heap is within noise of a
  // loser tree for k <= 64 and much simpler)
  std::vector<uint64_t> cur(n_runs);
  std::vector<MrRec> head(n_runs);
  std::vector<uint32_t> heap;
  heap.reserve(n_runs);
  for (uint32_t r = 0; r < n_runs; r++) {
    cur[r] = run_offsets[r];
    if (cur[r] < run_offsets[r + 1]) {
      mr_pack(in, cur[r], &head[r]);
      heap.push_back(r);
    }
  }
  auto heap_lt = [&](uint32_t x, uint32_t y) { return head[x] < head[y]; };
  auto sift_down = [&](size_t i) {
    size_t sz = heap.size();
    while (true) {
      size_t l = 2 * i + 1, r = 2 * i + 2, m = i;
      if (l < sz && heap_lt(heap[l], heap[m])) m = l;
      if (r < sz && heap_lt(heap[r], heap[m])) m = r;
      if (m == i) return;
      std::swap(heap[i], heap[m]);
      i = m;
    }
  };
  for (size_t i = heap.size(); i-- > 0;) sift_down(i);

  auto pop_min = [&](uint64_t* row_out, MrRec* rec_out) -> bool {
    if (heap.empty()) return false;
    uint32_t r = heap[0];
    *row_out = cur[r];
    *rec_out = head[r];
    cur[r]++;
    if (cur[r] < run_offsets[r + 1]) {
      mr_pack(in, cur[r], &head[r]);
    } else {
      heap[0] = heap.back();
      heap.pop_back();
    }
    if (!heap.empty()) sift_down(0);
    return true;
  };

  std::vector<uint64_t> seg;
  seg.reserve(64);
  MrRec seg_key{};
  bool have = false;
  uint64_t row;
  MrRec rec;
  while (pop_min(&row, &rec)) {
    if (have && !mr_same_key(seg_key, rec)) {
      mr_resolve_segment(in, seg.data(), seg.size(), uint64_add,
                         drop_tombstones, &out);
      seg.clear();
    }
    seg_key = rec;
    have = true;
    seg.push_back(row);
  }
  if (have)
    mr_resolve_segment(in, seg.data(), seg.size(), uint64_add,
                       drop_tombstones, &out);
  return (int64_t)out.count;
}
