"""ctypes binding for libtsst_native.so.

No pybind11 in the image (environment constraint) — the C ABI + ctypes is
the binding layer. Arrays cross the boundary as numpy buffers.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..errors import Corruption
from ..planar import unpack_planar_header

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libtsst_native.so")

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i64p = ctypes.POINTER(ctypes.c_int64)


_SRC = os.path.join(_DIR, "tsst_native.cc")
_MAKEFILE = os.path.join(_DIR, "Makefile")


def _so_current() -> bool:
    """True when the .so exists and is at least as new as its inputs
    (source and Makefile — a flag change must trigger a rebuild too)."""
    try:
        so = os.path.getmtime(_SO)
        return so >= os.path.getmtime(_SRC) and so >= os.path.getmtime(_MAKEFILE)
    except OSError:
        return False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.isfile(_SO)
    except Exception as e:
        log.info("native build unavailable: %s", e)
        return False


class NativeLib:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.tsst_crc32.restype = ctypes.c_uint32
        lib.tsst_crc32.argtypes = [_u8p, ctypes.c_uint64]
        lib.tsst_encode_block.restype = ctypes.c_int64
        lib.tsst_encode_block.argtypes = [
            _u8p, _u64p, _u64p, _u8p, _u8p, _u64p,
            ctypes.c_uint64, _u8p, ctypes.c_uint64,
        ]
        lib.tsst_decode_block.restype = ctypes.c_int64
        lib.tsst_decode_block.argtypes = [
            _u8p, ctypes.c_uint64, ctypes.c_uint64,
            _u64p, _u64p, _u64p, _u8p, _u64p, _u64p,
        ]
        lib.tsst_get_entries.restype = ctypes.c_int64
        lib.tsst_get_entries.argtypes = [
            _u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64, ctypes.c_uint64,
            _u64p, _u8p, _u64p, _u64p, ctypes.POINTER(ctypes.c_int32),
        ]
        # planar lookup may be absent in stale builds; probe and gate
        try:
            lib.tsst_planar_get_entries.restype = ctypes.c_int64
            lib.tsst_planar_get_entries.argtypes = [
                _u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64,
                ctypes.c_uint64, _u64p, _u8p, _u8p, ctypes.c_uint64,
                _u64p, ctypes.POINTER(ctypes.c_int32),
            ]
            self._has_planar = True
        except AttributeError:
            self._has_planar = False
        # CPU merge-resolve may be absent in stale builds; probe and gate
        try:
            lib.cpu_merge_resolve.restype = ctypes.c_int64
            lib.cpu_merge_resolve.argtypes = [
                _u32p, _u32p, _u64p, _u8p, _u32p, _u32p,
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_int32, ctypes.c_int32,
                _u32p, _u32p, _u64p, _u8p, _u32p, _u32p,
            ]
            self.has_merge_resolve = True
        except AttributeError:
            self.has_merge_resolve = False
        try:
            lib.cpu_merge_resolve_runs.restype = ctypes.c_int64
            lib.cpu_merge_resolve_runs.argtypes = [
                _u32p, _u32p, _u64p, _u8p, _u32p, _u32p, _u64p,
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_uint32, ctypes.c_int32, ctypes.c_int32,
                _u32p, _u32p, _u64p, _u8p, _u32p, _u32p,
            ]
            self.has_merge_resolve_runs = True
        except AttributeError:
            self.has_merge_resolve_runs = False
        # RLZ codec may be absent in stale builds; probe and gate
        try:
            lib.rlz_compress.restype = ctypes.c_int64
            lib.rlz_compress.argtypes = [
                _u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64,
            ]
            lib.rlz_decompress.restype = ctypes.c_int64
            lib.rlz_decompress.argtypes = [
                _u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64,
            ]
            self.has_rlz = True
        except AttributeError:
            self.has_rlz = False
        lib.wal_scan.restype = ctypes.c_int64
        lib.wal_scan.argtypes = [
            _u8p, ctypes.c_uint64, ctypes.c_uint64,
            _u64p, _u64p, _u64p, _i64p,
        ]
        lib.wal_count_records.restype = ctypes.c_int64
        lib.wal_count_records.argtypes = [_u8p, ctypes.c_uint64]
        lib.bloom_add_many.restype = None
        lib.bloom_add_many.argtypes = [
            _u32p, ctypes.c_uint32, _u8p, _u64p, ctypes.c_uint64,
        ]
        lib.bloom_may_contain.restype = ctypes.c_int32
        lib.bloom_may_contain.argtypes = [
            _u32p, ctypes.c_uint32, _u8p, ctypes.c_uint64,
        ]

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _u8(arr: np.ndarray):
        return arr.ctypes.data_as(_u8p)

    @staticmethod
    def _u64(arr: np.ndarray):
        return arr.ctypes.data_as(_u64p)

    # -- API ---------------------------------------------------------------

    def crc32(self, data: bytes) -> int:
        buf = np.frombuffer(data, dtype=np.uint8)
        return int(self._lib.tsst_crc32(self._u8(buf), len(buf)))

    def encode_block(
        self, keys: List[bytes], seqs: List[int], vtypes: List[int],
        vals: List[bytes],
    ) -> bytes:
        n = len(keys)
        key_buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
        val_buf = np.frombuffer(b"".join(vals), dtype=np.uint8)
        key_off = np.zeros(n + 1, dtype=np.uint64)
        val_off = np.zeros(n + 1, dtype=np.uint64)
        np.cumsum([len(k) for k in keys], out=key_off[1:])
        np.cumsum([len(v) for v in vals], out=val_off[1:])
        seq_arr = np.asarray(seqs, dtype=np.uint64)
        vt_arr = np.asarray(vtypes, dtype=np.uint8)
        cap = int(key_off[-1] + val_off[-1] + n * 17)
        out = np.empty(cap, dtype=np.uint8)
        if n == 0:
            return b""
        wrote = self._lib.tsst_encode_block(
            self._u8(key_buf if len(key_buf) else np.zeros(1, np.uint8)),
            self._u64(key_off),
            self._u64(seq_arr), self._u8(vt_arr),
            self._u8(val_buf if len(val_buf) else np.zeros(1, np.uint8)),
            self._u64(val_off),
            n, self._u8(out), cap,
        )
        if wrote < 0:
            raise ValueError("encode_block overflow")
        return out[:wrote].tobytes()

    def decode_block(self, raw: bytes) -> List[Tuple[bytes, int, int, bytes]]:
        data = np.frombuffer(raw, dtype=np.uint8)
        max_entries = max(1, len(raw) // 17)
        key_off = np.empty(max_entries, dtype=np.uint64)
        key_len = np.empty(max_entries, dtype=np.uint64)
        seqs = np.empty(max_entries, dtype=np.uint64)
        vtypes = np.empty(max_entries, dtype=np.uint8)
        val_off = np.empty(max_entries, dtype=np.uint64)
        val_len = np.empty(max_entries, dtype=np.uint64)
        n = self._lib.tsst_decode_block(
            self._u8(data), len(raw), max_entries,
            self._u64(key_off), self._u64(key_len),
            self._u64(seqs), self._u8(vtypes),
            self._u64(val_off), self._u64(val_len),
        )
        if n < 0:
            from ..errors import Corruption

            raise Corruption("native block decode failed")
        out = []
        for i in range(n):
            ko, kl = int(key_off[i]), int(key_len[i])
            vo, vl = int(val_off[i]), int(val_len[i])
            out.append((raw[ko:ko + kl], int(seqs[i]), int(vtypes[i]),
                        raw[vo:vo + vl]))
        return out

    def get_entries(self, raw: bytes, key: bytes,
                    max_matches: int = 64) -> Optional[Tuple[list, bool]]:
        """(entries, past_end) for ``key`` in one block: entries are
        (seq, vtype, value) newest-first as stored; past_end means the scan
        proved no later block can hold this key. None = slow path needed."""
        data = np.frombuffer(raw, dtype=np.uint8)
        kbuf = (np.frombuffer(key, dtype=np.uint8) if key
                else np.zeros(1, np.uint8))
        seqs = np.empty(max_matches, dtype=np.uint64)
        vtypes = np.empty(max_matches, dtype=np.uint8)
        val_off = np.empty(max_matches, dtype=np.uint64)
        val_len = np.empty(max_matches, dtype=np.uint64)
        past_end = ctypes.c_int32(0)
        n = self._lib.tsst_get_entries(
            self._u8(data), len(raw), self._u8(kbuf), len(key), max_matches,
            self._u64(seqs), self._u8(vtypes), self._u64(val_off),
            self._u64(val_len), ctypes.byref(past_end),
        )
        if n == -1:
            # overflow, not corruption: retry with room for a deeper merge
            # stack instead of falling back to a full block re-decode
            bound = max(1, len(raw) // 17)
            if max_matches < bound:
                return self.get_entries(raw, key, min(bound, max_matches * 8))
            return None
        if n < 0:
            return None
        return (
            [
                (int(seqs[i]), int(vtypes[i]),
                 raw[int(val_off[i]):int(val_off[i]) + int(val_len[i])])
                for i in range(n)
            ],
            bool(past_end.value),
        )

    def planar_get_entries(self, raw: bytes, key: bytes,
                           max_matches: int = 64
                           ) -> Optional[Tuple[list, bool]]:
        """get_entries over a PLANAR block (storage/planar.py): binary
        search in C over the key planes, values reassembled from the
        value planes. None = slow path needed."""
        if not self._has_planar:
            return None
        data = np.frombuffer(raw, dtype=np.uint8)
        kbuf = (np.frombuffer(key, dtype=np.uint8) if key
                else np.zeros(1, np.uint8))
        try:
            _, _, vlen_cap, _ = unpack_planar_header(raw)
        except Corruption:
            return None  # slow path will raise the descriptive error
        seqs = np.empty(max_matches, dtype=np.uint64)
        vtypes = np.empty(max_matches, dtype=np.uint8)
        vals = np.zeros((max_matches, max(1, vlen_cap)), dtype=np.uint8)
        val_len = np.empty(max_matches, dtype=np.uint64)
        past_end = ctypes.c_int32(0)
        n = self._lib.tsst_planar_get_entries(
            self._u8(data), len(raw), self._u8(kbuf), len(key),
            max_matches, self._u64(seqs), self._u8(vtypes),
            self._u8(vals), max(1, vlen_cap), self._u64(val_len),
            ctypes.byref(past_end),
        )
        if n == -1:
            if len(raw) >= 16:
                total = int.from_bytes(raw[:4], "little")
                if max_matches < total:
                    return self.planar_get_entries(
                        raw, key, min(total, max_matches * 8))
            return None
        if n < 0:
            return None
        return (
            [
                (int(seqs[i]), int(vtypes[i]),
                 vals[i, :int(val_len[i])].tobytes())
                for i in range(n)
            ],
            bool(past_end.value),
        )

    def merge_resolve(self, kw, klen, seq, vtype, vw, vlen,
                      uint64_add: bool, drop_tombstones: bool):
        """Native LSM merge-resolve (cpu_merge_resolve): inputs are the
        valid-prefix KVBatch lanes; returns (out_kw, out_klen, out_seq,
        out_vtype, out_vw, out_vlen, count). Semantics parity-pinned to
        numpy_merge_resolve (tests/test_native.py)."""
        n = len(klen)
        kwn = kw.shape[1]
        vwn = vw.shape[1]
        kw = np.ascontiguousarray(kw, dtype=np.uint32)
        klen = np.ascontiguousarray(klen, dtype=np.uint32)
        seq = np.ascontiguousarray(seq, dtype=np.uint64)
        vtype = np.ascontiguousarray(vtype, dtype=np.uint8)
        vw = np.ascontiguousarray(vw, dtype=np.uint32)
        vlen = np.ascontiguousarray(vlen, dtype=np.uint32)
        out_kw = np.empty((n, kwn), dtype=np.uint32)
        out_klen = np.empty(n, dtype=np.uint32)
        out_seq = np.empty(n, dtype=np.uint64)
        out_vtype = np.empty(n, dtype=np.uint8)
        out_vw = np.empty((n, vwn), dtype=np.uint32)
        out_vlen = np.empty(n, dtype=np.uint32)
        count = self._lib.cpu_merge_resolve(
            kw.ctypes.data_as(_u32p), klen.ctypes.data_as(_u32p),
            self._u64(seq), self._u8(vtype),
            vw.ctypes.data_as(_u32p), vlen.ctypes.data_as(_u32p),
            n, kwn, vwn, int(uint64_add), int(drop_tombstones),
            out_kw.ctypes.data_as(_u32p), out_klen.ctypes.data_as(_u32p),
            self._u64(out_seq), self._u8(out_vtype),
            out_vw.ctypes.data_as(_u32p), out_vlen.ctypes.data_as(_u32p),
        )
        if count < 0:
            raise ValueError("cpu_merge_resolve failed")
        return (out_kw, out_klen, out_seq, out_vtype, out_vw, out_vlen,
                int(count))

    def merge_resolve_runs(self, kw, klen, seq, vtype, vw, vlen,
                           run_offsets, uint64_add: bool,
                           drop_tombstones: bool):
        """Native k-way merge-resolve over PRE-SORTED runs
        (cpu_merge_resolve_runs): O(n log k) instead of the full-sort
        path's O(n log n). Caller must have verified each run is sorted
        in (key words asc, klen asc, seq desc) order."""
        n = len(klen)
        kwn = kw.shape[1]
        vwn = vw.shape[1]
        kw = np.ascontiguousarray(kw, dtype=np.uint32)
        klen = np.ascontiguousarray(klen, dtype=np.uint32)
        seq = np.ascontiguousarray(seq, dtype=np.uint64)
        vtype = np.ascontiguousarray(vtype, dtype=np.uint8)
        vw = np.ascontiguousarray(vw, dtype=np.uint32)
        vlen = np.ascontiguousarray(vlen, dtype=np.uint32)
        run_offsets = np.ascontiguousarray(run_offsets, dtype=np.uint64)
        out_kw = np.empty((n, kwn), dtype=np.uint32)
        out_klen = np.empty(n, dtype=np.uint32)
        out_seq = np.empty(n, dtype=np.uint64)
        out_vtype = np.empty(n, dtype=np.uint8)
        out_vw = np.empty((n, vwn), dtype=np.uint32)
        out_vlen = np.empty(n, dtype=np.uint32)
        count = self._lib.cpu_merge_resolve_runs(
            kw.ctypes.data_as(_u32p), klen.ctypes.data_as(_u32p),
            self._u64(seq), self._u8(vtype),
            vw.ctypes.data_as(_u32p), vlen.ctypes.data_as(_u32p),
            self._u64(run_offsets),
            n, len(run_offsets) - 1, kwn, vwn,
            int(uint64_add), int(drop_tombstones),
            out_kw.ctypes.data_as(_u32p), out_klen.ctypes.data_as(_u32p),
            self._u64(out_seq), self._u8(out_vtype),
            out_vw.ctypes.data_as(_u32p), out_vlen.ctypes.data_as(_u32p),
        )
        if count < 0:
            raise ValueError("cpu_merge_resolve_runs failed")
        return (out_kw, out_klen, out_seq, out_vtype, out_vw, out_vlen,
                int(count))

    def rlz_compress(self, data: bytes) -> bytes:
        from ..rlz import max_compressed_len

        src = (np.frombuffer(data, dtype=np.uint8) if data
               else np.zeros(1, np.uint8))
        cap = max_compressed_len(len(data))
        out = np.empty(cap, dtype=np.uint8)
        wrote = self._lib.rlz_compress(
            self._u8(src), len(data), self._u8(out), cap)
        if wrote < 0:  # sized by max_compressed_len — cannot happen
            raise ValueError("rlz_compress overflow")
        return out[:wrote].tobytes()

    def rlz_decompress(self, data: bytes, max_out: int) -> Optional[bytes]:
        """Decoded bytes, or None on malformed/oversized input (the
        Python wrapper raises the descriptive error)."""
        src = (np.frombuffer(data, dtype=np.uint8) if data
               else np.zeros(1, np.uint8))
        if len(data) >= 4:
            declared = int.from_bytes(data[:4], "little")
            if declared > max_out:
                return None
        else:
            return None
        # +32 slack enables the decoder's 16-byte wildcopy fast path
        # (it may scribble up to 15 bytes past the logical end)
        out = np.empty(declared + 32, dtype=np.uint8)
        n = self._lib.rlz_decompress(
            self._u8(src), len(data), self._u8(out), declared + 32)
        if n < 0:
            return None
        return out[:n].tobytes()

    def wal_scan(self, raw: bytes) -> Tuple[List[Tuple[int, int, int]], int]:
        """Returns ([(start_seq, body_off, body_len)], bad_crc_at)."""
        data = np.frombuffer(raw, dtype=np.uint8)
        # exact-size output arrays via a cheap structural pre-count (a
        # len/16 upper bound would allocate ~96MB for a 64MiB segment)
        max_records = max(
            1, int(self._lib.wal_count_records(self._u8(data), len(raw)))
        )
        seqs = np.empty(max_records, dtype=np.uint64)
        offs = np.empty(max_records, dtype=np.uint64)
        lens = np.empty(max_records, dtype=np.uint64)
        bad = ctypes.c_int64(-1)
        n = self._lib.wal_scan(
            self._u8(data), len(raw), max_records,
            self._u64(seqs), self._u64(offs), self._u64(lens),
            ctypes.byref(bad),
        )
        return (
            [(int(seqs[i]), int(offs[i]), int(lens[i])) for i in range(n)],
            int(bad.value),
        )

    def bloom_add_many(self, words: np.ndarray, keys: List[bytes]) -> None:
        n = len(keys)
        if n == 0:
            return
        key_buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
        key_off = np.zeros(n + 1, dtype=np.uint64)
        np.cumsum([len(k) for k in keys], out=key_off[1:])
        self.bloom_add_concat(words, key_buf, key_off, n)

    def bloom_add_concat(self, words: np.ndarray, key_buf: np.ndarray,
                         key_off: np.ndarray, n: int) -> None:
        """bloom_add_many over an already-concatenated key buffer +
        (n+1,) u64 offsets — the no-Python-objects bulk path."""
        key_buf = np.ascontiguousarray(key_buf, dtype=np.uint8)
        key_off = np.ascontiguousarray(key_off, dtype=np.uint64)
        self._lib.bloom_add_many(
            words.ctypes.data_as(_u32p), len(words),
            self._u8(key_buf if len(key_buf) else np.zeros(1, np.uint8)),
            self._u64(key_off), n,
        )

    def bloom_may_contain(self, words: np.ndarray, key: bytes) -> bool:
        buf = np.frombuffer(key, dtype=np.uint8) if key else np.zeros(1, np.uint8)
        return bool(self._lib.bloom_may_contain(
            words.ctypes.data_as(_u32p), len(words), self._u8(buf), len(key)
        ))


def _load() -> Optional[NativeLib]:
    if os.environ.get("RSTPU_DISABLE_NATIVE"):
        return None
    # Never load a .so older than its source: it is either a stale build
    # or a binary of unknown provenance. Rebuild from tsst_native.cc; on
    # build failure fall back to the pure-Python paths, loudly.
    if not _so_current() and not _build():
        if os.path.isfile(_SO):
            log.warning(
                "refusing stale/unverified %s (build failed); "
                "using pure-Python fallback paths", _SO,
            )
        return None
    try:
        return NativeLib(ctypes.CDLL(_SO))
    except (OSError, AttributeError) as e:
        log.warning("native lib load failed: %s", e)
        return None


_UNSET = object()
_native: object = _UNSET
_native_lock = threading.Lock()


def get_native() -> Optional[NativeLib]:
    """Lazily build+load the native library on first use (not at import).
    Locked: first use happens on hot paths from multiple threads, and two
    concurrent `make` runs could dlopen a partially written .so."""
    global _native
    if _native is _UNSET:
        with _native_lock:
            if _native is _UNSET:
                _native = _load()
    return _native  # type: ignore[return-value]


def native_available() -> bool:
    return get_native() is not None


def __getattr__(name: str):
    # PEP 562: keep `binding.NATIVE` working without import-time side effects.
    if name == "NATIVE":
        return get_native()
    raise AttributeError(name)
