"""Native (C++) storage hot paths, loaded via ctypes.

Build: ``make -C rocksplicator_tpu/storage/native`` (auto-attempted on
first import). The Python implementations remain authoritative fallbacks;
format parity is pinned by tests/test_native.py.
"""

from .binding import NATIVE, NativeLib, native_available

__all__ = ["NATIVE", "NativeLib", "native_available"]
