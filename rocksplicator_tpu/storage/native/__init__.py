"""Native (C++) storage hot paths, loaded via ctypes.

Build: ``make -C rocksplicator_tpu/storage/native`` (auto-attempted on
first *use*, never at import). The Python implementations remain
authoritative fallbacks; format parity is pinned by tests/test_native.py.
"""

from .binding import NativeLib, get_native, native_available

__all__ = ["NATIVE", "NativeLib", "get_native", "native_available"]


def __getattr__(name: str):
    if name == "NATIVE":
        return get_native()
    raise AttributeError(name)
