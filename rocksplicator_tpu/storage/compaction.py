"""Compaction: k-way merge of sorted runs with LSM resolution.

This module defines the **CompactionBackend seam** — the boundary behind
which the TPU offload plugs in (BASELINE.json north star: "L0→Ln compaction
jobs ... ship their key-value blocks to a TPU sidecar"). The default
backend is the CPU heap-merge; ``rocksplicator_tpu.tpu.compaction_service``
registers a TPU backend implementing the same interface.

An input "run" is an iterator of (key, seq, vtype, value) in (key asc,
seq desc) order; the output is the merged, deduplicated stream in the same
order, with per-key resolution:
- newest PUT wins; MERGE operands above it fold into it
- newest DELETE wins; at the bottom level tombstones (and the keys they
  shadow) are dropped entirely
- unresolved MERGE chains are partially merged when the operator allows
"""

from __future__ import annotations

import heapq
from typing import Iterable, Iterator, List, Optional, Tuple

from .merge import MergeOperator
from .records import OpType

Entry = Tuple[bytes, int, int, bytes]  # key, seq, vtype, value


class CompactionBackend:
    name = "base"

    def merge_runs(
        self,
        runs: List[Iterable[Entry]],
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
    ) -> Iterator[Entry]:
        raise NotImplementedError


class CpuCompactionBackend(CompactionBackend):
    """Heap-based k-way merge — the 32-core-CPU baseline the TPU backend is
    benchmarked against."""

    name = "cpu"

    def merge_runs(
        self,
        runs: List[Iterable[Entry]],
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
    ) -> Iterator[Entry]:
        # (key asc, seq desc) merge order.
        merged = heapq.merge(*runs, key=lambda e: (e[0], -e[1]))
        return resolve_stream(merged, merge_op, drop_tombstones)


def resolve_stream(
    merged: Iterable[Entry],
    merge_op: Optional[MergeOperator],
    drop_tombstones: bool,
) -> Iterator[Entry]:
    """Collapse a (key asc, seq desc)-ordered stream to one entry per key."""
    cur_key: Optional[bytes] = None
    group: List[Entry] = []
    for entry in merged:
        if entry[0] != cur_key:
            if group:
                yield from _resolve_group(group, merge_op, drop_tombstones)
            cur_key = entry[0]
            group = [entry]
        else:
            group.append(entry)
    if group:
        yield from _resolve_group(group, merge_op, drop_tombstones)


def _resolve_group(
    group: List[Entry],
    merge_op: Optional[MergeOperator],
    drop_tombstones: bool,
) -> List[Entry]:
    """group: all entries for one key, newest (highest seq) first. Returns
    the surviving entries (usually one; an unresolved MERGE chain without a
    partial-merge-capable operator survives as multiple entries, like
    RocksDB keeps stacked merge operands)."""
    key = group[0][0]
    top_seq = group[0][1]
    operands: List[bytes] = []
    for _key, seq, vtype, value in group:
        if vtype == OpType.PUT:
            if operands and merge_op:
                return [(key, top_seq, OpType.PUT,
                         merge_op.merge(key, value, list(reversed(operands))))]
            return [(key, top_seq, OpType.PUT, value)]
        if vtype == OpType.DELETE:
            if operands and merge_op:
                return [(key, top_seq, OpType.PUT,
                         merge_op.merge(key, None, list(reversed(operands))))]
            if drop_tombstones:
                return []
            return [(key, top_seq, OpType.DELETE, b"")]
        if vtype == OpType.MERGE:
            operands.append(value)
    # Only MERGE ops seen for this key.
    if drop_tombstones and merge_op:
        # Bottom level: no older data can exist — fold to a final value.
        return [(key, top_seq, OpType.PUT,
                 merge_op.merge(key, None, list(reversed(operands))))]
    if merge_op:
        partial = merge_op.partial_merge(key, list(reversed(operands)))
        if partial is not None:
            return [(key, top_seq, OpType.MERGE, partial)]
    # No (partial-merge-capable) operator: keep the chain intact.
    return [e for e in group if e[2] == OpType.MERGE]
