"""Compaction: k-way merge of sorted runs with LSM resolution.

This module defines the **CompactionBackend seam** — the boundary behind
which the TPU offload plugs in (BASELINE.json north star: "L0→Ln compaction
jobs ... ship their key-value blocks to a TPU sidecar"). The default
backend is the CPU heap-merge; ``rocksplicator_tpu.tpu.compaction_service``
registers a TPU backend implementing the same interface.

An input "run" is an iterator of (key, seq, vtype, value) in (key asc,
seq desc) order; the output is the merged, deduplicated stream in the same
order, with per-key resolution:
- newest PUT wins; MERGE operands above it fold into it
- newest DELETE wins; at the bottom level tombstones (and the keys they
  shadow) are dropped entirely
- unresolved MERGE chains are partially merged when the operator allows
"""

from __future__ import annotations

import heapq
from typing import Iterable, Iterator, List, Optional, Tuple

from .merge import MergeOperator, resolve_entry_group

Entry = Tuple[bytes, int, int, bytes]  # key, seq, vtype, value


class CompactionBackend:
    name = "base"
    # True on backends whose ``merge_runs_to_files`` accepts the
    # ``max_subcompactions``/``io_budget`` keywords (key-range
    # subcompactions + foreground-yielding IO budget); the engine only
    # passes them to backends that declare support, so third-party
    # backend signatures stay valid.
    supports_subcompactions = False
    # True on backends that additionally accept the round-17
    # ``mem_tracker``/``memory_budget_bytes`` keywords (streaming
    # bounded-memory merge + peak gauge) — a separate capability so a
    # third-party backend that declared subcompaction support before
    # round 17 keeps its narrower signature valid.
    supports_memory_budget = False

    def merge_runs(
        self,
        runs: List[Iterable[Entry]],
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
    ) -> Iterator[Entry]:
        raise NotImplementedError


class CpuCompactionBackend(CompactionBackend):
    """Heap-based k-way merge — the 32-core-CPU baseline the TPU backend is
    benchmarked against. Also carries the DIRECT array sink
    (``merge_runs_to_files``): when every input run reads as lanes and
    widths are uniform, the whole compaction runs array-to-array (lexsort
    merge + segment resolve + planar writer) with no per-entry Python —
    the engine's ``_write_entry_stream`` loop becomes the fallback, not
    the common case."""

    name = "cpu"
    supports_subcompactions = True
    supports_memory_budget = True

    def merge_runs(
        self,
        runs: List[Iterable[Entry]],
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
    ) -> Iterator[Entry]:
        # (key asc, seq desc) merge order.
        merged = heapq.merge(*runs, key=lambda e: (e[0], -e[1]))
        return resolve_stream(merged, merge_op, drop_tombstones)

    def merge_runs_to_files(
        self,
        runs: List,
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
        path_factory,
        block_bytes: int,
        compression: int,
        bits_per_key: int,
        target_file_bytes: int,
        max_subcompactions: int = 1,
        io_budget=None,
        mem_tracker=None,
        memory_budget_bytes: int = 0,
    ):
        """[(path, props)], [] for an all-tombstoned result, or None →
        the engine's tuple path. Shared implementation with the native
        backend (storage/native_compaction.direct_merge_runs_to_files);
        the native C resolve is used when the library is loaded, the
        numpy lexsort+reduceat resolve otherwise. Oversized inputs
        stream through the bounded-memory chunked merge
        (storage/stream_merge.py). With ``max_subcompactions > 1`` the
        in-RAM merge splits into parallel key-range slices;
        ``io_budget`` paces output writes; ``mem_tracker`` feeds the
        peak-bytes-materialized gauge."""
        from .native_compaction import direct_merge_runs_to_files

        return direct_merge_runs_to_files(
            runs, merge_op, drop_tombstones, path_factory, block_bytes,
            compression, bits_per_key, target_file_bytes,
            max_subcompactions=max_subcompactions, io_budget=io_budget,
            mem_tracker=mem_tracker,
            memory_budget_bytes=memory_budget_bytes,
        )


def resolve_stream(
    merged: Iterable[Entry],
    merge_op: Optional[MergeOperator],
    drop_tombstones: bool,
) -> Iterator[Entry]:
    """Collapse a (key asc, seq desc)-ordered stream to one entry per key."""
    cur_key: Optional[bytes] = None
    group: List[Entry] = []
    for entry in merged:
        if entry[0] != cur_key:
            if group:
                yield from _resolve_group(group, merge_op, drop_tombstones)
            cur_key = entry[0]
            group = [entry]
        else:
            group.append(entry)
    if group:
        yield from _resolve_group(group, merge_op, drop_tombstones)


def _resolve_group(
    group: List[Entry],
    merge_op: Optional[MergeOperator],
    drop_tombstones: bool,
) -> List[Entry]:
    """group: all entries for one key, newest (highest seq) first. The
    fold semantics live in storage/merge.resolve_entry_group — the single
    source of truth the array resolves are cross-checked against."""
    return resolve_entry_group(group, merge_op, drop_tombstones)
