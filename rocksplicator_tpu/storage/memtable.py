"""In-memory write buffer (memtable).

Reference: RocksDB memtable. Stores per-key op stacks (newest first) so
MERGE operands accumulate correctly before a flush; iteration yields
entries in (key asc, seq desc) order — the SST writer's required order.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from .merge import MergeOperator
from .records import OpType

# entry: (seq, vtype, value), newest first
_Entry = Tuple[int, int, bytes]


class MemTable:
    def __init__(self) -> None:
        self._data: Dict[bytes, List[_Entry]] = {}
        self._bytes = 0
        self.min_seq: Optional[int] = None
        self.max_seq = 0
        # Flat append-order columns mirroring _data — the vectorized
        # flush drain reads THESE (byte joins + np.fromiter, no dict
        # walk); ~4 list appends per write buy back ~70 ms per 200k-entry
        # flush. References only, no copies.
        self._flat_keys: List[bytes] = []
        self._flat_vals: List[bytes] = []
        self._flat_seqs: List[int] = []
        self._flat_vtypes: List[int] = []

    def apply(self, key: bytes, seq: int, vtype: int, value: bytes) -> None:
        self._data.setdefault(key, []).insert(0, (seq, vtype, value))
        self._flat_keys.append(key)
        self._flat_vals.append(value)
        self._flat_seqs.append(seq)
        self._flat_vtypes.append(vtype)
        self._bytes += len(key) + len(value) + 16
        if self.min_seq is None:
            self.min_seq = seq
        self.max_seq = max(self.max_seq, seq)

    def get(
        self, key: bytes, merge_op: Optional[MergeOperator]
    ) -> Tuple[bool, Optional[bytes], List[bytes]]:
        """Returns (resolved, value_or_None, pending_operands).

        resolved=True: value_or_None is the final answer (None = deleted).
        resolved=False: pending_operands are MERGE operands (newest last)
        still awaiting a base value from older levels.
        """
        entries = self._data.get(key)
        if not entries:
            return False, None, []
        operands: List[bytes] = []
        for seq, vtype, value in entries:  # newest -> oldest
            if vtype == OpType.PUT:
                if operands and merge_op:
                    return True, merge_op.merge(key, value, list(reversed(operands))), []
                return True, value, []
            if vtype == OpType.DELETE:
                if operands and merge_op:
                    return True, merge_op.merge(key, None, list(reversed(operands))), []
                return True, None, []
            if vtype == OpType.MERGE:
                operands.append(value)
        return False, None, list(reversed(operands))

    def absorb_older(self, older: "MemTable") -> None:
        """Fold an OLDER memtable's entries beneath this one's (flush-failure
        recovery path): older entries append after newer ones per key."""
        for key, entries in older._data.items():
            self._data.setdefault(key, []).extend(entries)
        self._flat_keys.extend(older._flat_keys)
        self._flat_vals.extend(older._flat_vals)
        self._flat_seqs.extend(older._flat_seqs)
        self._flat_vtypes.extend(older._flat_vtypes)
        self._bytes += older._bytes
        if older.min_seq is not None:
            self.min_seq = (
                older.min_seq if self.min_seq is None
                else min(self.min_seq, older.min_seq)
            )
        self.max_seq = max(self.max_seq, older.max_seq)

    def approximate_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._data)

    def entries(self) -> Iterator[Tuple[bytes, int, int, bytes]]:
        """(key, seq, vtype, value) in (key asc, seq desc) order."""
        for key in sorted(self._data):
            for seq, vtype, value in self._data[key]:
                yield key, seq, vtype, value

    def drain_lanes(self):
        """All entries as UNSORTED fixed-width lane arrays — the
        vectorized flush path (the caller lexsorts once over the key
        words, replacing the pure-Python ``sorted(self._data)`` +
        per-entry repack). Returns ``(lanes, key_bytes_matrix)`` or
        None when the planar lane representation can't express this
        memtable: non-uniform or zero/over-wide key length, non-uniform
        non-DELETE value widths, value wider than the planar u16 vlen
        field, or a DELETE carrying a value. Width checks run inline
        during collection so a disqualifying entry bails before any
        large buffer is built.

        ``lanes`` is the kernel lane dict (key_words_be, key_len,
        seq_hi/lo, vtype, val_words, val_len); the (n, klen) u8 key
        matrix rides along for bulk bloom construction. The columns come
        from the flat per-apply mirror lists, so no dict walk or
        per-entry tuple unpack happens here."""
        import numpy as np

        from .planar import PLANAR_MAX_KLEN, PLANAR_MAX_VLEN

        key_parts = self._flat_keys
        val_parts = self._flat_vals
        n = len(key_parts)
        if n == 0:
            return None
        # Width checks run VECTORIZED over the (cheap, 4n-byte) length
        # lanes before any value-byte buffer is built — one oversized
        # value among a million small ones bails here, not after a giant
        # transient allocation.
        klen = len(key_parts[0])
        if not (0 < klen <= PLANAR_MAX_KLEN):
            return None
        klens = np.fromiter(map(len, key_parts), dtype=np.uint32, count=n)
        if not bool((klens == klen).all()):
            return None
        vtype_arr = np.fromiter(
            self._flat_vtypes, dtype=np.uint32, count=n)
        vlens = np.fromiter(map(len, val_parts), dtype=np.uint32, count=n)
        is_del = vtype_arr == 2  # DELETE: no value in the planar layout
        if bool(vlens[is_del].any()):
            return None
        live_vlens = vlens[~is_del]
        vlen = int(live_vlens[0]) if len(live_vlens) else 0
        if vlen > PLANAR_MAX_VLEN or not bool((live_vlens == vlen).all()):
            return None
        key_mat = np.frombuffer(
            b"".join(key_parts), dtype=np.uint8).reshape(n, klen)
        seq = np.fromiter(self._flat_seqs, dtype=np.uint64, count=n)
        key_buf = np.zeros((n, 24), dtype=np.uint8)
        key_buf[:, :klen] = key_mat
        vw = max(2, (vlen + 3) // 4)
        val_buf = np.zeros((n, vw * 4), dtype=np.uint8)
        if vlen:
            if is_del.any():
                pad = bytes(vlen)
                joined = b"".join(v if v else pad for v in val_parts)
            else:
                joined = b"".join(val_parts)
            val_buf[:, :vlen] = np.frombuffer(
                joined, dtype=np.uint8).reshape(n, vlen)
        lanes = {
            "key_words_be": key_buf.view(">u4").astype(
                np.uint32).reshape(n, 6),
            "key_len": np.full(n, klen, dtype=np.uint32),
            "seq_hi": (seq >> np.uint64(32)).astype(np.uint32),
            "seq_lo": (seq & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            "vtype": vtype_arr,
            "val_words": val_buf.view("<u4").reshape(n, vw),
            "val_len": np.where(is_del, 0, vlen).astype(np.uint32),
        }
        return lanes, key_mat
