"""In-memory write buffer (memtable).

Reference: RocksDB memtable. Stores per-key op stacks (newest first) so
MERGE operands accumulate correctly before a flush; iteration yields
entries in (key asc, seq desc) order — the SST writer's required order.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from .merge import MergeOperator
from .records import OpType

# entry: (seq, vtype, value), newest first
_Entry = Tuple[int, int, bytes]


class MemTable:
    def __init__(self) -> None:
        self._data: Dict[bytes, List[_Entry]] = {}
        self._bytes = 0
        self.min_seq: Optional[int] = None
        self.max_seq = 0

    def apply(self, key: bytes, seq: int, vtype: int, value: bytes) -> None:
        self._data.setdefault(key, []).insert(0, (seq, vtype, value))
        self._bytes += len(key) + len(value) + 16
        if self.min_seq is None:
            self.min_seq = seq
        self.max_seq = max(self.max_seq, seq)

    def get(
        self, key: bytes, merge_op: Optional[MergeOperator]
    ) -> Tuple[bool, Optional[bytes], List[bytes]]:
        """Returns (resolved, value_or_None, pending_operands).

        resolved=True: value_or_None is the final answer (None = deleted).
        resolved=False: pending_operands are MERGE operands (newest last)
        still awaiting a base value from older levels.
        """
        entries = self._data.get(key)
        if not entries:
            return False, None, []
        operands: List[bytes] = []
        for seq, vtype, value in entries:  # newest -> oldest
            if vtype == OpType.PUT:
                if operands and merge_op:
                    return True, merge_op.merge(key, value, list(reversed(operands))), []
                return True, value, []
            if vtype == OpType.DELETE:
                if operands and merge_op:
                    return True, merge_op.merge(key, None, list(reversed(operands))), []
                return True, None, []
            if vtype == OpType.MERGE:
                operands.append(value)
        return False, None, list(reversed(operands))

    def absorb_older(self, older: "MemTable") -> None:
        """Fold an OLDER memtable's entries beneath this one's (flush-failure
        recovery path): older entries append after newer ones per key."""
        for key, entries in older._data.items():
            self._data.setdefault(key, []).extend(entries)
        self._bytes += older._bytes
        if older.min_seq is not None:
            self.min_seq = (
                older.min_seq if self.min_seq is None
                else min(self.min_seq, older.min_seq)
            )
        self.max_seq = max(self.max_seq, older.max_seq)

    def approximate_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._data)

    def entries(self) -> Iterator[Tuple[bytes, int, int, bytes]]:
        """(key, seq, vtype, value) in (key asc, seq desc) order."""
        for key in sorted(self._data):
            for seq, vtype, value in self._data[key]:
                yield key, seq, vtype, value
