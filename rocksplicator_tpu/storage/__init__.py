"""LSM storage engine — the L0 equivalent.

The reference vendors RocksDB as its storage engine (SURVEY.md §1 L0); this
package provides a from-scratch LSM engine with the API surface the upper
layers depend on: ``WriteBatch`` (incl. ``put_log_data`` for replication
timestamps), sequence numbers with ``get_updates_since``, checkpoints,
external-file ingestion (incl. ``ingest_behind``), backup/restore, merge
operators, and compaction with a pluggable backend — the seam where the TPU
offload plugs in (BASELINE.json north star).
"""

from .records import WriteBatch, OpType, decode_batch
from .engine import DB, DBOptions, destroy_db
from .errors import StorageError, NotFoundError, Corruption
from .merge import MergeOperator, UInt64AddOperator

__all__ = [
    "WriteBatch", "OpType", "decode_batch",
    "DB", "DBOptions", "destroy_db",
    "StorageError", "NotFoundError", "Corruption",
    "MergeOperator", "UInt64AddOperator",
]
