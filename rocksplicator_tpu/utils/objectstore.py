"""Object store abstraction — the S3Util equivalent.

Reference: common/s3util.{h,cpp} — AWS SDK wrapper with get/put/list(V2)/
delete/copy, ``getObjects(prefix, local_dir)`` batch download
(s3util.cpp:385-416), a direct-IO download path (s3util.h:82-103), rate
limiter hookup, and a ``BuildS3Util`` factory keyed by bucket + rate limit.

TPU-first design: a small ``ObjectStore`` interface with two backends:
``LocalObjectStore`` (a directory tree standing in for a bucket — used by
all tests and local deployments) and ``S3ObjectStore``, a real S3 backend
over the stdlib SigV4 wire client in ``utils/s3.py`` (works against AWS or
any S3-compatible endpoint; the in-process ``utils/s3_stub.py`` server
fills the reference's missing S3 mock, SURVEY §4). Parallel batched
transfer mirrors the reference's 8-thread upload/download executors
(admin_handler.cpp:399-407).
"""

from __future__ import annotations

import os
import shutil
import threading
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Dict, List, Optional, Tuple

from ..testing import failpoints as fp
from .rate_limiter import ConcurrentRateLimiter
from .retry_policy import RetryBudget, RetryPolicy, retry_call


class ObjectStoreError(Exception):
    pass


# batch-transfer retry: transient per-object failures inside
# get_objects/put_objects are retried under the unified policy (the S3
# and WebHDFS clients also retry transport errors internally; this layer
# catches what leaks through — and local-store EIO-class faults, which
# previously failed the whole batch on the first hiccup)
_BATCH_RETRY = RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=1.0)
_BATCH_BUDGET = RetryBudget(capacity=32.0, refill_per_sec=4.0)


def _transient_store_error(exc: BaseException) -> bool:
    """Retryable? Permanent object-store answers (missing key, bad
    bucket path) must surface immediately; transport-shaped failures
    (OSError incl. injected FailpointError, 5xx-status errors) retry."""
    status = getattr(exc, "status", None)
    if status is not None:
        return status == 0 or status >= 500
    if isinstance(exc, ObjectStoreError):
        return False
    return isinstance(exc, (OSError, ConnectionError))


class ObjectStore:
    """Abstract object store. Keys are '/'-separated paths within a bucket.
    Subclasses share the rate-limiter plumbing via ``_init_limiter`` /
    ``_charge`` (reference: S3Util rate limiter hookup)."""

    _limiter: Optional[ConcurrentRateLimiter] = None

    def _init_limiter(
        self, rate_limit_bytes_per_sec: Optional[float]
    ) -> None:
        self._limiter = (
            ConcurrentRateLimiter(rate_limit_bytes_per_sec)
            if rate_limit_bytes_per_sec
            else None
        )

    def _charge(self, nbytes: int) -> None:
        if self._limiter is not None and nbytes > 0:
            self._limiter.apply_cost(nbytes)

    def get_object(self, key: str, local_path: str,
                   direct_io: bool = False) -> None:
        raise NotImplementedError

    def get_object_bytes(self, key: str) -> bytes:
        raise NotImplementedError

    def put_object(self, local_path: str, key: str) -> None:
        raise NotImplementedError

    def put_object_bytes(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def list_objects(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def delete_object(self, key: str) -> None:
        raise NotImplementedError

    def copy_object(self, src_key: str, dst_key: str) -> None:
        raise NotImplementedError

    # -- batch ops (reference: s3util.cpp:385-416 + admin_handler 8-thread
    #    parallel batched checkpoint transfer) ----------------------------

    def get_objects(
        self, prefix: str, local_dir: str, parallelism: int = 8,
        direct_io: bool = False,
    ) -> List[str]:
        """Download every object under ``prefix`` into ``local_dir``.
        ``direct_io`` bypasses the page cache (O_DIRECT sink — reference
        s3util direct-IO download path). Returns local file paths.

        All-or-nothing: a failed fetch raises an ObjectStoreError naming
        the failing KEY (pool.map used to surface it as an opaque error
        mid-iteration) after the remaining fetches drain, and every file
        this call already produced — including the failing fetch's
        partial sink — is removed, so callers never see a half-downloaded
        batch directory."""
        keys = self.list_objects(prefix)
        os.makedirs(local_dir, exist_ok=True)
        results: List[str] = []
        lock = threading.Lock()

        def fetch(key: str) -> None:
            name = key[len(prefix):].lstrip("/") or os.path.basename(key)
            local_path = os.path.join(local_dir, name)
            os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
            try:
                retry_call(
                    lambda: self.get_object(
                        key, local_path, direct_io=direct_io),
                    policy=_BATCH_RETRY, classify=_transient_store_error,
                    op="objectstore.get", budget=_BATCH_BUDGET,
                )
            except Exception as e:
                try:
                    os.remove(local_path)  # partial sink
                except OSError:
                    pass
                raise ObjectStoreError(
                    f"get_objects: fetch of {key!r} failed: {e}") from e
            with lock:
                results.append(local_path)

        with ThreadPoolExecutor(max_workers=parallelism) as pool:
            error: Optional[ObjectStoreError] = None
            for fut in as_completed([pool.submit(fetch, k) for k in keys]):
                exc = fut.exception()
                if exc is not None and error is None:
                    error = exc  # first failure wins; let the rest drain
        if error is not None:
            for path in results:
                try:
                    os.remove(path)
                except OSError:
                    pass
            raise error
        return sorted(results)

    def put_objects(
        self, local_paths: List[str], prefix: str, parallelism: int = 8
    ) -> List[str]:
        """Upload files under ``prefix``; returns the object keys. Keys are
        ``prefix/<basename>``; duplicate basenames would silently overwrite
        each other, so they are rejected up front."""
        basenames = [os.path.basename(p) for p in local_paths]
        if len(set(basenames)) != len(basenames):
            dupes = sorted({b for b in basenames if basenames.count(b) > 1})
            raise ObjectStoreError(f"duplicate basenames in batch: {dupes}")
        keys: List[str] = []
        lock = threading.Lock()

        def push(local_path: str) -> None:
            key = prefix.rstrip("/") + "/" + os.path.basename(local_path)
            retry_call(
                lambda: self.put_object(local_path, key),
                policy=_BATCH_RETRY, classify=_transient_store_error,
                op="objectstore.put", budget=_BATCH_BUDGET,
            )
            with lock:
                keys.append(key)

        with ThreadPoolExecutor(max_workers=parallelism) as pool:
            list(pool.map(push, local_paths))
        return sorted(keys)


class LocalObjectStore(ObjectStore):
    """Directory-backed object store: bucket == a root directory."""

    def __init__(
        self,
        root: str,
        rate_limit_bytes_per_sec: Optional[float] = None,
    ):
        self._root = os.path.abspath(root)
        os.makedirs(self._root, exist_ok=True)
        self._init_limiter(rate_limit_bytes_per_sec)

    def _path(self, key: str) -> str:
        key = key.lstrip("/")
        path = os.path.abspath(os.path.join(self._root, key))
        if not path.startswith(self._root + os.sep) and path != self._root:
            raise ObjectStoreError(f"key escapes bucket root: {key!r}")
        return path

    def get_object(self, key: str, local_path: str,
                   direct_io: bool = False) -> None:
        fp.hit("objectstore.get")
        src = self._path(key)
        if not os.path.isfile(src):
            raise ObjectStoreError(f"no such object: {key}")
        self._charge(os.path.getsize(src))
        os.makedirs(os.path.dirname(os.path.abspath(local_path)), exist_ok=True)
        if direct_io:
            from .directio import DirectIOFile

            with open(src, "rb") as f, DirectIOFile(local_path) as out:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    out.write(chunk)
        else:
            # Zero-copy fast path when bucket and sink share a filesystem:
            # hardlink instead of copying the bytes (the dominant download
            # cost of a local-store SST bulk-ingest). Consumers that would
            # MUTATE the file must break the link themselves — the engine's
            # ingest does (its global-seqno footer rewrite would otherwise
            # write through to the bucket object). EXDEV/perm failures fall
            # back to the copy.
            try:
                if os.path.lexists(local_path):
                    os.remove(local_path)
                os.link(src, local_path)
            except OSError:
                shutil.copyfile(src, local_path)

    def get_object_bytes(self, key: str) -> bytes:
        fp.hit("objectstore.get")
        src = self._path(key)
        if not os.path.isfile(src):
            raise ObjectStoreError(f"no such object: {key}")
        with open(src, "rb") as f:
            data = f.read()
        self._charge(len(data))
        return data

    def put_object(self, local_path: str, key: str) -> None:
        fp.hit("objectstore.put")
        if not os.path.isfile(local_path):
            raise ObjectStoreError(f"no such local file: {local_path}")
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        self._charge(os.path.getsize(local_path))
        tmp = self._tmp_name(dst)
        shutil.copyfile(local_path, tmp)
        os.replace(tmp, dst)

    def put_object_bytes(self, key: str, data: bytes) -> None:
        fp.hit("objectstore.put")
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        self._charge(len(data))
        tmp = self._tmp_name(dst)
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, dst)

    @staticmethod
    def _tmp_name(dst: str) -> str:
        # Unique per writer so concurrent puts to one key can't interleave
        # in a shared temp file; last os.replace() wins atomically.
        return f"{dst}.{os.getpid()}.{threading.get_ident()}.tmp"

    def list_objects(self, prefix: str) -> List[str]:
        prefix = prefix.lstrip("/")
        out: List[str] = []
        for dirpath, _dirnames, filenames in os.walk(self._root):
            for name in filenames:
                if name.endswith(".tmp"):  # in-flight writer temp files
                    continue
                full = os.path.join(dirpath, name)
                key = os.path.relpath(full, self._root).replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete_object(self, key: str) -> None:
        path = self._path(key)
        try:
            os.remove(path)
        except FileNotFoundError:
            raise ObjectStoreError(f"no such object: {key}") from None

    def copy_object(self, src_key: str, dst_key: str) -> None:
        src, dst = self._path(src_key), self._path(dst_key)
        if not os.path.isfile(src):
            raise ObjectStoreError(f"no such object: {src_key}")
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copyfile(src, dst)


class S3ObjectStore(ObjectStore):
    """Real S3 backend over the stdlib SigV4 client (utils/s3.py) — works
    against AWS or any S3-compatible endpoint (minio, the s3_stub test
    server). Mirrors the reference S3Util surface (common/s3util.cpp:
    get/put/listV2/delete/copy + batch transfer + rate limiting). Cloud
    integration tests stay gated behind RSTPU_S3_INTEGRATION like the
    reference's --enable_integration_test."""

    def __init__(
        self,
        bucket: str,
        rate_limit_bytes_per_sec: Optional[float] = None,
        endpoint: Optional[str] = None,
    ):
        from .s3 import S3Client, S3Config, S3Error

        self._S3Error = S3Error
        cfg = S3Config()
        if endpoint:
            cfg.endpoint = endpoint
        try:
            self._client = S3Client(bucket, cfg)
        except S3Error as e:
            raise ObjectStoreError(str(e)) from e
        self._init_limiter(rate_limit_bytes_per_sec)

    def _wrap(self, fn, *args):
        try:
            return fn(*args)
        except self._S3Error as e:
            err = ObjectStoreError(str(e))
            # preserve the HTTP status so the batch-retry classifier
            # treats a 5xx/transport S3 failure like its HDFS twin
            err.status = e.status
            raise err from e

    def get_object(self, key: str, local_path: str,
                   direct_io: bool = False) -> None:
        os.makedirs(
            os.path.dirname(os.path.abspath(local_path)), exist_ok=True)
        n = self._wrap(
            self._client.get_object_to_file, key.lstrip("/"), local_path,
            direct_io)
        self._charge(n)

    def get_object_bytes(self, key: str) -> bytes:
        data = self._wrap(self._client.get_object, key.lstrip("/"))
        self._charge(len(data))
        return data

    def put_object(self, local_path: str, key: str) -> None:
        if not os.path.isfile(local_path):
            raise ObjectStoreError(f"no such local file: {local_path}")
        self._charge(os.path.getsize(local_path))
        self._wrap(
            self._client.put_object_from_file, key.lstrip("/"), local_path)

    def put_object_bytes(self, key: str, data: bytes) -> None:
        self._charge(len(data))
        self._wrap(self._client.put_object, key.lstrip("/"), data)

    def list_objects(self, prefix: str) -> List[str]:
        return self._wrap(self._client.list_objects, prefix.lstrip("/"))

    def delete_object(self, key: str) -> None:
        key = key.lstrip("/")
        # S3 DELETE is idempotent (204 for absent keys); preserve the
        # ObjectStore contract that deleting a missing object raises.
        # (Best-effort: the HEAD+DELETE pair is not atomic — concurrent
        # deleters may both succeed, which is acceptable for backup GC.)
        if not self._wrap(self._client.head_object, key):
            raise ObjectStoreError(f"no such object: {key}")
        self._wrap(self._client.delete_object, key)

    def copy_object(self, src_key: str, dst_key: str) -> None:
        self._wrap(self._client.copy_object, src_key.lstrip("/"),
                   dst_key.lstrip("/"))


# -- factory (reference: S3Util::BuildS3Util keyed by bucket+ratelimit) ----

_store_cache: Dict[Tuple[str, Optional[float]], ObjectStore] = {}
_store_cache_lock = threading.Lock()


def build_object_store(
    uri: str, rate_limit_bytes_per_sec: Optional[float] = None
) -> ObjectStore:
    """``local:///path`` or bare ``/path`` → LocalObjectStore; ``s3://bucket``
    → S3ObjectStore; ``hdfs://namenode:port/base`` → HdfsObjectStore
    (WebHDFS). Cached by (uri, ratelimit) like BuildS3Util."""
    key = (uri, rate_limit_bytes_per_sec)
    with _store_cache_lock:
        store = _store_cache.get(key)
        if store is None:
            if uri.startswith("s3://"):
                store = S3ObjectStore(uri[5:], rate_limit_bytes_per_sec)
            elif uri.startswith("hdfs://"):
                from .hdfs import HdfsObjectStore

                store = HdfsObjectStore(uri, rate_limit_bytes_per_sec)
            elif uri.startswith("local://"):
                store = LocalObjectStore(uri[8:], rate_limit_bytes_per_sec)
            else:
                store = LocalObjectStore(uri, rate_limit_bytes_per_sec)
            _store_cache[key] = store
        return store
