"""Common runtime utilities (reference: common/ — SURVEY.md §2.3)."""
