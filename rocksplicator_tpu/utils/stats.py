"""Stats: counters, metrics (latency histograms), and gauges.

Reference: common/stats/stats.{h,cpp}:89-241 — thread-local lock-free
counters/metrics flushed ~1s into global folly MultiLevelTimeSeries /
TimeseriesHistogram with 1-minute windows; dynamic string names plus
pre-registered enum names; pull-model gauges; text dump for the status
server; tag-style names like ``metric segment=x db=y``
(application_db_manager.cpp:120-125).

TPU-first design notes: the structure is the same (thread-local write path,
windowed global aggregation, pull-model text export), but implemented with
per-thread buffers drained on read rather than a background flusher thread —
Python threads are cheap to enumerate and the read path is cold.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Windowed aggregation
# ---------------------------------------------------------------------------

_WINDOW_SEC = 60          # one-minute windows, like the reference
_NUM_WINDOWS = 60         # keep an hour of per-minute buckets


class _TimeSeries:
    """Multi-level-ish time series: per-minute buckets + all-time total."""

    __slots__ = ("buckets", "total")

    def __init__(self) -> None:
        self.buckets: Dict[int, float] = {}
        self.total = 0.0

    def add(self, value: float, now: float) -> None:
        b = int(now // _WINDOW_SEC)
        self.buckets[b] = self.buckets.get(b, 0.0) + value
        self.total += value
        if len(self.buckets) > _NUM_WINDOWS + 2:
            cutoff = b - _NUM_WINDOWS
            for k in [k for k in self.buckets if k < cutoff]:
                del self.buckets[k]

    def rate_last_minute(self, now: float) -> float:
        # Sliding-window estimate: current partial bucket plus the previous
        # bucket weighted by its unexpired fraction (avoids the up-to-2x
        # over-read of naively summing both buckets).
        b = int(now // _WINDOW_SEC)
        frac_elapsed = (now - b * _WINDOW_SEC) / _WINDOW_SEC
        return self.buckets.get(b, 0.0) + self.buckets.get(b - 1, 0.0) * (
            1.0 - frac_elapsed
        )


class _Histogram:
    """Windowed histogram with percentile queries (log-spaced buckets).

    Besides the ~2-window view the percentile reads use, an ALL-TIME
    sparse bucket map (``totals``) accumulates forever: it is what the
    Prometheus ``/metrics`` export renders (native histograms must be
    monotone counters) and what the spectator's cross-replica merge
    sums — a log-bucket merge is lossless by construction (same bucket
    edges everywhere, merge = vector add)."""

    __slots__ = ("windows", "count", "sum", "totals")

    # log-spaced buckets, 8 per octave (~9% relative resolution), covering
    # 2^-4 (0.0625) .. 2^40 (~1e12) — enough for sub-ms latencies through
    # byte counts.
    _SUB = 8
    _MIN_EXP = -4 * 8
    _MAX_EXP = 40 * 8

    def __init__(self) -> None:
        self.windows: Dict[int, List[int]] = {}
        self.count = 0
        self.sum = 0.0
        self.totals: Dict[int, int] = {}

    @classmethod
    def _bucket_of(cls, value: float) -> int:
        if value <= 0:
            return 0
        e = int(math.floor(math.log2(value) * cls._SUB))
        return max(cls._MIN_EXP, min(cls._MAX_EXP, e)) - cls._MIN_EXP

    @classmethod
    def _bucket_value(cls, idx: int) -> float:
        # Upper edge of the bucket — conservative for percentile reads.
        return 2.0 ** ((idx + cls._MIN_EXP + 1) / cls._SUB)

    def add(self, value: float, now: float) -> None:
        w = int(now // _WINDOW_SEC)
        buckets = self.windows.get(w)
        if buckets is None:
            buckets = [0] * (self._MAX_EXP - self._MIN_EXP + 1)
            self.windows[w] = buckets
            if len(self.windows) > 3:
                cutoff = w - 2
                for k in [k for k in self.windows if k < cutoff]:
                    del self.windows[k]
        b = self._bucket_of(value)
        buckets[b] += 1
        self.totals[b] = self.totals.get(b, 0) + 1
        self.count += 1
        self.sum += value

    def percentile(self, pct: float, now: Optional[float] = None) -> float:
        """Percentile over the last ~2 windows."""
        now = time.time() if now is None else now
        w = int(now // _WINDOW_SEC)
        merged = [0] * (self._MAX_EXP - self._MIN_EXP + 1)
        for k in (w, w - 1):
            b = self.windows.get(k)
            if b:
                for i, c in enumerate(b):
                    merged[i] += c
        total = sum(merged)
        if total == 0:
            return 0.0
        target = total * pct / 100.0
        acc = 0
        for i, c in enumerate(merged):
            acc += c
            if acc >= target:
                return self._bucket_value(i)
        return self._bucket_value(len(merged) - 1)

    def avg(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def state(self) -> Dict:
        """Serializable all-time state: the scrape-RPC / merge shape.
        Bucket keys are stringified indices (JSON object keys)."""
        return {
            "count": self.count,
            "sum": self.sum,
            "buckets": {str(i): c for i, c in sorted(self.totals.items())},
        }


def merge_histogram_states(states: List[Dict]) -> Dict:
    """EXACT merge of histogram states (``_Histogram.state()`` shape):
    every replica buckets with the same log-spaced edges, so the merge
    is a plain per-bucket sum — no resampling, no approximation beyond
    the original per-replica bucketing."""
    buckets: Dict[int, int] = {}
    count = 0
    total = 0.0
    for st in states:
        if not st:
            continue
        count += int(st.get("count", 0))
        total += float(st.get("sum", 0.0))
        for k, c in (st.get("buckets") or {}).items():
            i = int(k)
            buckets[i] = buckets.get(i, 0) + int(c)
    return {
        "count": count,
        "sum": total,
        "buckets": {str(i): c for i, c in sorted(buckets.items())},
    }


def histogram_state_percentile(state: Dict, pct: float) -> float:
    """Percentile over a (possibly merged) histogram state. Same
    conservative upper-edge convention as ``_Histogram.percentile``."""
    buckets = [(int(k), int(c)) for k, c in (state.get("buckets") or {}).items()]
    buckets.sort()
    total = sum(c for _i, c in buckets)
    if total == 0:
        return 0.0
    target = total * pct / 100.0
    acc = 0
    for i, c in buckets:
        acc += c
        if acc >= target:
            return _Histogram._bucket_value(i)
    return _Histogram._bucket_value(buckets[-1][0])


# ---------------------------------------------------------------------------
# Thread-local write path
# ---------------------------------------------------------------------------


class _ThreadBuffer(threading.local):
    def __init__(self) -> None:
        self.counters: Dict[str, float] = defaultdict(float)
        self.metrics: Dict[str, List[float]] = defaultdict(list)
        # Guards this thread's buffers against a concurrent flush() drain.
        # Mostly uncontended (owner thread vs the occasional drainer).
        self.lock = threading.Lock()


class Stats:
    """Process-wide stats registry.

    API mirrors the reference (stats.h:89-241): ``incr`` (Incr),
    ``add_metric`` (AddMetric), gauges with pull callbacks, and
    ``dump_text`` for the status server.
    """

    _instance: Optional["Stats"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        import os
        import uuid

        self._lock = threading.Lock()
        # process-INSTANCE identity for scrape exports: pid alone is not
        # unique across hosts/containers (every containerized replica is
        # commonly pid 1), so a random token minted per registry makes
        # the aggregator's shared-registry dedup safe fleet-wide —
        # endpoints sharing one registry share the token; distinct
        # processes never do
        self._export_id = f"pid:{os.getpid()}:{uuid.uuid4().hex[:12]}"
        self._counters: Dict[str, _TimeSeries] = {}
        self._metrics: Dict[str, _Histogram] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._tls = _ThreadBuffer()
        self._all_buffers: List[_ThreadBuffer] = []
        self._buffers_lock = threading.Lock()
        self._flush_interval = 1.0
        self._last_flush = 0.0
        # whole-process scrape-dump cache (round 22): at fleet shape a
        # scrape walks every registered series and evaluates every
        # shard's gauges — O(shards) per scrape per SCRAPER. One cached
        # pass with a short TTL makes concurrent/periodic scrapers
        # (spectator, /metrics pollers, stats RPC) share it.
        self._dump_ttl = 0.5
        self._dump_lock = threading.Lock()
        self._export_cache: Tuple[float, Optional[Dict]] = (0.0, None)
        self._prom_cache: Tuple[float, Optional[str]] = (0.0, None)

    # -- singleton --------------------------------------------------------

    @classmethod
    def get(cls) -> "Stats":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    @classmethod
    def reset_for_test(cls) -> None:
        with cls._instance_lock:
            cls._instance = cls()

    # -- write path (hot; thread-local, no lock) --------------------------

    def incr(self, name: str, value: float = 1.0) -> None:
        buf = self._buf()
        with buf.lock:
            buf.counters[name] += value
        self._maybe_flush()

    def add_metric(self, name: str, value: float) -> None:
        buf = self._buf()
        with buf.lock:
            buf.metrics[name].append(value)
        self._maybe_flush()

    def add_gauge(self, name: str, callback: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = callback

    def remove_gauge(self, name: str) -> None:
        with self._lock:
            self._gauges.pop(name, None)

    # -- internals --------------------------------------------------------

    def _buf(self) -> _ThreadBuffer:
        buf = self._tls
        if not getattr(buf, "_registered", False):
            with self._buffers_lock:
                self._all_buffers.append(
                    _Snapshot(buf.counters, buf.metrics, buf.lock,
                              threading.current_thread())
                )
            buf._registered = True  # type: ignore[attr-defined]
        return buf

    def _maybe_flush(self) -> None:
        now = time.time()
        if now - self._last_flush >= self._flush_interval:
            self.flush(now)

    def flush(self, now: Optional[float] = None) -> None:
        """Drain every thread's buffer into the global windowed stores."""
        now = time.time() if now is None else now
        self._last_flush = now
        with self._buffers_lock:
            snaps = list(self._all_buffers)
        dead: List[_Snapshot] = []
        with self._lock:
            for snap in snaps:
                with snap.lock:
                    counters = list(snap.counters.items())
                    snap.counters.clear()
                    metrics = list(snap.metrics.items())
                    snap.metrics.clear()
                    if not snap.owner.is_alive():
                        dead.append(snap)
                for name, v in counters:
                    ts = self._counters.get(name)
                    if ts is None:
                        ts = self._counters[name] = _TimeSeries()
                    ts.add(v, now)
                for name, vals in metrics:
                    h = self._metrics.get(name)
                    if h is None:
                        h = self._metrics[name] = _Histogram()
                    for v in vals:
                        h.add(v, now)
        if dead:
            # Prune drained buffers of exited threads so _all_buffers does
            # not grow with every short-lived worker thread.
            with self._buffers_lock:
                self._all_buffers = [
                    s for s in self._all_buffers if s not in dead
                ]

    # -- read path --------------------------------------------------------

    def get_counter(self, name: str) -> float:
        self.flush()
        with self._lock:
            ts = self._counters.get(name)
            return ts.total if ts else 0.0

    def counter_rate(self, name: str) -> float:
        self.flush()
        now = time.time()
        with self._lock:
            ts = self._counters.get(name)
            return ts.rate_last_minute(now) if ts else 0.0

    def metric_percentile(self, name: str, pct: float) -> float:
        self.flush()
        with self._lock:
            h = self._metrics.get(name)
            return h.percentile(pct) if h else 0.0

    def metric_avg(self, name: str) -> float:
        self.flush()
        with self._lock:
            h = self._metrics.get(name)
            return h.avg() if h else 0.0

    def metric_count(self, name: str) -> int:
        self.flush()
        with self._lock:
            h = self._metrics.get(name)
            return h.count if h else 0

    def dump_text(self) -> str:
        """stats.txt-style dump (status_server.cpp /stats.txt endpoint)."""
        self.flush()
        now = time.time()
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._counters):
                ts = self._counters[name]
                lines.append(
                    f"counter {name} total={ts.total:.0f} "
                    f"last_minute={ts.rate_last_minute(now):.0f}"
                )
            for name in sorted(self._metrics):
                h = self._metrics[name]
                lines.append(
                    f"metric {name} count={h.count} avg={h.avg():.3f} "
                    f"p50={h.percentile(50, now):.3f} "
                    f"p90={h.percentile(90, now):.3f} "
                    f"p99={h.percentile(99, now):.3f}"
                )
            gauges = list(self._gauges.items())
        for name, cb in sorted(gauges):
            try:
                lines.append(f"gauge {name} value={cb():.3f}")
            except Exception as e:  # pragma: no cover - defensive
                lines.append(f"gauge {name} error={e!r}")
        return "\n".join(lines) + "\n"

    def gauge_values(
        self, prefixes: Optional[Tuple[str, ...]] = None
    ) -> Dict[str, float]:
        """Evaluate registered gauges (optionally filtered by base-name
        prefix). Callbacks run OUTSIDE the stats lock — a gauge is free
        to take its own subsystem's locks (the engine snapshot does)."""
        with self._lock:
            gauges = list(self._gauges.items())
        out: Dict[str, float] = {}
        for name, cb in gauges:
            if prefixes is not None and not name.startswith(prefixes):
                continue
            try:
                out[name] = float(cb())
            except Exception:  # pragma: no cover - defensive
                continue
        return out

    def export_state(self) -> Dict:
        """The scrape-RPC body: every counter (all-time total + 1-minute
        rate), every histogram's exact all-time state, every gauge's
        current value — JSON-serializable, mergeable across replicas by
        the spectator (``merge_histogram_states`` et al.). Carries the
        process identity: in-process multi-replicator topologies
        (chaos/cluster tests) share ONE registry, so an aggregator
        scraping two such endpoints must count the registry once, not
        twice (stats_aggregator dedupes on this field)."""
        self.flush()
        now = time.time()
        with self._lock:
            counters = {
                name: {"total": ts.total,
                       "rate_1m": ts.rate_last_minute(now)}
                for name, ts in self._counters.items()
            }
            metrics = {name: h.state() for name, h in self._metrics.items()}
        return {
            "time": now,
            "process": self._export_id,
            "counters": counters,
            "metrics": metrics,
            "gauges": self.gauge_values(),
        }

    def export_state_cached(self, max_age: Optional[float] = None) -> Dict:
        """``export_state`` behind the whole-process dump cache: one
        registry pass (and ONE gauge-callback sweep — the O(shards)
        cost) serves every scraper inside the TTL. Single-flight: a
        scraper finding the cache stale builds the dump under the dump
        lock while concurrent scrapers wait and reuse it. Callers must
        treat the dict as frozen (the stats-RPC handler copies the top
        level before annotating)."""
        ttl = self._dump_ttl if max_age is None else max_age
        at, cached = self._export_cache
        if cached is not None and time.monotonic() - at < ttl:
            return cached
        with self._dump_lock:
            at, cached = self._export_cache
            if cached is not None and time.monotonic() - at < ttl:
                return cached
            state = self.export_state()
            self._export_cache = (time.monotonic(), state)
            return state

    def dump_prometheus_cached(self, max_age: Optional[float] = None) -> str:
        """``dump_prometheus`` behind the same short-TTL cache (its own
        slot — the two dumps have different shapes but share the
        sub-linear-in-scrapers property)."""
        ttl = self._dump_ttl if max_age is None else max_age
        at, cached = self._prom_cache
        if cached is not None and time.monotonic() - at < ttl:
            return cached
        with self._dump_lock:
            at, cached = self._prom_cache
            if cached is not None and time.monotonic() - at < ttl:
                return cached
            text = self.dump_prometheus()
            self._prom_cache = (time.monotonic(), text)
            return text

    def dump_prometheus(self) -> str:
        """Prometheus text exposition of counters, gauges, and the
        log-bucketed histograms (classic ``_bucket``/``_sum``/``_count``
        lines over the ALL-TIME totals, so every series is the monotone
        counter Prometheus requires). Tagged names (``name k=v``) become
        labels; dotted names become underscore-joined metric names under
        the ``rstpu_`` namespace."""
        self.flush()
        now = time.time()
        with self._lock:
            counters = [(n, ts.total, ts.rate_last_minute(now))
                        for n, ts in self._counters.items()]
            metrics = [(n, h.state()) for n, h in self._metrics.items()]
        gauges = self.gauge_values()

        # family name -> (type, sample lines); one TYPE header per family
        families: Dict[str, Tuple[str, List[str]]] = {}

        def fam_of(base: str, ftype: str) -> List[str]:
            fam = _prom_name(base) + ("_total" if ftype == "counter" else "")
            return families.setdefault(fam, (ftype, []))[1]

        for name, total, _rate in sorted(counters):
            base, tags = split_tagged(name)
            fam_of(base, "counter").append(
                f"{_prom_name(base)}_total{_prom_labels(tags)} "
                f"{_prom_num(total)}")
        for name, value in sorted(gauges.items()):
            base, tags = split_tagged(name)
            fam_of(base, "gauge").append(
                f"{_prom_name(base)}{_prom_labels(tags)} "
                f"{_prom_num(value)}")
        for name, state in sorted(metrics):
            base, tags = split_tagged(name)
            fam = _prom_name(base)
            lines = fam_of(base, "histogram")
            acc = 0
            for k, c in sorted(
                    ((int(i), c) for i, c in state["buckets"].items())):
                acc += c
                le = _Histogram._bucket_value(k)
                lines.append(
                    f"{fam}_bucket"
                    f"{_prom_labels(tags, le=_prom_num(le))} {acc}")
            lines.append(
                f"{fam}_bucket{_prom_labels(tags, le='+Inf')} "
                f"{state['count']}")
            lines.append(
                f"{fam}_sum{_prom_labels(tags)} {_prom_num(state['sum'])}")
            lines.append(
                f"{fam}_count{_prom_labels(tags)} {state['count']}")

        out: List[str] = []
        for fam in sorted(families):
            ftype, lines = families[fam]
            out.append(f"# TYPE {fam} {ftype}")
            out.extend(lines)
        return "\n".join(out) + "\n"


class _Snapshot:
    """Holds references to a thread's buffers so flush() can drain them."""

    __slots__ = ("counters", "metrics", "lock", "owner")

    def __init__(self, counters, metrics, lock, owner):
        self.counters = counters
        self.metrics = metrics
        self.lock = lock
        self.owner = owner


def tagged(name: str, **tags: str) -> str:
    """Tag-style metric naming: ``tagged("db_size", db="seg00001")`` →
    ``"db_size db=seg00001"`` (reference application_db_manager.cpp:120-125)."""
    if not tags:
        return name
    return name + " " + " ".join(f"{k}={v}" for k, v in sorted(tags.items()))


def split_tagged(name: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`tagged`: ``"db_size db=seg00001"`` →
    ``("db_size", {"db": "seg00001"})``. Tokens without ``=`` after the
    base name are kept verbatim in a ``_`` tag rather than dropped."""
    parts = name.split(" ")
    tags: Dict[str, str] = {}
    for tok in parts[1:]:
        k, sep, v = tok.partition("=")
        if sep:
            tags[k] = v
        elif tok:
            tags["_"] = tok
    return parts[0], tags


def _prom_name(base: str) -> str:
    """Dotted stats name → Prometheus metric name (``rstpu_`` namespace,
    ``[a-zA-Z0-9_:]`` alphabet)."""
    safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in base)
    return "rstpu_" + safe


def _prom_labels(tags: Dict[str, str], **extra: str) -> str:
    items = dict(tags)
    items.update(extra)
    if not items:
        return ""
    def esc(v: str) -> str:
        return str(v).replace("\\", "\\\\").replace('"', '\\"')
    return ("{" + ",".join(
        f'{k}="{esc(v)}"' for k, v in sorted(items.items())) + "}")


def _prom_num(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


_PROM_LINE = None  # compiled lazily (keeps `re` off the hot import path)


def parse_prometheus_text(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Strict-enough parser for the Prometheus text format the export
    produces: returns ``{metric_name: [(labels, value), ...]}``. Raises
    ``ValueError`` on any line that is neither a comment nor a valid
    sample — the metrics-smoke gate."""
    import re

    global _PROM_LINE
    if _PROM_LINE is None:
        _PROM_LINE = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*)\})?'
            r' ([0-9eE+.\-]+|\+Inf|-Inf|NaN)$')
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            raise ValueError(f"unparseable metrics line {lineno}: {line!r}")
        name, rawlabels, rawval = m.groups()
        labels: Dict[str, str] = {}
        if rawlabels:
            for part in re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)='
                                   r'"((?:[^"\\]|\\.)*)"', rawlabels):
                labels[part[0]] = part[1]
        value = float("inf") if rawval == "+Inf" else (
            float("-inf") if rawval == "-Inf" else float(rawval))
        out.setdefault(name, []).append((labels, value))
    return out
