"""HDFS object store over the WebHDFS REST protocol (stdlib-only).

Reference: the admin plane's backupDB/restoreDB run over ``NewHdfsEnv``
(rocksdb_admin/admin_handler.cpp:696-863) — RocksDB file IO against an
HDFS deployment. Here HDFS is one more backend behind the ObjectStore
URI seam (``hdfs://namenode:port/base``), speaking WebHDFS:

  CREATE  PUT    /webhdfs/v1/<p>?op=CREATE&overwrite=true  -> 307 -> PUT data
  OPEN    GET    /webhdfs/v1/<p>?op=OPEN                   -> 307 -> GET data
  LIST    GET    /webhdfs/v1/<p>?op=LISTSTATUS             -> FileStatuses
  DELETE  DELETE /webhdfs/v1/<p>?op=DELETE&recursive=false
  MKDIRS  PUT    /webhdfs/v1/<p>?op=MKDIRS

The two-step redirect (namenode chooses a datanode) is followed
manually — stdlib redirect handling drops PUT bodies. No kerberos/auth
(``user.name`` query param only), matching the reference's simple-auth
HdfsEnv usage. Integration against a live cluster is env-gated the same
way as S3 (RSTPU_HDFS_INTEGRATION=hdfs://...); CI drives the protocol
against a stub WebHDFS server (tests/test_hdfs.py).
"""

from __future__ import annotations

import http.client
import json
import os
import random
import urllib.parse
from typing import List, Optional, Tuple

from ..testing import failpoints as fp
from .objectstore import ObjectStore, ObjectStoreError
from .retry_policy import RetryBudget, RetryPolicy, retry_call

_MAX_REDIRECTS = 4
_CHUNK = 1 << 20

# transient-failure retry under the unified policy (previously WebHDFS
# had NO retry: one namenode hiccup failed the whole backup/restore)
_HDFS_RETRY = RetryPolicy(max_attempts=4, base_delay=0.1, max_delay=5.0)
_HDFS_RETRY_BUDGET = RetryBudget(capacity=20.0, refill_per_sec=2.0)


def _transient_hdfs_error(exc: BaseException) -> bool:
    if isinstance(exc, HdfsError):
        # 0 = transport-level; 5xx = server-side transient. 4xx (missing
        # path, bad op) and 3xx anomalies are permanent.
        return exc.status == 0 or exc.status >= 500
    return isinstance(exc, (OSError, http.client.HTTPException))


class HdfsError(ObjectStoreError):
    """WebHDFS failure; ``status`` carries the HTTP code (0 = transport)."""

    def __init__(self, msg: str, status: int = 0):
        super().__init__(msg)
        self.status = status


def _parse_uri(uri: str) -> Tuple[str, int, str]:
    """hdfs://host:port/base -> (host, port, /base)."""
    parsed = urllib.parse.urlsplit(uri)
    if parsed.scheme != "hdfs" or not parsed.hostname:
        raise ValueError(f"not an hdfs:// URI: {uri}")
    return (parsed.hostname, parsed.port or 9870,
            parsed.path.rstrip("/"))


class HdfsObjectStore(ObjectStore):
    def __init__(self, uri: str,
                 rate_limit_bytes_per_sec: Optional[float] = None,
                 user: Optional[str] = None, timeout: float = 60.0):
        self._host, self._port, self._base = _parse_uri(
            uri if uri.startswith("hdfs://") else f"hdfs://{uri}")
        self._user = user or os.environ.get("RSTPU_HDFS_USER", "rstpu")
        self._timeout = timeout
        self._init_limiter(rate_limit_bytes_per_sec)

    # -- REST plumbing -----------------------------------------------------

    def _path(self, key: str) -> str:
        return f"{self._base}/{key.lstrip('/')}" if key else self._base

    def _url(self, path: str, op: str, **params) -> str:
        q = {"op": op, "user.name": self._user, **params}
        return (f"/webhdfs/v1{urllib.parse.quote(path)}"
                f"?{urllib.parse.urlencode(q)}")

    def _send(self, host: str, port: int, method: str, url: str, body,
              sink=None):
        """One HTTP exchange. Returns (status, location, data). With a
        ``sink`` file object, a 2xx response body is streamed into it in
        _CHUNK pieces and ``data`` is b""."""
        fp.hit("hdfs.request")  # OSError-shaped: absorbed by the retry
        conn = http.client.HTTPConnection(host, port, timeout=self._timeout)
        try:
            headers = {}
            if body is not None and hasattr(body, "read"):
                body.seek(0)  # redirect retries must resend from the start
                # explicit length: http.client would otherwise fall back
                # to chunked transfer, which plain HTTP/1.0 datanode
                # stubs (and some gateways) do not accept
                headers["Content-Length"] = str(
                    os.fstat(body.fileno()).st_size)
            conn.request(method, url, body=body, headers=headers)
            resp = conn.getresponse()
            if resp.status in (301, 302, 307):
                loc = resp.getheader("Location")
                resp.read()
                return resp.status, loc, b""
            if sink is not None and resp.status < 300:
                while True:
                    chunk = resp.read(_CHUNK)
                    if not chunk:
                        return resp.status, None, b""
                    sink.write(chunk)
                    self._charge(len(chunk))
            return resp.status, None, resp.read()
        finally:
            conn.close()

    def _request(self, method: str, path: str, op: str, body=None,
                 sink=None, **params):
        """One WebHDFS op with transient-failure retries (exp backoff +
        full jitter + shared budget — utils/retry_policy.py). Retries
        are safe: CREATE is overwrite-idempotent (file bodies are
        rewound in ``_send``), OPEN re-streams, and a partial ``sink``
        from a failed attempt is truncated before the next one."""

        if op == "DELETE":
            # NOT retried: a retry after a transport failure that
            # followed a server-side successful delete reads
            # {"boolean": false} and fabricates a not-found for an op
            # that succeeded — surface the transport ambiguity instead
            return self._request_once(
                method, path, op, body=body, sink=sink, **params)

        def attempt():
            if sink is not None:
                sink.seek(0)
                sink.truncate()
            return self._request_once(
                method, path, op, body=body, sink=sink, **params)

        _seed = os.environ.get("RSTPU_RETRY_SEED")
        return retry_call(
            attempt, policy=_HDFS_RETRY, classify=_transient_hdfs_error,
            op="hdfs.request", budget=_HDFS_RETRY_BUDGET,
            rng=random.Random(int(_seed)) if _seed else None,
        )

    def _request_once(self, method: str, path: str, op: str, body=None,
                      sink=None, **params):
        """Issue one WebHDFS op, following namenode->datanode redirects
        manually. Per spec the data body is only sent to the redirect
        target; a server that handles CREATE directly (HttpFS /
        noredirect namenodes) is detected by a 2xx on the body-less
        first hop, and the op is re-issued WITH the body so the write
        is never silently dropped."""
        host, port = self._host, self._port
        url = self._url(path, op, **params)
        body_sent = body is None
        for _ in range(_MAX_REDIRECTS):
            status, loc, data = self._send(
                host, port, method, url, body if body_sent else None,
                sink=sink)
            if loc is not None and status in (301, 302, 307):
                parsed = urllib.parse.urlsplit(loc)
                host = parsed.hostname or host
                port = parsed.port or port
                url = (parsed.path +
                       (f"?{parsed.query}" if parsed.query else ""))
                if not body_sent:
                    body_sent = True
                    status, _loc, data = self._send(
                        host, port, method, url, body, sink=sink)
                    if status >= 300:
                        raise HdfsError(
                            f"{op} {path}: {status} {data[:200]!r}",
                            status=status)
                    return status, data
                continue
            if status >= 300:
                raise HdfsError(f"{op} {path}: {status} {data[:200]!r}",
                                status=status)
            if not body_sent:
                # no redirect and the body never went out: this server
                # takes the data directly — re-issue with it
                status, _loc, data = self._send(
                    host, port, method, url, body, sink=sink)
                if status >= 300:
                    raise HdfsError(
                        f"{op} {path}: {status} {data[:200]!r}",
                        status=status)
            return status, data
        # distinct non-zero, non-5xx status: a redirect loop is a
        # PERMANENT misconfiguration — status 0 would classify it
        # transient and re-walk the whole loop under backoff
        raise HdfsError(f"{op} {path}: too many redirects", status=310)

    # -- ObjectStore API ---------------------------------------------------

    def put_object_bytes(self, key: str, data: bytes) -> None:
        self._charge(len(data))
        self._request("PUT", self._path(key), "CREATE", body=data,
                      overwrite="true")

    def put_object(self, local_path: str, key: str) -> None:
        # file object body: http.client streams it with a fstat'd
        # Content-Length — no whole-object buffering
        self._charge(os.path.getsize(local_path))
        with open(local_path, "rb") as f:
            self._request("PUT", self._path(key), "CREATE", body=f,
                          overwrite="true")

    def get_object_bytes(self, key: str) -> bytes:
        _status, data = self._request("GET", self._path(key), "OPEN")
        self._charge(len(data))
        return data

    def get_object(self, key: str, local_path: str,
                   direct_io: bool = False) -> None:
        parent = os.path.dirname(local_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{local_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                self._request("GET", self._path(key), "OPEN", sink=f)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.replace(tmp, local_path)

    def list_objects(self, prefix: str) -> List[str]:
        """Every file whose KEY starts with ``prefix`` — the STRING-prefix
        contract Local/S3 implement (a prefix may be a partial filename:
        archive.py enumerates 'dbmeta-<seq>' chains with prefix
        '.../dbmeta'). The walk is rooted at the prefix's parent
        DIRECTORY and filtered by string prefix, so partial-name
        prefixes match exactly like the other backends."""
        prefix = prefix.lstrip("/")
        root = prefix.rstrip("/").rsplit("/", 1)[0] if "/" in prefix else ""
        out: List[str] = []
        pending = [root]
        while pending:
            cur = pending.pop()
            try:
                _s, data = self._request(
                    "GET", self._path(cur), "LISTSTATUS")
            except HdfsError as e:
                if e.status == 404:
                    continue
                raise
            statuses = json.loads(data)["FileStatuses"]["FileStatus"]
            for st in statuses:
                # LISTSTATUS of a FILE returns one entry with empty suffix
                name = st["pathSuffix"]
                child = (f"{cur}/{name}" if cur and name
                         else (name or cur))
                if st["type"] == "DIRECTORY":
                    # descend only where the subtree can still match
                    if child.startswith(prefix) or prefix.startswith(
                            child + "/"):
                        pending.append(child)
                elif child.startswith(prefix):
                    out.append(child)
        return sorted(out)

    def delete_object(self, key: str) -> None:
        _s, data = self._request("DELETE", self._path(key), "DELETE",
                                 recursive="false")
        # WebHDFS answers 200 {"boolean": false} for a missing path; the
        # ObjectStore contract (Local/S3 parity) is that this raises
        try:
            deleted = bool(json.loads(data)["boolean"])
        except (ValueError, KeyError, TypeError):
            deleted = True  # non-JSON success body: trust the 2xx
        if not deleted:
            raise HdfsError(f"DELETE {self._path(key)}: no such object",
                            status=404)

    def copy_object(self, src_key: str, dst_key: str) -> None:
        self.put_object_bytes(dst_key, self.get_object_bytes(src_key))
