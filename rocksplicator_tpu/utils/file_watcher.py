"""File watching: callback with full file content on complete modification.

Reference: common/file_watcher.{h,cpp} (inotify IN_CLOSE_WRITE singleton
watcher, survives delete/recreate, file_watcher.cpp:63-120) and
common/FilePoller.* / MultiFilePoller.* (mtime-polling alternative vendored
from wangle).

TPU-first design: a single polling implementation (mtime + content hash) —
portable, no inotify dependency, identical callback contract: the callback
receives the *full file content* and only fires when content actually
changed. A singleton thread multiplexes all watched files, like the
reference's one-epoll-thread design.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .misc import read_file as _read

log = logging.getLogger(__name__)

Callback = Callable[[bytes], None]


class FileWatcher:
    """Singleton polling file watcher.

    ``add_file(path, cb)`` registers a callback fired with the file's full
    content whenever its content changes (and once immediately if the file
    exists). ``remove_file`` unregisters. Files may not exist yet, may be
    deleted and recreated — the watcher keeps polling.
    """

    _instance: Optional["FileWatcher"] = None
    _instance_lock = threading.Lock()

    def __init__(self, poll_interval_sec: float = 0.1):
        self._poll_interval = poll_interval_sec
        self._lock = threading.Lock()
        # path -> (callbacks, last_content_hash)
        self._files: Dict[str, Tuple[List[Callback], Optional[str]]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def instance(cls) -> "FileWatcher":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    @classmethod
    def reset_for_test(cls) -> None:
        with cls._instance_lock:
            if cls._instance is not None:
                cls._instance.stop()
            cls._instance = None

    def add_file(self, path: str, callback: Callback) -> None:
        path = os.path.abspath(path)
        initial: Optional[bytes] = None
        with self._lock:
            entry = self._files.get(path)
            if entry is None:
                content = _read(path)
                digest = (
                    hashlib.sha1(content).hexdigest() if content is not None else None
                )
                self._files[path] = ([callback], digest)
                initial = content
            else:
                # Already watched: only the new callback gets the current
                # content; the shared digest is left for _poll to advance so
                # existing subscribers still see any pending change.
                cbs, digest = entry
                self._files[path] = (cbs + [callback], digest)
                initial = _read(path)
            self._ensure_thread()
        if initial is not None:
            _safe_call(callback, initial, path)

    def remove_file(self, path: str, callback: Optional[Callback] = None) -> None:
        path = os.path.abspath(path)
        with self._lock:
            entry = self._files.get(path)
            if entry is None:
                return
            cbs, digest = entry
            if callback is None:
                self._files.pop(path, None)
            else:
                # Equality, not identity: bound methods are re-created on
                # every attribute access but compare equal.
                cbs = [c for c in cbs if c != callback]
                if cbs:
                    self._files[path] = (cbs, digest)
                else:
                    self._files.pop(path, None)

    def poll_now(self) -> None:
        """Force one poll cycle synchronously (test hook)."""
        self._poll()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- internals --------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="file-watcher", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._poll_interval):
            try:
                self._poll()
            except Exception:  # pragma: no cover - defensive
                log.exception("file watcher poll failed")

    def _poll(self) -> None:
        with self._lock:
            paths = list(self._files.keys())
        for path in paths:
            content = _read(path)
            if content is None:
                continue
            digest = hashlib.sha1(content).hexdigest()
            fire: List[Callback] = []
            with self._lock:
                entry = self._files.get(path)
                if entry is None:
                    continue
                cbs, old_digest = entry
                if digest != old_digest:
                    self._files[path] = (cbs, digest)
                    fire = list(cbs)
            for cb in fire:
                _safe_call(cb, content, path)


def _safe_call(cb: Callback, content: bytes, path: str) -> None:
    try:
        cb(content)
    except Exception:  # pragma: no cover - defensive
        log.exception("file watcher callback failed for %s", path)


class MultiFilePoller:
    """Multi-file registration facade over the singleton watcher.

    Reference: common/MultiFilePoller.* (vendored from wangle) — one
    callback observing a set of files, invoked with a {path: content} map
    whenever any member changes. A cancellation id unregisters the group.
    """

    def __init__(self, watcher: "FileWatcher" = None):
        self._watcher = watcher or FileWatcher.instance()
        self._groups: Dict[int, List[Tuple[str, Callback]]] = {}
        self._next_id = 0
        self._lock = threading.Lock()

    def add_files(self, paths: List[str], callback) -> int:
        """``callback(contents: Dict[str, bytes])`` fires on any change;
        returns a cancellation id."""
        contents: Dict[str, bytes] = {}
        registrations: List[Tuple[str, Callback]] = []

        def make_cb(path: str) -> Callback:
            def cb(content: bytes) -> None:
                contents[path] = content
                callback(dict(contents))

            return cb

        for path in paths:
            cb = make_cb(os.path.abspath(path))
            registrations.append((os.path.abspath(path), cb))
            self._watcher.add_file(path, cb)
        with self._lock:
            self._next_id += 1
            self._groups[self._next_id] = registrations
            return self._next_id

    def cancel(self, cancellation_id: int) -> None:
        with self._lock:
            group = self._groups.pop(cancellation_id, None)
        for path, cb in group or []:
            self._watcher.remove_file(path, cb)
