"""O_DIRECT file sink for object downloads.

Reference parity: DirectIOWritableFile (/root/reference/common/s3util.h:
82-103) — large SST downloads bypass the page cache so a restore/ingest
storm doesn't evict the serving working set. Semantics reproduced:

- writes buffer into an ALIGNED block (O_DIRECT requires buffer, offset
  and length all aligned); full blocks flush with O_DIRECT pwrites;
- the unaligned tail is written on close through a plain fd (the
  reference's final unaligned chunk takes the same escape hatch);
- filesystems that refuse O_DIRECT (tmpfs, some overlays) degrade to
  buffered IO with a log line rather than failing the download.

Alignment buffer comes from mmap (page-aligned by construction) — no
ctypes posix_memalign needed.
"""

from __future__ import annotations

import logging
import mmap
import os

log = logging.getLogger(__name__)

ALIGN = 4096


class DirectIOFile:
    """Sequential writer; use as a context manager."""

    def __init__(self, path: str, align: int = ALIGN,
                 buffer_blocks: int = 256):
        self._path = path
        self._align = align
        self._cap = align * buffer_blocks
        self._buf = mmap.mmap(-1, self._cap)  # page-aligned anonymous map
        self._fill = 0
        self._offset = 0
        self._direct = True
        try:
            self._fd = os.open(
                path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC | os.O_DIRECT,
                0o644)
        except OSError as e:
            log.info("%s: O_DIRECT unavailable (%s) — buffered fallback",
                     path, e)
            self._direct = False
            self._fd = os.open(
                path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)

    def write(self, data: bytes) -> None:
        view = memoryview(data)
        while len(view):
            take = min(len(view), self._cap - self._fill)
            self._buf[self._fill:self._fill + take] = view[:take]
            self._fill += take
            view = view[take:]
            if self._fill == self._cap:
                self._flush_aligned(self._cap)

    def _flush_aligned(self, nbytes: int) -> None:
        """Write the first ``nbytes`` (aligned) of the buffer, shift the
        remainder down. The memoryview matters: slicing the mmap directly
        would copy to an UNALIGNED heap buffer and O_DIRECT pwrites would
        fail with EINVAL."""
        view = memoryview(self._buf)[:nbytes]
        try:
            done = 0
            while done < nbytes:
                try:
                    n = os.pwrite(self._fd, view[done:],
                                  self._offset + done)
                except OSError as e:
                    if not self._direct:
                        raise
                    # filesystem accepted O_DIRECT at open but rejects
                    # the write (alignment/fs quirks) — degrade to
                    # buffered and retry; a second failure propagates
                    log.info(
                        "%s: O_DIRECT write failed (%s) — buffered "
                        "fallback", self._path, e)
                    os.close(self._fd)
                    self._direct = False
                    self._fd = os.open(self._path, os.O_WRONLY, 0o644)
                    continue
                if n <= 0:
                    # advancing by nbytes anyway would publish a holey
                    # file that os.replace then marks complete
                    raise OSError(
                        f"short pwrite ({n} of {nbytes - done} bytes)")
                done += n
        finally:
            view.release()
        self._offset += nbytes
        rest = self._fill - nbytes
        if rest:
            self._buf[:rest] = self._buf[nbytes:self._fill]
        self._fill = rest

    def close(self) -> None:
        if self._fd < 0:
            return
        full = (self._fill // self._align) * self._align
        if full:
            self._flush_aligned(full)
        tail = bytes(self._buf[:self._fill])
        os.close(self._fd)
        self._fd = -1
        if tail:
            # the final unaligned chunk goes through a buffered fd
            with open(self._path, "r+b") as f:
                f.seek(self._offset)
                f.write(tail)
        self._buf.close()

    def __enter__(self) -> "DirectIOFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
