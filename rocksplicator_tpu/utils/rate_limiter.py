"""Token-bucket rate limiters.

Reference: common/concurrent_rate_limiter.h (lock-free token bucket via
atomic State CAS) and common/aws_s3_rate_limiter.h (adapter implementing the
AWS SDK ``RateLimiterInterface``). Python's GIL stands in for the CAS loop;
the API (``try_get`` non-blocking, ``apply_cost`` blocking) matches both.
"""

from __future__ import annotations

import threading
import time


class ConcurrentRateLimiter:
    """Token bucket: ``rate`` tokens/sec, burst up to ``burst`` tokens."""

    def __init__(self, rate: float, burst: float | None = None):
        if rate <= 0:
            raise ValueError("rate must be positive")
        self._rate = rate
        self._burst = burst if burst is not None else rate
        self._tokens = self._burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        # _tokens may be negative (debt from an oversized apply_cost); refill
        # pays the debt first, then accumulates up to the burst cap.
        elapsed = now - self._last
        if elapsed > 0:
            self._tokens = min(self._burst, self._tokens + elapsed * self._rate)
            self._last = now

    def try_get(self, tokens: float = 1.0) -> bool:
        """Non-blocking acquire; True iff tokens were available."""
        with self._lock:
            now = time.monotonic()
            self._refill(now)
            if self._tokens >= tokens:
                self._tokens -= tokens
                return True
            return False

    def apply_cost(self, tokens: float = 1.0) -> float:
        """Blocking acquire (AWS RateLimiterInterface::ApplyCost semantics):
        charge the bucket — going into token debt if ``tokens`` exceeds the
        burst capacity — then sleep off any deficit. Returns seconds slept."""
        with self._lock:
            self._refill(time.monotonic())
            self._tokens -= tokens
            deficit = -self._tokens
        if deficit > 0:
            sleep_time = deficit / self._rate
            time.sleep(sleep_time)
            return sleep_time
        return 0.0

    @property
    def rate(self) -> float:
        return self._rate

    def set_rate(self, rate: float) -> None:
        if rate <= 0:
            raise ValueError("rate must be positive")
        with self._lock:
            self._refill(time.monotonic())
            self._rate = rate
