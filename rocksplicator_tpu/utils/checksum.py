"""Polynomial block checksum — host (numpy) side.

The device implementation lives in ops/block_encode.py; this module is
jax-free so the storage layer can verify device-written blocks without
touching the accelerator stack. H = Σ (b_i + 1) · r^(i+1) mod 2^32 over
the zero-padded canonical block length (r = odd FNV prime): order- and
position-sensitive, fully vectorizable on both sides.
"""

from __future__ import annotations

import numpy as np

CHK_R = np.uint32(0x01000193)

_powers_cache: dict = {}


def _powers(length: int) -> np.ndarray:
    """r^1..r^length (wrapping u32), cached per length — verification
    runs on every block read, and the vector depends only on length."""
    arr = _powers_cache.get(length)
    if arr is None:
        with np.errstate(over="ignore"):
            arr = np.cumprod(np.full(length, CHK_R, np.uint32),
                             dtype=np.uint32)
        if len(_powers_cache) > 64:  # block sizes are few; bound anyway
            _powers_cache.clear()
        _powers_cache[length] = arr
    return arr


def poly_checksum(data: bytes, length: int | None = None) -> int:
    """Checksum of ``data`` zero-padded to ``length`` bytes (a short tail
    block verifies against the same padded value the device computed)."""
    buf = np.frombuffer(data, dtype=np.uint8)
    if length is not None and len(buf) < length:
        buf = np.pad(buf, (0, length - len(buf)))
    with np.errstate(over="ignore"):
        return int(
            ((buf.astype(np.uint32) + np.uint32(1))
             * _powers(len(buf))).sum(dtype=np.uint32)
        )


def poly_checksum_words(words: np.ndarray, length: int | None = None) -> int:
    """Word-domain variant for PLANAR blocks: H = Σ (w_i + 1) · r^(i+1)
    mod 2^32 over u32 plane words zero-padded to ``length`` words. The
    device computes the identical value over its plane matrix
    (ops/block_encode.py planar_checksums_tpu)."""
    buf = np.asarray(words, dtype=np.uint32).ravel()
    if length is not None and len(buf) < length:
        buf = np.pad(buf, (0, length - len(buf)))
    with np.errstate(over="ignore"):
        return int(
            ((buf + np.uint32(1)) * _powers(len(buf))).sum(dtype=np.uint32)
        )
