"""FastReadMap: read-optimized copy-on-write hash map.

Reference: rocksdb_replicator/fast_read_map.h:36-140 — RWSpinLock + shared_ptr
swap so readers never block writers and reads are wait-free. In Python the
same effect comes from swapping an immutable dict reference (attribute reads
are atomic under the GIL); writers copy-on-write under a mutex. Readers also
get consistent snapshot iteration, which the reference exposes via ``for_each``.
"""

from __future__ import annotations

import threading
from typing import Dict, Generic, Hashable, Iterator, Optional, Tuple, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class FastReadMap(Generic[K, V]):
    def __init__(self) -> None:
        self._map: Dict[K, V] = {}
        self._write_lock = threading.Lock()

    def get(self, key: K) -> Optional[V]:
        return self._map.get(key)

    def add(self, key: K, value: V) -> bool:
        """Add; False if the key already exists (reference semantics)."""
        with self._write_lock:
            if key in self._map:
                return False
            new = dict(self._map)
            new[key] = value
            self._map = new
            return True

    def remove(self, key: K) -> bool:
        with self._write_lock:
            if key not in self._map:
                return False
            new = dict(self._map)
            del new[key]
            self._map = new
            return True

    def clear(self) -> None:
        with self._write_lock:
            self._map = {}

    def snapshot(self) -> Dict[K, V]:
        """Wait-free consistent snapshot (the swapped dict itself)."""
        return self._map

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: K) -> bool:
        return key in self._map

    def items(self) -> Iterator[Tuple[K, V]]:
        return iter(self._map.items())
