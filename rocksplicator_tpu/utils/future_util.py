"""Async combinators: delayed futures and speculative (hedged) requests.

Reference: common/future_util.{h,cpp} — ``GenerateDelayedFuture`` and a
speculative/backup-request future combinator used for hedged reads at the
router layer. Here expressed over asyncio.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, TypeVar

T = TypeVar("T")


async def delayed(value: T, delay_sec: float) -> T:
    """GenerateDelayedFuture equivalent."""
    await asyncio.sleep(delay_sec)
    return value


async def speculate(
    primary: Callable[[], Awaitable[T]],
    backup: Callable[[], Awaitable[T]],
    backup_delay_sec: float,
) -> T:
    """Hedged request: start ``primary``; if it hasn't completed within
    ``backup_delay_sec``, also start ``backup``; return the first success.
    Fails only if both fail (reference future_util speculative combinator).
    """
    primary_task = asyncio.ensure_future(primary())
    try:
        return await asyncio.wait_for(asyncio.shield(primary_task), backup_delay_sec)
    except asyncio.TimeoutError:
        pass
    except Exception:
        # Primary failed fast — fall through to the backup alone.
        return await backup()

    backup_task = asyncio.ensure_future(backup())
    tasks = {primary_task, backup_task}
    result: T
    last_exc: BaseException | None = None
    while tasks:
        done, tasks = await asyncio.wait(tasks, return_when=asyncio.FIRST_COMPLETED)
        for task in done:
            exc = task.exception()
            if exc is None:
                for t in tasks:
                    t.cancel()
                # rstpu-check: allow(loop-blocking) asyncio.Task.result() on a task from asyncio.wait's done set — already completed, returns immediately
                return task.result()
            last_exc = exc
    assert last_exc is not None
    raise last_exc
