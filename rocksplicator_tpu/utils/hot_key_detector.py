"""Hot-key detection via SpaceSaving-style K-bucket counting with time decay.

Reference: common/hot_key_detector.{h,cpp}:64-204 — tracks the top-K keys by
access count; counts decay over time so stale hot keys cool off. Reference
benchmark: record(int) ≈55ns (hot_key_detector.h:52-62); the Python version
trades that for simplicity (the C++ native engine owns the true hot path).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Hashable, List, Tuple


class HotKeyDetector:
    """Track the hottest keys among a stream of accesses.

    ``record(key)`` counts an access; ``is_above(key, threshold)`` reports
    whether the key's decayed rate share exceeds ``threshold`` (0..1);
    ``top(n)`` returns the hottest keys.
    """

    def __init__(self, num_buckets: int = 100, decay_half_life_sec: float = 60.0):
        self._k = num_buckets
        self._half_life = decay_half_life_sec
        self._lock = threading.Lock()
        self._counts: Dict[Hashable, float] = {}
        self._total = 0.0
        self._last_decay = time.monotonic()

    def _decay(self, now: float) -> None:
        elapsed = now - self._last_decay
        if elapsed < 1.0:
            return
        factor = 0.5 ** (elapsed / self._half_life)
        self._last_decay = now
        self._total *= factor
        for k in list(self._counts):
            v = self._counts[k] * factor
            if v < 0.5:
                del self._counts[k]
            else:
                self._counts[k] = v

    def record(self, key: Hashable, count: float = 1.0) -> None:
        with self._lock:
            now = time.monotonic()
            self._decay(now)
            self._total += count
            if key in self._counts:
                self._counts[key] += count
            elif len(self._counts) < self._k:
                self._counts[key] = count
            else:
                # SpaceSaving: evict the minimum, inherit its count.
                min_key = min(self._counts, key=self._counts.__getitem__)
                min_count = self._counts.pop(min_key)
                self._counts[key] = min_count + count

    def is_above(self, key: Hashable, threshold: float) -> bool:
        with self._lock:
            self._decay(time.monotonic())
            if self._total <= 0:
                return False
            return self._counts.get(key, 0.0) / self._total > threshold

    def top(self, n: int = 10) -> List[Tuple[Hashable, float]]:
        with self._lock:
            self._decay(time.monotonic())
            items = sorted(self._counts.items(), key=lambda kv: -kv[1])
            return items[:n]

    def total(self) -> float:
        """Decayed total access count (the denominator of is_above)."""
        with self._lock:
            self._decay(time.monotonic())
            return self._total
