"""RAII latency timers and slow-request sampled logging.

Reference: common/timer.h (RAII latency metric) and common/slow_log_timer.h:20-45
(slow-request sampling logger).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Optional

from .stats import Stats

log = logging.getLogger(__name__)


class Timer:
    """Context manager that records elapsed milliseconds as a metric."""

    def __init__(self, metric_name: str, stats: Optional[Stats] = None):
        self._metric = metric_name
        self._stats = stats or Stats.get()
        self._start = 0.0
        self.elapsed_ms = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self.elapsed_ms = (time.monotonic() - self._start) * 1000.0
        self._stats.add_metric(self._metric, self.elapsed_ms)
        return False


class SlowLogTimer(Timer):
    """Timer that additionally logs a sampled message when elapsed time
    exceeds ``threshold_ms`` (reference slow_log_timer.h:20-45)."""

    def __init__(
        self,
        metric_name: str,
        threshold_ms: float = 100.0,
        sample_rate: float = 0.1,
        context: str = "",
        stats: Optional[Stats] = None,
    ):
        super().__init__(metric_name, stats)
        self._threshold_ms = threshold_ms
        self._sample_rate = sample_rate
        self._context = context

    def __exit__(self, *exc) -> bool:
        super().__exit__(*exc)
        if self.elapsed_ms > self._threshold_ms and random.random() < self._sample_rate:
            log.warning(
                "slow request: %s took %.1fms (threshold %.1fms) %s",
                self._metric,
                self.elapsed_ms,
                self._threshold_ms,
                self._context,
            )
        return False
