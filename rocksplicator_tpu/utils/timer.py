"""RAII latency timers and slow-request sampled logging.

Reference: common/timer.h (RAII latency metric) and common/slow_log_timer.h:20-45
(slow-request sampling logger).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Optional

from .retry_policy import seeded_rng
from .stats import Stats

log = logging.getLogger(__name__)

# Slow-log sampling draws ride the seeded_rng()/RSTPU_RETRY_SEED
# contract (utils/retry_policy.py — the ONE home of the seed-pinning
# rule) instead of the global `random`: with the seed pinned, a chaos
# schedule or slow-log test sees a deterministic sample sequence.
# Created lazily so an env seed set at process start (how chaos arms
# children) is honored regardless of import order.
_slow_log_rng: Optional[random.Random] = None


def _slow_log_draw() -> float:
    global _slow_log_rng
    if _slow_log_rng is None:
        _slow_log_rng = seeded_rng()
    return _slow_log_rng.random()


def reset_slow_log_rng_for_test() -> None:
    """Re-derive the sampling RNG from the environment (tests pin
    RSTPU_RETRY_SEED and need the stream to restart)."""
    global _slow_log_rng
    _slow_log_rng = None


class Timer:
    """Context manager that records elapsed milliseconds as a metric."""

    def __init__(self, metric_name: str, stats: Optional[Stats] = None):
        self._metric = metric_name
        self._stats = stats or Stats.get()
        self._start = 0.0
        self.elapsed_ms = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self.elapsed_ms = (time.monotonic() - self._start) * 1000.0
        self._stats.add_metric(self._metric, self.elapsed_ms)
        return False


class SlowLogTimer(Timer):
    """Timer that additionally logs a sampled message when elapsed time
    exceeds ``threshold_ms`` (reference slow_log_timer.h:20-45)."""

    def __init__(
        self,
        metric_name: str,
        threshold_ms: float = 100.0,
        sample_rate: float = 0.1,
        context: str = "",
        stats: Optional[Stats] = None,
    ):
        super().__init__(metric_name, stats)
        self._threshold_ms = threshold_ms
        self._sample_rate = sample_rate
        self._context = context

    def __exit__(self, *exc) -> bool:
        super().__exit__(*exc)
        if self.elapsed_ms > self._threshold_ms \
                and _slow_log_draw() < self._sample_rate:
            log.warning(
                "slow request: %s took %.1fms (threshold %.1fms) %s",
                self._metric,
                self.elapsed_ms,
                self._threshold_ms,
                self._context,
            )
        return False
