"""Real S3 backend: AWS Signature V4 + REST, stdlib only.

Reference: common/s3util.{h,cpp} wraps the AWS C++ SDK (get/put/listV2/
delete/copy + batch download s3util.cpp:385-416). No AWS SDK is baked into
this image, so this module implements the actual S3 wire protocol —
SigV4 request signing (hmac/hashlib), the REST verbs over http.client,
and ListObjectsV2 XML — making ``S3ObjectStore`` a working production
backend against AWS or any S3-compatible endpoint (minio, the in-process
``s3_stub`` test server), not a boto3 shim.

Credentials come from the standard env (AWS_ACCESS_KEY_ID /
AWS_SECRET_ACCESS_KEY / AWS_SESSION_TOKEN, region from AWS_REGION or
AWS_DEFAULT_REGION) or explicit constructor args. A custom endpoint
(``http://host:port``) switches to path-style addressing, matching minio
convention.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import os
import random
import socket
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ..testing import failpoints as fp
from .retry_policy import RetryBudget, RetryPolicy, backoff_step

_ALGORITHM = "AWS4-HMAC-SHA256"

# One retry budget per process, shared by every S3Client: a hard-down
# endpoint degrades to fail-fast instead of every caller independently
# multiplying load (utils/retry_policy.py).
_S3_RETRY_BUDGET = RetryBudget(capacity=20.0, refill_per_sec=2.0)
_UNRESERVED = frozenset(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-._~"
)


class S3Error(Exception):
    def __init__(self, message: str, status: int = 0, code: str = ""):
        super().__init__(message)
        self.status = status
        self.code = code


def _tmp_name(dst: str) -> str:
    """Unique per writer so concurrent downloads of one target can't
    interleave; the final os.replace() wins atomically."""
    import threading

    return f"{dst}.{os.getpid()}.{threading.get_ident()}.tmp"


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    """AWS canonical URI encoding (NOT urllib.quote: AWS requires
    uppercase percent escapes and '~' unreserved)."""
    out = []
    for ch in s.encode("utf-8"):
        c = chr(ch)
        if c in _UNRESERVED or (c == "/" and not encode_slash):
            out.append(c)
        else:
            out.append("%%%02X" % ch)
    return "".join(out)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def signing_key(secret_key: str, date: str, region: str, service: str) -> bytes:
    """SigV4 key derivation chain (date is YYYYMMDD)."""
    k = _hmac(("AWS4" + secret_key).encode("utf-8"), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_request(
    method: str,
    canonical_uri: str,
    query: Iterable[Tuple[str, str]],
    headers: Dict[str, str],
    signed_headers: List[str],
    payload_sha256: str,
) -> str:
    cq = "&".join(
        f"{_uri_encode(k)}={_uri_encode(v)}"
        for k, v in sorted(query)
    )
    ch = "".join(
        f"{h}:{' '.join(headers[h].split())}\n" for h in signed_headers
    )
    return "\n".join([
        method, canonical_uri, cq, ch, ";".join(signed_headers),
        payload_sha256,
    ])


def string_to_sign(amz_date: str, scope: str, creq: str) -> str:
    return "\n".join([
        _ALGORITHM, amz_date, scope,
        hashlib.sha256(creq.encode("utf-8")).hexdigest(),
    ])


def sign_request(
    method: str,
    canonical_uri: str,
    query: Iterable[Tuple[str, str]],
    headers: Dict[str, str],
    payload_sha256: str,
    access_key: str,
    secret_key: str,
    region: str,
    amz_date: str,
    service: str = "s3",
) -> str:
    """Returns the Authorization header value. ``headers`` must already
    contain every header to be signed (host, x-amz-date,
    x-amz-content-sha256, ...); all lowercase-keyed headers are signed."""
    signed = sorted(h.lower() for h in headers)
    lower = {h.lower(): v for h, v in headers.items()}
    creq = canonical_request(
        method, canonical_uri, query, lower, signed, payload_sha256)
    date = amz_date[:8]
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = string_to_sign(amz_date, scope, creq)
    sig = hmac.new(
        signing_key(secret_key, date, region, service),
        sts.encode("utf-8"), hashlib.sha256,
    ).hexdigest()
    return (
        f"{_ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}"
    )


@dataclass
class S3Config:
    region: str = field(
        default_factory=lambda: os.environ.get(
            "AWS_REGION", os.environ.get("AWS_DEFAULT_REGION", "us-east-1"))
    )
    access_key: str = field(
        default_factory=lambda: os.environ.get("AWS_ACCESS_KEY_ID", ""))
    secret_key: str = field(
        default_factory=lambda: os.environ.get("AWS_SECRET_ACCESS_KEY", ""))
    session_token: Optional[str] = field(
        default_factory=lambda: os.environ.get("AWS_SESSION_TOKEN"))
    # http(s)://host[:port] — None = AWS virtual-hosted style
    endpoint: Optional[str] = field(
        default_factory=lambda: os.environ.get("RSTPU_S3_ENDPOINT"))
    connect_timeout: float = 10.0
    read_timeout: float = 120.0
    max_retries: int = 3


class S3Client:
    """Low-level S3 REST client for one bucket.

    Verbs mirror s3util.h: getObject(+ToFile), putObject, listObjects(V2
    w/ continuation), deleteObject, copyObject. Transient failures (5xx,
    connection resets) retry with exponential backoff like the SDK's
    default retry strategy.
    """

    def __init__(self, bucket: str, config: Optional[S3Config] = None):
        self.bucket = bucket
        self.cfg = config or S3Config()
        # exp backoff + full jitter (was an inline 2**n*0.1 sleep);
        # RSTPU_RETRY_SEED pins the jitter for reproducible chaos runs
        self._retry = RetryPolicy(
            max_attempts=self.cfg.max_retries + 1,
            base_delay=0.1, max_delay=5.0)
        _seed = os.environ.get("RSTPU_RETRY_SEED")
        self._retry_rng = random.Random(int(_seed) if _seed else None)
        if not self.cfg.access_key or not self.cfg.secret_key:
            raise S3Error(
                "missing AWS credentials (AWS_ACCESS_KEY_ID / "
                "AWS_SECRET_ACCESS_KEY)"
            )
        if self.cfg.endpoint:
            u = urllib.parse.urlparse(self.cfg.endpoint)
            self._secure = u.scheme == "https"
            self._host = u.hostname or "127.0.0.1"
            self._port = u.port or (443 if self._secure else 80)
            self._path_style = True
            host_hdr = (
                self._host if self._port in (80, 443)
                else f"{self._host}:{self._port}"
            )
            self._host_header = host_hdr
        else:
            self._secure = True
            self._host = f"{bucket}.s3.{self.cfg.region}.amazonaws.com"
            self._port = 443
            self._path_style = False
            self._host_header = self._host

    # -- plumbing ----------------------------------------------------------

    def _canonical_uri(self, key: str) -> str:
        path = f"/{self.bucket}/{key}" if self._path_style else f"/{key}"
        return _uri_encode(path, encode_slash=False)

    def _request(
        self,
        method: str,
        key: str = "",
        query: Optional[List[Tuple[str, str]]] = None,
        body: bytes = b"",
        extra_headers: Optional[Dict[str, str]] = None,
        body_path: Optional[str] = None,
        sink_path: Optional[str] = None,
        sink_direct: bool = False,
    ) -> Tuple[int, Dict[str, str], bytes]:
        """One signed request with retries. ``body_path`` streams a file
        up (payload hashed incrementally first — SigV4 signs the hash, so
        one extra read pass replaces holding the file in RAM);
        ``sink_path`` streams a 200 response to a file in chunks and
        returns b"" as data (error bodies still return in full)."""
        query = query or []
        uri = self._canonical_uri(key)
        if body_path is not None:
            h = hashlib.sha256()
            body_len = 0
            with open(body_path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
                    body_len += len(chunk)
            payload_hash = h.hexdigest()
        else:
            payload_hash = hashlib.sha256(body).hexdigest()
            body_len = len(body)
        attempt = 0
        while True:
            now = datetime.datetime.now(datetime.timezone.utc)
            amz_date = now.strftime("%Y%m%dT%H%M%SZ")
            headers = {
                "host": self._host_header,
                "x-amz-date": amz_date,
                "x-amz-content-sha256": payload_hash,
            }
            if self.cfg.session_token:
                headers["x-amz-security-token"] = self.cfg.session_token
            if extra_headers:
                headers.update(
                    {k.lower(): v for k, v in extra_headers.items()})
            auth = sign_request(
                method, uri, query, headers, payload_hash,
                self.cfg.access_key, self.cfg.secret_key, self.cfg.region,
                amz_date,
            )
            send_headers = dict(headers)
            send_headers["Authorization"] = auth
            if body_len or method in ("PUT", "POST"):
                send_headers["content-length"] = str(body_len)
            # the wire query string must be byte-identical to the signed
            # canonical query string (same ordering and escaping)
            qs = "&".join(
                f"{_uri_encode(k)}={_uri_encode(v)}"
                for k, v in sorted(query)
            )
            target = uri + ("?" + qs if qs else "")
            try:
                fp.hit("s3.request")  # OSError-shaped: retried below
                conn_cls = (
                    http.client.HTTPSConnection if self._secure
                    else http.client.HTTPConnection
                )
                conn = conn_cls(
                    self._host, self._port, timeout=self.cfg.read_timeout)
                try:
                    send_body = body or None
                    if body_path is not None:
                        send_body = open(body_path, "rb")
                    try:
                        conn.request(method, target, body=send_body,
                                     headers=send_headers)
                    finally:
                        if body_path is not None:
                            send_body.close()
                    resp = conn.getresponse()
                    status = resp.status
                    rheaders = {k.lower(): v for k, v in resp.getheaders()}
                    if sink_path is not None and status == 200:
                        tmp = _tmp_name(sink_path)
                        if sink_direct:
                            # page-cache-bypassing sink (reference
                            # DirectIOWritableFile, s3util.h:82-103)
                            from .directio import DirectIOFile

                            sink_cm = DirectIOFile(tmp)
                        else:
                            sink_cm = open(tmp, "wb")
                        with sink_cm as out:
                            for chunk in iter(
                                    lambda: resp.read(1 << 20), b""):
                                out.write(chunk)
                        os.replace(tmp, sink_path)
                        data = b""
                    else:
                        data = resp.read()
                finally:
                    conn.close()
            except (OSError, socket.timeout, http.client.HTTPException) as e:
                if not self._retry_sleep(attempt):
                    raise S3Error(f"S3 request failed: {e!r}") from e
                attempt += 1
                continue
            if status >= 500 and self._retry_sleep(attempt):
                attempt += 1
                continue
            return status, rheaders, data

    def _retry_sleep(self, attempt: int) -> bool:
        """One backoff step under the unified policy; False when the
        attempt or the process-wide retry budget is exhausted."""
        return backoff_step(
            self._retry, attempt, op="s3.request",
            budget=_S3_RETRY_BUDGET, rng=self._retry_rng)

    @staticmethod
    def _error(status: int, data: bytes, what: str) -> S3Error:
        code, msg = "", ""
        try:
            root = ET.fromstring(data.decode("utf-8"))
            code = (root.findtext("Code") or "").strip()
            msg = (root.findtext("Message") or "").strip()
        except Exception:
            pass
        return S3Error(
            f"{what}: HTTP {status} {code} {msg}".strip(), status, code)

    # -- verbs (s3util.h API surface) --------------------------------------

    def get_object(self, key: str) -> bytes:
        status, _h, data = self._request("GET", key)
        if status != 200:
            raise self._error(status, data, f"getObject {key}")
        return data

    def get_object_to_file(self, key: str, local_path: str,
                           direct_io: bool = False) -> int:
        """Streams the object to ``local_path`` (1 MiB chunks, atomic
        replace; ``direct_io`` bypasses the page cache via O_DIRECT —
        s3util.h:82-103). Returns the byte count."""
        status, headers, data = self._request(
            "GET", key, sink_path=local_path, sink_direct=direct_io)
        if status != 200:
            raise self._error(status, data, f"getObject {key}")
        try:
            return os.path.getsize(local_path)
        except OSError:
            return int(headers.get("content-length", "0") or "0")

    def put_object(self, key: str, data: bytes) -> None:
        status, _h, body = self._request("PUT", key, body=data)
        if status not in (200, 201):
            raise self._error(status, body, f"putObject {key}")

    def put_object_from_file(self, key: str, local_path: str) -> int:
        """Streams a file up without buffering it in RAM (one hashing
        pass for the signed payload sha256, then a streamed send).
        Returns the byte count."""
        status, _h, body = self._request("PUT", key, body_path=local_path)
        if status not in (200, 201):
            raise self._error(status, body, f"putObject {key}")
        return os.path.getsize(local_path)

    def delete_object(self, key: str) -> None:
        status, _h, body = self._request("DELETE", key)
        if status not in (200, 204):
            raise self._error(status, body, f"deleteObject {key}")

    def head_object(self, key: str) -> bool:
        """True/False for 200/404; any other status raises (a 403 is a
        permission problem, not object absence)."""
        status, _h, data = self._request("HEAD", key)
        if status == 200:
            return True
        if status == 404:
            return False
        raise self._error(status, data, f"headObject {key}")

    def copy_object(self, src_key: str, dst_key: str) -> None:
        src = _uri_encode(f"/{self.bucket}/{src_key}", encode_slash=False)
        status, _h, body = self._request(
            "PUT", dst_key, extra_headers={"x-amz-copy-source": src})
        if status != 200:
            raise self._error(status, body, f"copyObject {src_key}")
        # S3 reports some copy failures inside a 200 body
        if b"<Error>" in body:
            raise self._error(200, body, f"copyObject {src_key}")

    def list_objects(self, prefix: str) -> List[str]:
        """Full ListObjectsV2 with continuation (s3util listAllObjects)."""
        keys: List[str] = []
        token: Optional[str] = None
        while True:
            query: List[Tuple[str, str]] = [
                ("list-type", "2"), ("prefix", prefix),
            ]
            if token:
                query.append(("continuation-token", token))
            status, _h, data = self._request("GET", "", query=query)
            if status != 200:
                raise self._error(status, data, f"listObjects {prefix}")
            root = ET.fromstring(data.decode("utf-8"))
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for contents in root.iter(f"{ns}Contents"):
                k = contents.findtext(f"{ns}Key")
                if k is not None:
                    keys.append(k)
            truncated = (root.findtext(f"{ns}IsTruncated") or "").lower()
            token = root.findtext(f"{ns}NextContinuationToken")
            if truncated != "true" or not token:
                return keys
