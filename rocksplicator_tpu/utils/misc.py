"""Small host/environment helpers.

Reference: common/availability_zone.{h,cpp} (AZ from EC2 metadata),
common/network_util (local eth0 IP), common/timeutil, common/file_util,
common/deploy_info. TPU-first: AZ comes from env/config (no EC2 metadata
endpoint), and the host identity helpers are zero-egress.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Optional


def availability_zone(default: str = "us-east-1a") -> str:
    """AZ of this host. Env override RSTPU_AZ; else default (the reference
    queries EC2 instance metadata — not applicable on TPU VMs)."""
    return os.environ.get("RSTPU_AZ", default)


def placement_group(default: str = "pg0") -> str:
    return os.environ.get("RSTPU_PG", default)


def local_ip() -> str:
    """Best-effort local routable IP (reference common/network_util)."""
    env = os.environ.get("RSTPU_LOCAL_IP")
    if env:
        return env
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # No packets are sent for a UDP connect; this just picks the
        # interface the kernel would route through.
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def now_ms() -> int:
    return int(time.time() * 1000)


def now_us() -> int:
    return int(time.time() * 1_000_000)


def read_file(path: str) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def write_file_atomic(path: str, data: bytes) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the directory so the rename itself survives power loss
    # (file fsync alone does not make the new directory entry durable)
    try:
        dfd = os.open(parent, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # some filesystems disallow dir fsync; best effort


def build_revision() -> str:
    """Deploy info (reference common/deploy_info)."""
    return os.environ.get("RSTPU_BUILD_REVISION", "dev")
