"""gflags-style flag registry.

The reference defines ~80 ``DEFINE_*`` gflags across the tree (e.g.
rocksdb_replicator/replicated_db.cpp:36-90 defines 13 replication knobs) and
exports them read-only via the status server's ``/gflags.txt``
(common/stats/status_server.cpp). This module provides the same three
capabilities: define-with-default, process-wide override (CLI / env / test),
and text export.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional


class _Flag:
    __slots__ = ("name", "default", "value", "help", "type")

    def __init__(self, name: str, default: Any, help: str):
        self.name = name
        self.default = default
        self.value = default
        self.help = help
        self.type = type(default)


class FlagRegistry:
    """Process-wide flag registry. Thread-safe."""

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, help: str = "") -> None:
        with self._lock:
            if name in self._flags:
                # Re-definition with an identical default is a no-op so that
                # modules can be safely re-imported (e.g. under pytest).
                return
            flag = _Flag(name, default, help)
            # Environment override: RSTPU_FLAG_<NAME>.
            env = os.environ.get("RSTPU_FLAG_" + name.upper())
            if env is not None:
                flag.value = _coerce(env, flag.type)
            self._flags[name] = flag

    def get(self, name: str) -> Any:
        return self._flags[name].value

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            flag = self._flags[name]
            flag.value = _coerce(value, flag.type)

    def reset(self, name: Optional[str] = None) -> None:
        with self._lock:
            names = [name] if name else list(self._flags)
            for n in names:
                self._flags[n].value = self._flags[n].default

    def override(self, **kv: Any) -> "_FlagOverride":
        """Scoped override for tests: ``with FLAGS.override(x=1): ...``"""
        return _FlagOverride(self, kv)

    def dump_text(self) -> str:
        """Export in the /gflags.txt style: --name=value per line."""
        with self._lock:
            lines = [
                f"--{f.name}={f.value}"
                for f in sorted(self._flags.values(), key=lambda f: f.name)
            ]
        return "\n".join(lines) + "\n"

    def parse_args(self, argv: list) -> list:
        """Consume --name=value args; returns the remainder."""
        rest = []
        for arg in argv:
            if arg.startswith("--") and "=" in arg:
                name, _, val = arg[2:].partition("=")
                if name in self._flags:
                    self.set(name, val)
                    continue
            rest.append(arg)
        return rest

    def __getattr__(self, name: str) -> Any:
        try:
            return self._flags[name].value
        except KeyError:
            raise AttributeError(f"undefined flag: {name}") from None


class _FlagOverride:
    def __init__(self, registry: FlagRegistry, kv: Dict[str, Any]):
        self._registry = registry
        self._kv = kv
        self._saved: Dict[str, Any] = {}

    def __enter__(self):
        try:
            for k, v in self._kv.items():
                self._saved[k] = self._registry.get(k)
                self._registry.set(k, v)
        except Exception:
            # Roll back overrides already applied: __exit__ won't run when
            # __enter__ raises.
            for k, v in self._saved.items():
                self._registry.set(k, v)
            raise
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            self._registry.set(k, v)
        return False


def _coerce(value: Any, typ: type) -> Any:
    # A bool value only passes through unchanged for bool flags; for e.g.
    # int flags it falls through to typ(value) so the flag holds 1, not True.
    if isinstance(value, typ) and not (typ is not bool and isinstance(value, bool)):
        return value
    if typ is bool:
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    return typ(value)


FLAGS = FlagRegistry()
define_flag = FLAGS.define
