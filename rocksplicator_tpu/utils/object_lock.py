"""Per-object (per-db-name) lock registry.

Reference: common/object_lock.h:42-209 — striped per-object mutexes used to
serialize admin operations per db name. The reference uses bucketed intrusive
lists with a node pool; here a refcounted dict of locks gives the same
semantics (an object's lock exists only while held or waited on).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Hashable, Iterator, Tuple


class ObjectLock:
    def __init__(self) -> None:
        self._guard = threading.Lock()
        self._locks: Dict[Hashable, Tuple[threading.RLock, int]] = {}

    def lock(self, key: Hashable) -> None:
        with self._guard:
            entry = self._locks.get(key)
            if entry is None:
                lk = threading.RLock()
                self._locks[key] = (lk, 1)
            else:
                lk, refs = entry
                self._locks[key] = (lk, refs + 1)
        lk.acquire()

    def unlock(self, key: Hashable) -> None:
        with self._guard:
            lk, refs = self._locks[key]
            if refs == 1:
                del self._locks[key]
            else:
                self._locks[key] = (lk, refs - 1)
        lk.release()

    def try_lock(self, key: Hashable) -> bool:
        with self._guard:
            entry = self._locks.get(key)
            if entry is None:
                lk = threading.RLock()
                self._locks[key] = (lk, 1)
            else:
                lk, refs = entry
                self._locks[key] = (lk, refs + 1)
        ok = lk.acquire(blocking=False)
        if not ok:
            with self._guard:
                lk2, refs = self._locks[key]
                if refs == 1:
                    del self._locks[key]
                else:
                    self._locks[key] = (lk2, refs - 1)
        return ok

    @contextmanager
    def locked(self, key: Hashable) -> Iterator[None]:
        self.lock(key)
        try:
            yield
        finally:
            self.unlock(key)

    def num_live_locks(self) -> int:
        with self._guard:
            return len(self._locks)
