"""Graceful shutdown: signal → stop servers with pre/post hooks.

Reference: common/graceful_shutdown_handler.{h,cpp} — folly AsyncSignalHandler
that stops the thrift server, with registered pre- and post-stop hooks.
"""

from __future__ import annotations

import inspect
import logging
import signal
import threading
from typing import Callable, List

log = logging.getLogger(__name__)


class GracefulShutdownHandler:
    """Registers SIGTERM/SIGINT handlers that run pre-hooks, stop the given
    servers (anything with a ``stop()``), run post-hooks, then set an event
    the main thread can wait on."""

    def __init__(self, drain_timeout: float = 10.0) -> None:
        self._drain_timeout = drain_timeout
        self._pre_hooks: List[Callable[[], None]] = []
        self._post_hooks: List[Callable[[], None]] = []
        self._servers: List[object] = []
        self.done = threading.Event()
        self._installed = False
        self._lock = threading.Lock()

    def add_server(self, server: object) -> None:
        self._servers.append(server)

    def register_pre_shutdown_hook(self, hook: Callable[[], None]) -> None:
        self._pre_hooks.append(hook)

    def register_post_shutdown_hook(self, hook: Callable[[], None]) -> None:
        self._post_hooks.append(hook)

    def install(self) -> None:
        signal.signal(signal.SIGTERM, self._on_signal)
        signal.signal(signal.SIGINT, self._on_signal)
        self._installed = True

    def _on_signal(self, signum, frame) -> None:
        log.info("received signal %s, shutting down", signum)
        threading.Thread(target=self.shutdown, daemon=True).start()

    def shutdown(self) -> None:
        with self._lock:
            if self.done.is_set():
                return
            for hook in self._pre_hooks:
                _safe(hook)
            for server in self._servers:
                stop = getattr(server, "stop", None)
                if callable(stop):
                    # servers supporting graceful drain get the window;
                    # others (e.g. the status server) stop immediately —
                    # decided by signature, not by catching TypeError (which
                    # would double-invoke stop() and mask real errors)
                    def _stop(s=stop):
                        try:
                            params = inspect.signature(s).parameters
                        except (TypeError, ValueError):
                            params = {}
                        if "drain_timeout" in params:
                            s(drain_timeout=self._drain_timeout)
                        else:
                            s()

                    _safe(_stop)
            for hook in self._post_hooks:
                _safe(hook)
            self.done.set()

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)


def _safe(fn: Callable[[], None]) -> None:
    try:
        fn()
    except Exception:  # pragma: no cover - defensive
        log.exception("shutdown hook failed")
