"""Segment / db-name helpers.

Reference: common/segment_utils.h:16-33 — ``SegmentToDbName`` ("seg" +
zero-padded 5-digit shard → ``seg00042``), ``DbNameToSegment``,
``ExtractShardId``, ``DbNameToHelixPartitionName`` (``test00100`` →
``test_100``).
"""

from __future__ import annotations

SHARD_DIGITS = 5


def segment_to_db_name(segment: str, shard_id: int) -> str:
    """``("seg", 42)`` → ``"seg00042"``."""
    if shard_id < 0 or shard_id >= 10 ** SHARD_DIGITS:
        raise ValueError(f"shard_id out of range: {shard_id}")
    return f"{segment}{shard_id:0{SHARD_DIGITS}d}"


def db_name_to_segment(db_name: str) -> str:
    """``"seg00042"`` → ``"seg"``."""
    if len(db_name) <= SHARD_DIGITS:
        raise ValueError(f"db name too short: {db_name!r}")
    return db_name[:-SHARD_DIGITS]


def extract_shard_id(db_name: str) -> int:
    """``"seg00042"`` → ``42``; returns -1 on malformed names (matches the
    reference's tolerant behavior)."""
    if len(db_name) <= SHARD_DIGITS:
        return -1
    tail = db_name[-SHARD_DIGITS:]
    if not tail.isdigit():
        return -1
    return int(tail)


def db_name_to_partition_name(db_name: str) -> str:
    """``"test00100"`` → ``"test_100"`` (Helix partition naming)."""
    seg = db_name_to_segment(db_name)
    shard = extract_shard_id(db_name)
    if shard < 0:
        raise ValueError(f"malformed db name: {db_name!r}")
    return f"{seg}_{shard}"


def partition_name_to_db_name(partition: str) -> str:
    """``"test_100"`` → ``"test00100"``."""
    seg, _, shard = partition.rpartition("_")
    return segment_to_db_name(seg, int(shard))
