"""In-process S3-compatible server for tests and local development.

Fills the reference's acknowledged S3-mock gap (SURVEY §4: "For S3 there
is no mock — only the gated integration tests"). Implements the subset
the framework uses — PUT/GET/HEAD/DELETE object, PUT with
x-amz-copy-source (copy), ListObjectsV2 with prefix + continuation — and
VERIFIES each request's SigV4 signature against the configured
credentials by recomputing it from the raw request, so the client's
signer is exercised end-to-end, not just its happy path.

Usage:
    srv = S3StubServer(access_key="test", secret_key="secret")
    srv.start()   # -> endpoint http://127.0.0.1:<port>
    ...
    srv.stop()
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .s3 import signing_key, string_to_sign

_MAX_KEYS_DEFAULT = 1000


class S3StubServer:
    def __init__(
        self,
        access_key: str = "test-access",
        secret_key: str = "test-secret",
        region: str = "us-east-1",
        max_keys: int = _MAX_KEYS_DEFAULT,
        verify_signatures: bool = True,
    ):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.max_keys = max_keys
        self.verify_signatures = verify_signatures
        # bucket -> key -> bytes
        self.data: Dict[str, Dict[str, bytes]] = {}
        self.lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> str:
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args) -> None:
                pass

            def _fail(self, status: int, code: str, msg: str) -> None:
                body = (
                    f"<?xml version=\"1.0\"?><Error><Code>{code}</Code>"
                    f"<Message>{msg}</Message></Error>"
                ).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _ok(self, body: bytes = b"",
                    content_type: str = "application/xml",
                    status: int = 200) -> None:
                self.send_response(status)
                if body or status != 204:
                    self.send_header("Content-Type", content_type)
                    self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", "0") or "0")
                return self.rfile.read(n) if n else b""

            def _parse(self) -> Tuple[str, str, List[Tuple[str, str]]]:
                """(bucket, key, query) from a path-style request path."""
                raw_path, _, raw_query = self.path.partition("?")
                parts = urllib.parse.unquote(raw_path).lstrip("/").split(
                    "/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                query = urllib.parse.parse_qsl(
                    raw_query, keep_blank_values=True)
                return bucket, key, query

            def _verify(self, body: bytes) -> Optional[str]:
                """Recompute the SigV4 signature from the raw request;
                returns an error string or None."""
                if not stub.verify_signatures:
                    return None
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256 "):
                    return "missing/invalid Authorization"
                try:
                    fields = dict(
                        kv.strip().split("=", 1)
                        for kv in auth[len("AWS4-HMAC-SHA256 "):].split(",")
                    )
                    cred = fields["Credential"].split("/")
                    akey, date, region = cred[0], cred[1], cred[2]
                    signed_headers = fields["SignedHeaders"].split(";")
                    got_sig = fields["Signature"]
                except Exception:
                    return "malformed Authorization"
                if akey != stub.access_key:
                    return "unknown access key"
                amz_date = self.headers.get("x-amz-date", "")
                payload_hash = self.headers.get("x-amz-content-sha256", "")
                if hashlib.sha256(body).hexdigest() != payload_hash:
                    return "payload hash mismatch"
                raw_path, _, raw_query = self.path.partition("?")
                # canonical query: already-encoded pairs, sorted
                pairs = []
                if raw_query:
                    for item in raw_query.split("&"):
                        k, _, v = item.partition("=")
                        pairs.append((k, v))
                cq = "&".join(f"{k}={v}" for k, v in sorted(pairs))
                ch = "".join(
                    f"{h}:{' '.join((self.headers.get(h) or '').split())}\n"
                    for h in signed_headers
                )
                creq = "\n".join([
                    self.command, raw_path, cq, ch,
                    ";".join(signed_headers), payload_hash,
                ])
                scope = f"{date}/{region}/s3/aws4_request"
                sts = string_to_sign(amz_date, scope, creq)
                want = hmac.new(
                    signing_key(stub.secret_key, date, region, "s3"),
                    sts.encode(), hashlib.sha256,
                ).hexdigest()
                if not hmac.compare_digest(want, got_sig):
                    return "signature mismatch"
                return None

            # -- verbs ----------------------------------------------------

            def do_PUT(self) -> None:  # noqa: N802
                body = self._read_body()
                err = self._verify(body)
                if err:
                    return self._fail(403, "SignatureDoesNotMatch", err)
                bucket, key, _q = self._parse()
                src = self.headers.get("x-amz-copy-source")
                with stub.lock:
                    bkt = stub.data.setdefault(bucket, {})
                    if src:
                        sparts = urllib.parse.unquote(
                            src.lstrip("/")).split("/", 1)
                        sbucket = sparts[0]
                        skey = sparts[1] if len(sparts) > 1 else ""
                        sdata = stub.data.get(sbucket, {}).get(skey)
                        if sdata is None:
                            return self._fail(
                                404, "NoSuchKey", f"copy source {src}")
                        bkt[key] = sdata
                        return self._ok(
                            b"<?xml version=\"1.0\"?><CopyObjectResult>"
                            b"<ETag>\"stub\"</ETag></CopyObjectResult>")
                    bkt[key] = body
                self._ok()

            def do_GET(self) -> None:  # noqa: N802
                err = self._verify(b"")
                if err:
                    return self._fail(403, "SignatureDoesNotMatch", err)
                bucket, key, query = self._parse()
                qd = dict(query)
                if not key and qd.get("list-type") == "2":
                    return self._list(bucket, qd)
                with stub.lock:
                    data = stub.data.get(bucket, {}).get(key)
                if data is None:
                    return self._fail(404, "NoSuchKey", key)
                self._ok(data, content_type="application/octet-stream")

            def do_HEAD(self) -> None:  # noqa: N802
                bucket, key, _q = self._parse()
                with stub.lock:
                    exists = key in stub.data.get(bucket, {})
                self.send_response(200 if exists else 404)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_DELETE(self) -> None:  # noqa: N802
                err = self._verify(b"")
                if err:
                    return self._fail(403, "SignatureDoesNotMatch", err)
                bucket, key, _q = self._parse()
                with stub.lock:
                    stub.data.get(bucket, {}).pop(key, None)
                self._ok(status=204)

            def _list(self, bucket: str, qd: Dict[str, str]) -> None:
                prefix = qd.get("prefix", "")
                token = qd.get("continuation-token", "")
                with stub.lock:
                    keys = sorted(
                        k for k in stub.data.get(bucket, {})
                        if k.startswith(prefix)
                    )
                if token:
                    keys = [k for k in keys if k > token]
                page = keys[: stub.max_keys]
                truncated = len(keys) > len(page)
                parts = [
                    "<?xml version=\"1.0\"?>",
                    "<ListBucketResult>",
                    f"<IsTruncated>{'true' if truncated else 'false'}"
                    "</IsTruncated>",
                ]
                for k in page:
                    parts.append(f"<Contents><Key>{k}</Key></Contents>")
                if truncated and page:
                    parts.append(
                        f"<NextContinuationToken>{page[-1]}"
                        "</NextContinuationToken>")
                parts.append("</ListBucketResult>")
                self._ok("".join(parts).encode())

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="s3-stub", daemon=True)
        self._thread.start()
        return self.endpoint

    @property
    def port(self) -> int:
        assert self._httpd is not None
        return self._httpd.server_address[1]

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
